"""The batched replay kernel — the north star.

Replays thousands of workflow histories as one vectorized finite-state-
machine simulation: ``lax.scan`` over the (padded) time axis, every step
applying one event row per workflow to the dense state tensors with masked
updates. Branchless by construction: the event-type × transition function
is expressed as per-type masks blended with ``jnp.where`` (all transitions
are computed for all lanes and selected — the VPU-friendly formulation),
and pending-map scatter writes use one-hot slot masks precomputed by the
packer.

Semantics are the oracle's (cadence_tpu/core/state_builder.py ==
/root/reference/service/history/stateBuilder.go:112-613 +
mutableStateBuilder Replicate* methods); differential tests assert parity.
Two deliberate deviations, both matching the reference's *rebuild* path
(nDCStateRebuilder.go:92-160):

  * timer-task dedup bits (AC_TIMER_STATUS / TI_STATUS) are not tracked
    in-scan; the reference's taskRefresher resets and regenerates them
    after a rebuild, which ops/refresh.py does vectorized.
  * per-event transfer/timer tasks are not emitted from the scan (O(B*T)
    memory); they're regenerated from final state by ops/refresh.py.

TPU notes: all state is int32 (VPU-native); the scan is memory-bound on
HBM (state read+write per step), so capacities directly set the bytes/step
— keep slot tables as small as the workload allows.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

import numpy as np

from cadence_tpu.core.enums import (
    CloseStatus, EventType as E, WorkflowState,
    WORKFLOW_CLOSE_STATUS, decision_attempt_increment,
)
from cadence_tpu.core.ids import EMPTY_EVENT_ID, EMPTY_VERSION

from . import schema as S
from .pack import PackedHistories, PackedLanes, round_scan_len


# Transition-table groups: each tuple is the event-type set gating one
# update block of replay_step. ``type_signature`` canonicalizes a
# batch's present-type set to the union of touched groups, so the
# jit specialization key is "which blocks run", not the raw type list —
# a bounded, storm-stable set of executables.
_TYPE_GROUPS = None  # populated lazily (E enum below)


def _type_groups():
    global _TYPE_GROUPS
    if _TYPE_GROUPS is None:
        _TYPE_GROUPS = (
            (E.WorkflowExecutionStarted,),
            (E.WorkflowExecutionCompleted, E.WorkflowExecutionFailed,
             E.WorkflowExecutionTimedOut, E.WorkflowExecutionCanceled,
             E.WorkflowExecutionTerminated,
             E.WorkflowExecutionContinuedAsNew),
            (E.WorkflowExecutionCancelRequested,),
            (E.WorkflowExecutionSignaled,),
            (E.DecisionTaskScheduled,),
            (E.DecisionTaskStarted,),
            (E.DecisionTaskCompleted,),
            (E.DecisionTaskTimedOut, E.DecisionTaskFailed),
            (E.ActivityTaskScheduled,),
            (E.ActivityTaskStarted,),
            (E.ActivityTaskCompleted, E.ActivityTaskFailed,
             E.ActivityTaskTimedOut, E.ActivityTaskCanceled),
            (E.ActivityTaskCancelRequested,),
            (E.TimerStarted,),
            (E.TimerFired, E.TimerCanceled),
            (E.StartChildWorkflowExecutionInitiated,),
            (E.ChildWorkflowExecutionStarted,),
            (E.StartChildWorkflowExecutionFailed,
             E.ChildWorkflowExecutionCompleted,
             E.ChildWorkflowExecutionFailed,
             E.ChildWorkflowExecutionCanceled,
             E.ChildWorkflowExecutionTimedOut,
             E.ChildWorkflowExecutionTerminated),
            (E.RequestCancelExternalWorkflowExecutionInitiated,),
            (E.RequestCancelExternalWorkflowExecutionFailed,
             E.ExternalWorkflowExecutionCancelRequested),
            (E.SignalExternalWorkflowExecutionInitiated,),
            (E.SignalExternalWorkflowExecutionFailed,
             E.ExternalWorkflowExecutionSignaled),
        )
    return _TYPE_GROUPS


def check_scan_mode(scan_mode: str, allowed=("auto", "scan", "assoc")):
    """Reject unknown ``scan_mode`` strings up front: the kernel
    selectors otherwise each read the string differently, so a typo
    ("asoc", "Scan") would silently pick a kernel instead of erroring."""
    if scan_mode not in allowed:
        raise ValueError(
            f"scan_mode must be one of {'/'.join(allowed)} "
            f"(got {scan_mode!r})"
        )


def type_signature(present) -> tuple:
    """Canonical static type set for ``replay_step(types=...)``.

    Expands the batch's present event types to whole transition groups
    (a group either runs or is statically skipped), returned as a sorted
    tuple usable as a jit static argument. Skipped groups cost nothing
    at trace or run time; retained groups still test exact types at
    runtime, so the result is bit-identical to the unspecialized step.
    """
    ps = {int(t) for t in present}
    out = set()
    for g in _type_groups():
        if any(int(t) in ps for t in g):
            out.update(int(t) for t in g)
    return tuple(sorted(out))


# --------------------------------------------------------------------------
# Column-major carry layout.
#
# The scan carries state as flat per-column vectors ([B] exec columns,
# [B, N] slot-table columns) instead of the packed [B, X_N] / [B, N, C]
# tensors: a masked update then touches one small vector, where the
# packed layout's ``.at[:, col].set`` forces XLA:CPU to rewrite the whole
# tensor per update (~6x measured on the exec table at B=512 — the step
# body is the throughput bound for shallow workloads). Conversion happens
# once per scan at the boundaries; element values and update order are
# identical, so results are bit-identical to the packed formulation.
# --------------------------------------------------------------------------


def state_to_cols(state: S.StateTensors):
    """StateTensors → flat column pytree (the scan-carry layout)."""
    ex = state.exec_info
    return (
        tuple(ex[:, c] for c in range(ex.shape[1])),
        state.vh_items[:, :, 0],
        state.vh_items[:, :, 1],
        state.vh_len,
        tuple(state.activities[:, :, c] for c in range(S.AC_N)),
        tuple(state.timers[:, :, c] for c in range(S.TI_N)),
        tuple(state.children[:, :, c] for c in range(S.CH_N)),
        tuple(state.cancels[:, :, c] for c in range(S.RC_N)),
        tuple(state.signals[:, :, c] for c in range(S.SG_N)),
    )


def cols_to_state(cols) -> S.StateTensors:
    exc, vh_e, vh_v, vh_len, ac, ti, ch, rc, sg = cols
    return S.StateTensors(
        exec_info=jnp.stack(exc, axis=1),
        activities=jnp.stack(ac, axis=-1),
        timers=jnp.stack(ti, axis=-1),
        children=jnp.stack(ch, axis=-1),
        cancels=jnp.stack(rc, axis=-1),
        signals=jnp.stack(sg, axis=-1),
        vh_items=jnp.stack([vh_e, vh_v], axis=-1),
        vh_len=vh_len,
    )


def _tbl_set(tbl, onehot, col, val):
    """tbl[col][B, N] ← val[B] (broadcast over slots) where onehot."""
    if onehot is not None:
        tbl[col] = jnp.where(onehot, val[:, None], tbl[col])


def _tbl_blend(tbl, onehot, row_vals):
    """Whole-row write: tbl[c] ← row_vals[c] where onehot[B, N].
    row_vals entries are [B] vectors or scalars."""
    if onehot is None:
        return
    for c, v in enumerate(row_vals):
        vv = v[:, None] if getattr(v, "ndim", 0) == 1 else v
        tbl[c] = jnp.where(onehot, vv, tbl[c])


def _tbl_clear(tbl, onehot):
    if onehot is not None:
        for c in range(len(tbl)):
            tbl[c] = jnp.where(onehot, 0, tbl[c])


def replay_step_cols(cols, ev: jnp.ndarray, types: Optional[tuple] = None):
    """Apply one event row per workflow to the column-layout carry.

    ev: [B, EV_N] int32. ``types``: static sorted tuple of event types
    present in the batch (``type_signature``); transition blocks whose
    types are statically absent are skipped entirely — a shallow storm
    touches a fraction of the transition table. ``None`` keeps every
    block."""
    et = ev[:, S.EV_TYPE]
    valid = et >= 0
    type_set = None if types is None else frozenset(types)

    def m(*query):
        if type_set is not None:
            query = [t for t in query if int(t) in type_set]
            if not query:
                return None
        out = jnp.zeros_like(valid)
        for t in query:
            out = out | (et == int(t))
        return valid & out

    def slot_mask(mask, capacity):
        """[B, capacity] one-hot of EV_SLOT under ``mask``."""
        if mask is None:
            return None
        slot = ev[:, S.EV_SLOT]
        return mask[:, None] & (
            slot[:, None] == jnp.arange(capacity)[None, :]
        )

    ev_id = ev[:, S.EV_ID]
    version = ev[:, S.EV_VERSION]
    task_id = ev[:, S.EV_TASK_ID]
    ts = ev[:, S.EV_TS]
    batch_first = ev[:, S.EV_BATCH_FIRST]
    a0, a1, a2, a3 = (ev[:, S.EV_A0], ev[:, S.EV_A1], ev[:, S.EV_A2], ev[:, S.EV_A3])
    a4, a5, a6, a7 = (ev[:, S.EV_A4], ev[:, S.EV_A5], ev[:, S.EV_A6], ev[:, S.EV_A7])

    exc, vh_e, vh_v, vh_len, ac, ti, ch, rc, sg = cols
    exc = list(exc)
    ac, ti, ch = list(ac), list(ti), list(ch)
    rc, sg = list(rc), list(sg)

    def xset(col, mask, val):
        """exec column masked update (no-op on statically absent mask)."""
        if mask is not None:
            exc[col] = jnp.where(mask, val, exc[col])

    # ---- common preamble (stateBuilder.go:134-155 + batch-end bookkeeping)
    xset(S.X_LAST_EVENT_TASK_ID, valid, task_id)
    xset(S.X_CUR_VERSION, valid, version)
    xset(S.X_NEXT_EVENT_ID, valid, ev_id + 1)
    xset(S.X_LAST_FIRST_EVENT_ID, valid, batch_first)

    # ---- version-history add_or_update (versionHistory.go AddOrUpdateItem)
    cap_v = vh_v.shape[1]
    last_idx = jnp.maximum(vh_len - 1, 0)
    # read the last *materialized* slot: past capacity, last_idx exceeds
    # the table and an unclamped gather's out-of-bounds semantics are
    # backend-defined; the clamped read keeps overflowed states (chained
    # bench iterations, not real histories) deterministic and identical
    # across the scan / Pallas / assoc kernels. write_idx keeps the raw
    # last_idx so same-version writes past capacity still match no slot.
    last_ver = jnp.take_along_axis(
        vh_v, jnp.minimum(last_idx, cap_v - 1)[:, None], axis=1)[:, 0]
    same = (vh_len > 0) & (last_ver == version)
    write_idx = jnp.where(same, last_idx, jnp.minimum(vh_len, cap_v - 1))
    wmask = valid[:, None] & (write_idx[:, None] == jnp.arange(cap_v)[None, :])
    vh_e = jnp.where(wmask, ev_id[:, None], vh_e)
    vh_v = jnp.where(wmask, version[:, None], vh_v)
    vh_len = jnp.where(valid & ~same, vh_len + 1, vh_len)

    # ---- workflow lifecycle ------------------------------------------------
    m_start = m(E.WorkflowExecutionStarted)
    xset(S.X_STATE, m_start, int(WorkflowState.Created))
    xset(S.X_CLOSE_STATUS, m_start, int(CloseStatus.NONE))
    xset(S.X_LAST_PROCESSED_EVENT, m_start, EMPTY_EVENT_ID)
    xset(S.X_START_TS, m_start, ts)
    xset(S.X_WORKFLOW_TIMEOUT, m_start, a0)
    xset(S.X_DECISION_TIMEOUT_VALUE, m_start, a1)
    xset(S.X_ATTEMPT, m_start, a2)
    xset(S.X_HAS_RETRY_POLICY, m_start, a3)
    xset(S.X_WF_EXPIRATION_TS, m_start, a4)
    xset(S.X_PARENT_INITIATED_ID, m_start, a7)
    for col in (S.X_DEC_SCHEDULE_ID, S.X_DEC_STARTED_ID):
        xset(col, m_start, EMPTY_EVENT_ID)
    xset(S.X_DEC_VERSION, m_start, EMPTY_VERSION)
    for col in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT, S.X_DEC_SCHEDULED_TS,
                S.X_DEC_STARTED_TS, S.X_DEC_ORIGINAL_SCHEDULED_TS):
        xset(col, m_start, 0)

    close_terms = []
    for t, cs in WORKFLOW_CLOSE_STATUS:
        mk = m(t)
        if mk is not None:
            close_terms.append((mk, int(cs)))
    if close_terms:
        close_status = sum(mk * cs for mk, cs in close_terms)
        m_close = close_status > 0
        xset(S.X_STATE, m_close, int(WorkflowState.Completed))
        xset(S.X_CLOSE_STATUS, m_close, close_status)
        xset(S.X_COMPLETION_EVENT_BATCH_ID, m_close, batch_first)

    xset(S.X_CANCEL_REQUESTED, m(E.WorkflowExecutionCancelRequested), 1)
    m_sig = m(E.WorkflowExecutionSignaled)
    if m_sig is not None:
        xset(S.X_SIGNAL_COUNT, m_sig, exc[S.X_SIGNAL_COUNT] + 1)

    # ---- decision sub-FSM (mutableStateDecisionTaskManager.go) -------------
    m_dsch = m(E.DecisionTaskScheduled)
    xset(S.X_DEC_VERSION, m_dsch, version)
    xset(S.X_DEC_SCHEDULE_ID, m_dsch, ev_id)
    xset(S.X_DEC_STARTED_ID, m_dsch, EMPTY_EVENT_ID)
    xset(S.X_DEC_TIMEOUT, m_dsch, a0)
    xset(S.X_DEC_ATTEMPT, m_dsch, a1)
    xset(S.X_DEC_SCHEDULED_TS, m_dsch, ts)
    xset(S.X_DEC_ORIGINAL_SCHEDULED_TS, m_dsch, ts)
    xset(S.X_DEC_STARTED_TS, m_dsch, 0)

    m_dsta = m(E.DecisionTaskStarted)
    if m_dsta is not None:
        # Created → Running on first decision start (:228-235)
        xset(
            S.X_STATE,
            m_dsta & (exc[S.X_STATE] == int(WorkflowState.Created)),
            int(WorkflowState.Running),
        )
        xset(S.X_DEC_VERSION, m_dsta, version)
        xset(S.X_DEC_STARTED_ID, m_dsta, ev_id)
        xset(S.X_DEC_ATTEMPT, m_dsta, 0)  # replication magic (:216-224)
        xset(S.X_DEC_STARTED_TS, m_dsta, ts)

    m_dcom = m(E.DecisionTaskCompleted)
    # delete decision, keep original-scheduled ts (:659-674)
    xset(S.X_DEC_VERSION, m_dcom, EMPTY_VERSION)
    xset(S.X_DEC_SCHEDULE_ID, m_dcom, EMPTY_EVENT_ID)
    xset(S.X_DEC_STARTED_ID, m_dcom, EMPTY_EVENT_ID)
    for col in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT, S.X_DEC_SCHEDULED_TS,
                S.X_DEC_STARTED_TS):
        xset(col, m_dcom, 0)
    xset(S.X_LAST_PROCESSED_EVENT, m_dcom, a0)

    # fail/timeout → fail_decision(+transient schedule) fused:
    m_dto = m(E.DecisionTaskTimedOut)
    m_dfail = m(E.DecisionTaskFailed)
    if m_dto is not None or m_dfail is not None:
        fill = jnp.zeros_like(valid)
        dto = fill if m_dto is None else m_dto
        dfail = fill if m_dfail is None else m_dfail
        increment = decision_attempt_increment(dfail, dto, a0)
        no_increment = (dto | dfail) & ~increment
        # transient decision fires iff attempt was incremented (oracle:
        # replicate_transient_decision_task_scheduled precondition
        # collapses to `increment` right after fail_decision)
        new_attempt = exc[S.X_DEC_ATTEMPT] + 1
        xset(S.X_DEC_VERSION, increment, exc[S.X_CUR_VERSION])
        xset(S.X_DEC_SCHEDULE_ID, increment, batch_first)
        xset(S.X_DEC_STARTED_ID, increment, EMPTY_EVENT_ID)
        xset(S.X_DEC_TIMEOUT, increment, exc[S.X_DECISION_TIMEOUT_VALUE])
        xset(S.X_DEC_ATTEMPT, increment, new_attempt)
        xset(S.X_DEC_SCHEDULED_TS, increment, ts)
        xset(S.X_DEC_STARTED_TS, increment, 0)
        xset(S.X_DEC_ORIGINAL_SCHEDULED_TS, increment, 0)

        xset(S.X_DEC_VERSION, no_increment, EMPTY_VERSION)
        xset(S.X_DEC_SCHEDULE_ID, no_increment, EMPTY_EVENT_ID)
        xset(S.X_DEC_STARTED_ID, no_increment, EMPTY_EVENT_ID)
        for col in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT, S.X_DEC_SCHEDULED_TS,
                    S.X_DEC_STARTED_TS, S.X_DEC_ORIGINAL_SCHEDULED_TS):
            xset(col, no_increment, 0)

    # ---- pending activities ------------------------------------------------
    cap_a = ac[0].shape[1]

    oh_sched = slot_mask(m(E.ActivityTaskScheduled), cap_a)
    if oh_sched is not None:
        # expiration: scheduled + max(schedule_to_close, retry expiration
        # if larger) — mutableStateBuilder.go:2012-2022
        exp_interval = jnp.where((a5 > 0) & (a6 > a2), a6, a2)
        _tbl_blend(ac, oh_sched, [
            1,                      # AC_OCC
            version,                # AC_VERSION
            ev_id,                  # AC_SCHEDULE_ID
            batch_first,            # AC_SCHEDULED_BATCH_ID
            ts,                     # AC_SCHEDULED_TS
            EMPTY_EVENT_ID,         # AC_STARTED_ID
            0,                      # AC_STARTED_TS
            a0,                     # AC_ID_HASH
            a1,                     # AC_SCH_TO_START
            a2,                     # AC_SCH_TO_CLOSE
            a3,                     # AC_START_TO_CLOSE
            a4,                     # AC_HEARTBEAT
            0,                      # AC_CANCEL_REQUESTED
            EMPTY_EVENT_ID,         # AC_CANCEL_REQUEST_ID
            0,                      # AC_ATTEMPT
            a5,                     # AC_HAS_RETRY
            ts + exp_interval,      # AC_EXPIRATION_TS
            0,                      # AC_LAST_HB_TS
            0,                      # AC_TIMER_STATUS
        ])

    oh_start = slot_mask(m(E.ActivityTaskStarted), cap_a)
    _tbl_set(ac, oh_start, S.AC_VERSION, version)
    _tbl_set(ac, oh_start, S.AC_STARTED_ID, ev_id)
    _tbl_set(ac, oh_start, S.AC_STARTED_TS, ts)
    _tbl_set(ac, oh_start, S.AC_LAST_HB_TS, ts)
    _tbl_set(ac, oh_start, S.AC_ATTEMPT, a1)

    _tbl_clear(ac, slot_mask(
        m(E.ActivityTaskCompleted, E.ActivityTaskFailed,
          E.ActivityTaskTimedOut, E.ActivityTaskCanceled),
        cap_a,
    ))

    oh_acreq = slot_mask(m(E.ActivityTaskCancelRequested), cap_a)
    _tbl_set(ac, oh_acreq, S.AC_VERSION, version)
    _tbl_set(ac, oh_acreq, S.AC_CANCEL_REQUESTED, jnp.ones_like(ev_id))
    _tbl_set(ac, oh_acreq, S.AC_CANCEL_REQUEST_ID, ev_id)

    # ---- pending timers ----------------------------------------------------
    cap_t = ti[0].shape[1]
    oh_tstart = slot_mask(m(E.TimerStarted), cap_t)
    _tbl_blend(ti, oh_tstart, [
        1,          # TI_OCC
        version,    # TI_VERSION
        ev_id,      # TI_STARTED_ID
        a0,         # TI_ID_HASH
        ts + a1,    # TI_EXPIRY_TS
        0,          # TI_STATUS
    ] if oh_tstart is not None else [])
    _tbl_clear(ti, slot_mask(m(E.TimerFired, E.TimerCanceled), cap_t))

    # ---- pending children --------------------------------------------------
    cap_c = ch[0].shape[1]
    oh_cinit = slot_mask(m(E.StartChildWorkflowExecutionInitiated), cap_c)
    _tbl_blend(ch, oh_cinit, [
        1,                  # CH_OCC
        version,            # CH_VERSION
        ev_id,              # CH_INITIATED_ID
        batch_first,        # CH_INITIATED_BATCH_ID
        EMPTY_EVENT_ID,     # CH_STARTED_ID
        a0,                 # CH_WF_ID_HASH
        0,                  # CH_RUN_ID_HASH
        a1,                 # CH_POLICY
    ] if oh_cinit is not None else [])

    oh_cstart = slot_mask(m(E.ChildWorkflowExecutionStarted), cap_c)
    _tbl_set(ch, oh_cstart, S.CH_STARTED_ID, ev_id)
    _tbl_set(ch, oh_cstart, S.CH_RUN_ID_HASH, a1)

    _tbl_clear(ch, slot_mask(
        m(E.StartChildWorkflowExecutionFailed,
          E.ChildWorkflowExecutionCompleted, E.ChildWorkflowExecutionFailed,
          E.ChildWorkflowExecutionCanceled, E.ChildWorkflowExecutionTimedOut,
          E.ChildWorkflowExecutionTerminated),
        cap_c,
    ))

    # ---- pending external cancels / signals --------------------------------
    cap_rc = rc[0].shape[1]
    oh_rcinit = slot_mask(
        m(E.RequestCancelExternalWorkflowExecutionInitiated), cap_rc
    )
    _tbl_blend(rc, oh_rcinit,
               [1, version, ev_id, batch_first]
               if oh_rcinit is not None else [])
    _tbl_clear(rc, slot_mask(
        m(E.RequestCancelExternalWorkflowExecutionFailed,
          E.ExternalWorkflowExecutionCancelRequested),
        cap_rc,
    ))

    cap_sg = sg[0].shape[1]
    oh_sginit = slot_mask(
        m(E.SignalExternalWorkflowExecutionInitiated), cap_sg
    )
    _tbl_blend(sg, oh_sginit,
               [1, version, ev_id, batch_first]
               if oh_sginit is not None else [])
    _tbl_clear(sg, slot_mask(
        m(E.SignalExternalWorkflowExecutionFailed,
          E.ExternalWorkflowExecutionSignaled),
        cap_sg,
    ))

    return (
        tuple(exc), vh_e, vh_v, vh_len,
        tuple(ac), tuple(ti), tuple(ch), tuple(rc), tuple(sg),
    )


def replay_step(
    state: S.StateTensors, ev: jnp.ndarray, types: Optional[tuple] = None,
) -> S.StateTensors:
    """Apply one event row per workflow. ev: [B, EV_N] int32.

    Single-step convenience wrapper over ``replay_step_cols`` (which the
    scans use directly so the column conversion happens once per scan,
    not once per step)."""
    return cols_to_state(replay_step_cols(state_to_cols(state), ev, types))


def replay_scan(
    state: S.StateTensors, events_tm: jnp.ndarray,
    unroll: Optional[int] = None,
    types: Optional[tuple] = None,
) -> S.StateTensors:
    """Scan the full (time-major [T, B, EV_N]) event tensor.

    ``unroll``: steps fused per scan iteration — the scan is HBM-bound
    on the state carry, and unrolling lets XLA keep intermediates on
    chip across fused steps (~10-15% on v5e at unroll=8; measured in
    bench.py's configuration). Defaults to 8 on TPU and 1 elsewhere:
    unrolling only pays on the device, while on CPU (the test suite) it
    multiplies XLA compile time by the unroll factor.

    ``types``: static present-type tuple (``type_signature``) —
    statically skips transition blocks the batch cannot touch."""
    if unroll is None:
        unroll = 8 if jax.default_backend() == "tpu" else 1
    final, _ = lax.scan(
        lambda s, ev: (replay_step_cols(s, ev, types=types), None),
        state_to_cols(state), events_tm, unroll=unroll,
    )
    return cols_to_state(final)


replay_scan_jit = jax.jit(
    replay_scan, donate_argnums=(0,), static_argnames=("unroll", "types"),
)


def _lane_mask(flag, leaf):
    return flag.reshape(flag.shape + (1,) * (leaf.ndim - 1))


def cols_to_mat(cols) -> jnp.ndarray:
    """Column carry → one [B, R] int32 matrix (R = total state columns).

    The packed scan's snapshot flush scatters this single buffer instead
    of ~60 column leaves: one dynamic-update-scatter per flush step, one
    extra carry array — the per-leaf formulation pays per-op dispatch on
    every leaf every flush, which dominates on CPU."""
    exc, vh_e, vh_v, vh_len, ac, ti, ch, rc, sg = cols
    parts = [jnp.stack(exc, axis=1), vh_e, vh_v, vh_len[:, None]]
    for tbl in (ac, ti, ch, rc, sg):
        parts.extend(tbl)
    return jnp.concatenate(parts, axis=1)


def mat_to_state(mat, caps: S.Capacities) -> S.StateTensors:
    """Inverse of ``cols_to_mat`` (rows → StateTensors)."""
    o = 0

    def take(n):
        nonlocal o
        sl = mat[:, o : o + n]
        o += n
        return sl

    ex = take(S.X_N)
    v = caps.max_version_items
    vh_e, vh_v = take(v), take(v)
    vh_len = take(1)[:, 0]

    def tbl(ncols, cap):
        return jnp.stack([take(cap) for _ in range(ncols)], axis=-1)

    return S.StateTensors(
        exec_info=ex,
        vh_items=jnp.stack([vh_e, vh_v], axis=-1),
        vh_len=vh_len,
        activities=tbl(S.AC_N, caps.max_activities),
        timers=tbl(S.TI_N, caps.max_timers),
        children=tbl(S.CH_N, caps.max_children),
        cancels=tbl(S.RC_N, caps.max_request_cancels),
        signals=tbl(S.SG_N, caps.max_signals_ext),
    )


def _caps_of(state: S.StateTensors) -> S.Capacities:
    return S.Capacities(
        max_events=0,
        max_activities=state.activities.shape[1],
        max_timers=state.timers.shape[1],
        max_children=state.children.shape[1],
        max_request_cancels=state.cancels.shape[1],
        max_signals_ext=state.signals.shape[1],
        max_version_items=state.vh_items.shape[1],
    )


def replay_scan_packed(
    state: S.StateTensors,
    out0: S.StateTensors,
    events_tm: jnp.ndarray,
    seg_end_tm: jnp.ndarray,
    out_row_tm: jnp.ndarray,
    unroll: Optional[int] = None,
    types: Optional[tuple] = None,
    init: Optional[S.StateTensors] = None,
    reset_row_tm: Optional[jnp.ndarray] = None,
):
    """Scan a lane-packed event tensor (ops/pack.py pack_lanes).

    ``state``: [L] lane carry — ``empty_state(L)``, or each lane's FIRST
    segment's initial row (``PackedLanes.lane_state0()``) when resuming
    from checkpoints. ``out0``: [n_out] output snapshot buffer, MUST be
    ``empty_state(n_out)`` — rows never written (padding) stay pristine
    and lane resets reuse its row 0 as the empty template.
    ``events_tm``/``seg_end_tm``/``out_row_tm``: [T, L(, EV_N)] from
    ``PackedLanes.time_major()``.

    At a segment-end step each flagged lane scatters its full state into
    its precomputed output row and resets to the NEXT segment's initial
    carry — ``empty_state`` normally, or its row of ``init`` when that
    segment resumes from a checkpoint (``reset_row_tm``: [T, L] indices
    into ``init``; the sentinel ``init.batch`` selects the appended
    pristine empty row). So each history's snapshot is bit-identical to
    replaying it alone from its initial state. Steps with no segment end
    skip the flush entirely (lax.cond).

    Returns (final_lane_state, out) — callers read ``out``.
    """
    if unroll is None:
        unroll = 8 if jax.default_backend() == "tpu" else 1
    caps = _caps_of(out0)
    n_out = out0.exec_info.shape[0]
    out_cols0 = state_to_cols(out0)
    empty_row = jax.tree_util.tree_map(lambda x: x[:1], out_cols0)
    if init is None:
        # single empty template row; every reset gathers row 0
        init_cols = empty_row
        reset_row_tm = jnp.zeros(seg_end_tm.shape, jnp.int32)
    else:
        if reset_row_tm is None:
            raise ValueError("init requires reset_row_tm")
        init_cols = jax.tree_util.tree_map(
            lambda a, e: jnp.concatenate([a, e], axis=0),
            state_to_cols(init), empty_row,
        )
    # one sentinel row past the end absorbs non-flush lanes' writes
    out_mat0 = jnp.concatenate(
        [cols_to_mat(out_cols0),
         jnp.zeros((1, cols_to_mat(out_cols0).shape[1]), jnp.int32)],
        axis=0,
    )
    # hoisted out of the scan: the per-step flush gate and scatter index
    # as vectorized [T]-shaped precomputes (a per-step jnp.any reduction
    # inside the loop measurably dominates the flush cost on CPU)
    idx_tm = jnp.where(seg_end_tm, out_row_tm, n_out).astype(jnp.int32)
    any_tm = jnp.any(seg_end_tm, axis=1)

    def body(carry, xs):
        st, out = carry
        ev, seg, idx, flush_now, rrow = xs
        st = replay_step_cols(st, ev, types=types)

        def flush(args):
            st, out = args
            # idx is host-derived, always within [0, n_out] (sentinel)
            out = out.at[idx].set(
                cols_to_mat(st), mode="promise_in_bounds"
            )
            st = jax.tree_util.tree_map(
                lambda s, ini: jnp.where(
                    _lane_mask(seg, s), ini[rrow], s
                ),
                st, init_cols,
            )
            return st, out

        st, out = lax.cond(flush_now, flush, lambda args: args, (st, out))
        return (st, out), None

    (st, out), _ = lax.scan(
        body, (state_to_cols(state), out_mat0),
        (events_tm, seg_end_tm, idx_tm, any_tm, reset_row_tm),
        unroll=unroll,
    )
    return cols_to_state(st), mat_to_state(out[:n_out], caps)


replay_scan_packed_jit = jax.jit(
    replay_scan_packed, donate_argnums=(0, 1),
    static_argnames=("unroll", "types"),
)


def replay_packed_lanes(
    packed: PackedLanes, specialize: bool = True,
    initial: Optional[S.StateTensors] = None,
    scan_mode: str = "auto",
) -> S.StateTensors:
    """Replay a lane-packed batch; returns numpy state with one row per
    history, in input order (``packed.side`` indexes it directly).

    ``initial``: [n_histories] per-history initial carries (checkpoint
    resume) — defaults to ``packed.initial`` (set by
    ``pack_lanes(resume=...)``); each history's segment then seeds from
    its row instead of ``empty_state``, bit-identically to replaying
    the full history from scratch.

    ``scan_mode``: ``"scan"`` = the sequential O(T)-depth kernels;
    ``"assoc"`` = the parallel-in-time associative path (ops/assoc.py,
    segment resets ride the packer's segment table); ``"auto"`` picks
    assoc off-TPU when every present type is provably affine — the
    sequential scan otherwise. The lane-packed assoc path has no
    per-event hybrid chunker, so a batch with a non-affine type falls
    back to the sequential packed scan under BOTH ``"auto"`` and a
    forced ``"assoc"``. On TPU every ``scan_mode`` rides the serving
    kernels below (the Pallas/TPU assoc path is still an open item —
    see ROADMAP).

    On TPU, lanes packed with ``seg_align`` a multiple of the Pallas
    time block ride the chunked VMEM-resident kernel
    (ops/replay_pallas.py replay_scan_pallas_packed); everywhere else —
    and for unaligned packings — the XLA scan handles arbitrary segment
    boundaries."""
    check_scan_mode(scan_mode)
    caps = packed.caps
    if scan_mode != "scan" and jax.default_backend() != "tpu":
        from .assoc import classify_types, replay_assoc_lanes

        _, non = classify_types(packed.present_types)
        if not non:
            # unspecialized on this facade: one compile per SHAPE. The
            # per-type-set specialization only pays when a storm reuses
            # one signature (the dispatcher grows a monotone set for
            # exactly that); here it would recompile per batch.
            return replay_assoc_lanes(
                packed, initial=initial, specialize=False)
    if initial is None:
        initial = packed.initial
    n_pad = round_scan_len(packed.n_histories)
    out0 = jax.tree_util.tree_map(
        jnp.asarray, S.empty_state(n_pad, caps)
    )
    if initial is None:
        state0 = jax.tree_util.tree_map(
            jnp.asarray, S.empty_state(packed.lanes, caps)
        )
        init_j = None
        reset = None
    else:
        state0 = jax.tree_util.tree_map(
            jnp.asarray, packed.lane_state0(initial)
        )
        init_j = jax.tree_util.tree_map(jnp.asarray, initial)
        reset = packed.reset_rows()
    types = type_signature(packed.present_types) if specialize else None
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and packed.seg_align % 8 == 0:
        from .replay_pallas import replay_scan_pallas_packed

        _, out = replay_scan_pallas_packed(
            state0, out0, jnp.asarray(packed.teb()),
            jnp.asarray(packed.seg_end), jnp.asarray(packed.out_row),
            caps, tb=packed.seg_align,
            init=init_j,
            reset_row=None if reset is None else jnp.asarray(reset),
        )
    else:
        ev_tm, seg_tm, row_tm = packed.time_major()
        kwargs = {}
        if init_j is not None:
            kwargs = dict(
                init=init_j,
                reset_row_tm=jnp.asarray(
                    np.ascontiguousarray(reset.T)
                ),
            )
        _, out = replay_scan_packed_jit(
            state0, out0, jnp.asarray(ev_tm), jnp.asarray(seg_tm),
            jnp.asarray(row_tm), types=types, **kwargs,
        )
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x)[: packed.n_histories], out
    )


def replay_packed(
    packed,
    initial: Optional[S.StateTensors] = None,
    scan_mode: str = "auto",
) -> S.StateTensors:
    """Replay a packed batch on the default device; returns numpy state.

    Accepts :class:`PackedHistories` (one history per lane) or
    :class:`PackedLanes` (ragged lane packing; rows come back per
    history). On TPU the PackedHistories path rides the Pallas
    VMEM-resident kernel through the packer's field-major layout + host
    presence masks (the serving-path configuration bench.py measures);
    elsewhere the default (``scan_mode="auto"``) is the parallel-in-time
    associative path (ops/assoc.py) whenever every present event type is
    provably affine, falling back to the sequential XLA scan otherwise —
    all paths are bit-identical (tests/test_fuzz_differential.py).
    ``scan_mode="scan"`` forces the sequential kernels;
    ``scan_mode="assoc"`` forces the associative one (hybrid-chunking
    around any nonaffine steps) — off TPU only: on a TPU backend every
    mode rides the Pallas/sequential serving path, the TPU assoc
    benchmark being an open ROADMAP item. The XLA batch dimension is padded to
    the geometric shape grid (``round_scan_len``) so a storm of
    arbitrary batch sizes compiles a bounded set of executables."""
    check_scan_mode(scan_mode)
    if isinstance(packed, PackedLanes):
        # initial: [n_histories] per-history resume carries (checkpoint
        # rows); defaults to packed.initial from pack_lanes(resume=...)
        return replay_packed_lanes(
            packed, initial=initial, scan_mode=scan_mode)
    if initial is None:
        initial = packed.initial
    state = initial if initial is not None else S.empty_state(packed.batch, packed.caps)
    state = jax.tree_util.tree_map(jnp.asarray, state)
    if packed.batch == 0:
        return jax.tree_util.tree_map(np.asarray, state)
    if scan_mode != "scan" and jax.default_backend() != "tpu":
        from .assoc import (
            classify_types, events_fm_of, replay_assoc, replay_assoc_fm,
        )

        present = [
            int(t)
            for t in np.unique(packed.events[:, :, S.EV_TYPE])
            if t >= 0
        ]
        _, non = classify_types(present)
        if scan_mode == "assoc" or not non:
            b = packed.batch
            bp = round_scan_len(b)
            evf = events_fm_of(packed.events)
            if bp > b:
                pad = np.zeros((S.EV_N, bp - b, evf.shape[2]), np.int32)
                pad[S.EV_TYPE] = -1
                evf = np.concatenate([evf, pad], axis=1)
                state = jax.tree_util.tree_map(
                    lambda x, p: jnp.concatenate(
                        [x, jnp.asarray(p)], axis=0
                    ),
                    state,
                    S.empty_state(bp - b, packed.caps),
                )
            if non:
                # hybrid: sequential steps only at nonaffine events
                final = replay_assoc(state, events_fm=evf)
            else:
                # unspecialized: one compile per shape (see the lanes
                # branch above)
                final = replay_assoc_fm(state, evf)
            if bp > b:
                final = jax.tree_util.tree_map(lambda x: x[:b], final)
            return jax.tree_util.tree_map(np.asarray, final)
    if jax.default_backend() == "tpu":
        from .replay_pallas import BT, replay_scan_pallas_teb

        # smallest whole tile covering the batch (small rebuild batches
        # shouldn't pad to the full throughput tile)
        bt = min(BT, ((packed.batch + 1023) // 1024) * 1024)
        final = replay_scan_pallas_teb(
            state, jnp.asarray(packed.teb()), packed.caps,
            interpret=False, bt=bt, presence=packed.presence(bt),
        )
    else:
        b = packed.batch
        bp = round_scan_len(b)
        events_tm = packed.time_major()
        if bp > b:
            pad = np.zeros(
                (events_tm.shape[0], bp - b, S.EV_N), dtype=np.int32
            )
            pad[:, :, S.EV_TYPE] = -1
            events_tm = np.concatenate([events_tm, pad], axis=1)
            state = jax.tree_util.tree_map(
                lambda x, p: jnp.concatenate(
                    [x, jnp.asarray(p)], axis=0
                ),
                state,
                S.empty_state(bp - b, packed.caps),
            )
        final = replay_scan_jit(state, jnp.asarray(events_tm))
        if bp > b:
            final = jax.tree_util.tree_map(lambda x: x[:b], final)
    return jax.tree_util.tree_map(np.asarray, final)


# Parallel-in-time entry points (ops/assoc.py): replay_assoc is the
# chunked hybrid over an unpacked time-major tensor — associative
# composition over affine runs, short sequential scans at any step the
# classifier cannot prove affine. Re-exported here because replay.py is
# the kernel facade the dispatcher and rebuild paths import from.
from .assoc import replay_assoc  # noqa: E402,F401
from .assoc import classify_types as assoc_classify_types  # noqa: E402,F401
