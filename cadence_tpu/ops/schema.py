"""Dense tensor layout for batched workflow-history replay.

The on-device ABI: every workflow's mutable state is a fixed set of int32
tensors, every history event is one int32 row. Strings (activity IDs, timer
IDs, task lists, payloads) never influence transitions — the packer
(ops/pack.py) hashes the keyed ones to int31 and keeps originals in host
side tables; slot indices for pending-map entries are precomputed host-side
so the kernel does pure dense masked updates (no on-device hash lookups).

This encodes the reference's WorkflowExecutionInfo
(/root/reference/common/persistence/dataInterfaces.go:259-316) + pending
maps (ActivityInfo :625, TimerInfo :665, ChildExecutionInfo :674,
RequestCancelInfo, SignalInfo) + version histories
(/root/reference/common/persistence/versionHistory.go) as tensors.

Timestamps on device are int32 **seconds** (host precision is ns); Cadence
timeouts are second-granular so nothing is lost on the transition surface.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List

import numpy as np

# --------------------------------------------------------------------------
# Event row columns: events[B, T, EV_N]
# --------------------------------------------------------------------------
EV_TYPE = 0            # EventType, or -1 for padding
EV_ID = 1              # event_id
EV_VERSION = 2         # failover version
EV_TASK_ID = 3         # LastEventTaskID source
EV_TS = 4              # seconds
EV_BATCH_FIRST = 5     # first event_id of this event's transaction batch
EV_IS_BATCH_LAST = 6   # 1 if last event of its batch
EV_SLOT = 7            # precomputed pending-map slot this event touches, or -1
EV_A0 = 8              # per-type attributes (see pack.py for the mapping)
EV_A1 = 9
EV_A2 = 10
EV_A3 = 11
EV_A4 = 12
EV_A5 = 13
EV_A6 = 14
EV_A7 = 15
EV_N = 16

# --------------------------------------------------------------------------
# Execution-info columns: exec_info[B, X_N]
# --------------------------------------------------------------------------
X_STATE = 0
X_CLOSE_STATUS = 1
X_NEXT_EVENT_ID = 2
X_LAST_FIRST_EVENT_ID = 3
X_LAST_EVENT_TASK_ID = 4
X_LAST_PROCESSED_EVENT = 5
X_START_TS = 6
X_WORKFLOW_TIMEOUT = 7        # seconds
X_DECISION_TIMEOUT_VALUE = 8  # seconds
X_DEC_VERSION = 9
X_DEC_SCHEDULE_ID = 10
X_DEC_STARTED_ID = 11
X_DEC_TIMEOUT = 12            # seconds
X_DEC_ATTEMPT = 13
X_DEC_SCHEDULED_TS = 14
X_DEC_STARTED_TS = 15
X_DEC_ORIGINAL_SCHEDULED_TS = 16
X_CANCEL_REQUESTED = 17
X_SIGNAL_COUNT = 18
X_ATTEMPT = 19                # workflow retry attempt
X_HAS_RETRY_POLICY = 20
X_COMPLETION_EVENT_BATCH_ID = 21
X_PARENT_INITIATED_ID = 22
X_WF_EXPIRATION_TS = 23
X_CUR_VERSION = 24
X_N = 25

# --------------------------------------------------------------------------
# Pending-activity slot columns: activities[B, A, AC_N]
# --------------------------------------------------------------------------
AC_OCC = 0
AC_VERSION = 1
AC_SCHEDULE_ID = 2
AC_SCHEDULED_BATCH_ID = 3
AC_SCHEDULED_TS = 4
AC_STARTED_ID = 5
AC_STARTED_TS = 6
AC_ID_HASH = 7
AC_SCH_TO_START = 8
AC_SCH_TO_CLOSE = 9
AC_START_TO_CLOSE = 10
AC_HEARTBEAT = 11
AC_CANCEL_REQUESTED = 12
AC_CANCEL_REQUEST_ID = 13
AC_ATTEMPT = 14
AC_HAS_RETRY = 15
AC_EXPIRATION_TS = 16
AC_LAST_HB_TS = 17
AC_TIMER_STATUS = 18   # refreshed by ops/refresh.py, not tracked in-scan
AC_N = 19

# --------------------------------------------------------------------------
# Pending-timer slot columns: timers[B, TM, TI_N]
# --------------------------------------------------------------------------
TI_OCC = 0
TI_VERSION = 1
TI_STARTED_ID = 2
TI_ID_HASH = 3
TI_EXPIRY_TS = 4
TI_STATUS = 5          # refreshed by ops/refresh.py
TI_N = 6

# --------------------------------------------------------------------------
# Pending-child slot columns: children[B, C, CH_N]
# --------------------------------------------------------------------------
CH_OCC = 0
CH_VERSION = 1
CH_INITIATED_ID = 2
CH_INITIATED_BATCH_ID = 3
CH_STARTED_ID = 4
CH_WF_ID_HASH = 5
CH_RUN_ID_HASH = 6
CH_POLICY = 7
CH_N = 8

# --------------------------------------------------------------------------
# Pending external cancel/signal slot columns: [B, RC, RC_N] / [B, SG, SG_N]
# --------------------------------------------------------------------------
RC_OCC = 0
RC_VERSION = 1
RC_INITIATED_ID = 2
RC_INITIATED_BATCH_ID = 3
RC_N = 4

SG_OCC = 0
SG_VERSION = 1
SG_INITIATED_ID = 2
SG_INITIATED_BATCH_ID = 3
SG_N = 4


@dataclasses.dataclass(frozen=True)
class Capacities:
    """Slot-table sizes. Histories whose pending sets exceed these are
    rejected at pack time and routed to the host replay path (the
    overflow-to-host escape hatch, SURVEY.md §7 hard part (b))."""

    max_events: int = 1024        # T: scan length (padded)
    max_activities: int = 32      # A
    max_timers: int = 16          # TM
    max_children: int = 16        # C
    max_request_cancels: int = 8  # RC
    max_signals_ext: int = 8      # SG
    max_version_items: int = 8    # V: version-history items (NDC)


@dataclasses.dataclass
class StateTensors:
    """The batched mutable-state pytree. All arrays int32.

    Works with numpy (host packing) and jax.numpy (device) arrays alike.
    """

    exec_info: Any      # [B, X_N]
    activities: Any     # [B, A, AC_N]
    timers: Any         # [B, TM, TI_N]
    children: Any       # [B, C, CH_N]
    cancels: Any        # [B, RC, RC_N]
    signals: Any        # [B, SG, SG_N]
    vh_items: Any       # [B, V, 2]  (event_id, version)
    vh_len: Any         # [B]

    @property
    def batch(self) -> int:
        return self.exec_info.shape[0]

    def tree_flatten(self):
        return (
            (
                self.exec_info, self.activities, self.timers, self.children,
                self.cancels, self.signals, self.vh_items, self.vh_len,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


def _register_pytree() -> None:
    try:
        from jax import tree_util

        tree_util.register_pytree_node(
            StateTensors,
            lambda s: s.tree_flatten(),
            StateTensors.tree_unflatten,
        )
    except ImportError:  # jax optional for host-only use
        pass


_register_pytree()


# --------------------------------------------------------------------------
# Per-history state rows (the checkpoint subsystem's unit of persistence).
#
# A "state row" is one workflow's slice of a StateTensors batch as a plain
# dict of numpy arrays — what cadence_tpu/checkpoint/ stores and what the
# packer's resume path seeds segment carries from. Timestamps inside a row
# are epoch-relative (the packer's rel_ts encoding), so a row moved between
# batches with different epochs must be shifted by ``rebase_state_row``.
# --------------------------------------------------------------------------

STATE_ROW_FIELDS = (
    "exec_info", "activities", "timers", "children", "cancels",
    "signals", "vh_items", "vh_len",
)

# epoch-relative timestamp positions per field: (column index gated on > 0)
ROW_TS_COLS = {
    "exec_info": (
        X_START_TS, X_DEC_SCHEDULED_TS, X_DEC_STARTED_TS,
        X_DEC_ORIGINAL_SCHEDULED_TS, X_WF_EXPIRATION_TS,
    ),
    "activities": (
        AC_SCHEDULED_TS, AC_STARTED_TS, AC_EXPIRATION_TS, AC_LAST_HB_TS,
    ),
    "timers": (TI_EXPIRY_TS,),
}


def state_row(state: StateTensors, b: int) -> Dict[str, Any]:
    """Copy workflow ``b``'s slice of a StateTensors batch to a row dict."""
    return {
        f: np.array(np.asarray(getattr(state, f))[b], dtype=np.int32)
        for f in STATE_ROW_FIELDS
    }


def set_state_row(state: StateTensors, b: int, row: Dict[str, Any]) -> None:
    """Write a row dict into slice ``b`` of a numpy StateTensors."""
    for f in STATE_ROW_FIELDS:
        np.asarray(getattr(state, f))[b] = row[f]


def rebase_state_row(row: Dict[str, Any], delta_s: int) -> Dict[str, Any]:
    """Shift every set (non-zero) epoch-relative timestamp by ``delta_s``
    seconds — moves a row from epoch e_old to e_new = e_old - delta_s.
    Returns a new row; the input is untouched."""
    out = {f: np.array(v, dtype=np.int32) for f, v in row.items()}
    if delta_s:
        for field, cols in ROW_TS_COLS.items():
            arr = out[field]
            for c in cols:
                col = arr[..., c]
                col[col > 0] += delta_s
    return out


# --------------------------------------------------------------------------
# Per-column update algebra — the metadata the parallel-in-time replay
# (ops/assoc.py) and the ASSOC-UNPROVEN static-analysis rule share.
#
# Every kernel write to a state cell must compose associatively for the
# segmented-scan replay to be sound. Four algebras cover the transition
# surface:
#
#   set      x -> v            the mul=0 affine case (last-writer-wins;
#                              provenance resolution). The default.
#   counter  x -> x + d        the mul=1 affine case (prefix sums).
#   fsm      x -> f(x)         bounded function table closed under
#                              composition (X_STATE's Created->Running
#                              promotion; {identity, promote, const}).
#   rle      run-length        the version-history add_or_update: append
#                              on version change, recovered from a
#                              segmented prefix count of change flags.
#
# A column NOT listed here is "set". A new kernel transition that reads
# prior state in any other shape (cross-column arithmetic, data-
# dependent control) has no declared algebra — the analysis gate then
# reports ASSOC-UNPROVEN and the runtime classifier routes the type to
# the sequential fallback.
# --------------------------------------------------------------------------

UPDATE_ALGEBRA = {
    "exec:X_STATE": "fsm",
    "exec:X_SIGNAL_COUNT": "counter",
    "exec:X_DEC_ATTEMPT": "counter",
    "vh:event_id": "rle",
    "vh:version": "rle",
    "vh:len": "rle",
}

DEFAULT_ALGEBRA = "set"

ALGEBRAS = ("set", "counter", "fsm", "rle")


def update_algebra(label: str) -> str:
    """Composition algebra of one state-cell label (``exec:X_*``,
    ``vh:*``, or a slot-table label like ``activities:AC_*``)."""
    return UPDATE_ALGEBRA.get(label, DEFAULT_ALGEBRA)


# (prefix, count constant) per column table — the reflection surface
# shared with cadence_tpu/analysis/transition_surface.py
_COLUMN_GROUPS = (
    ("EV_", "EV_N"), ("X_", "X_N"), ("AC_", "AC_N"), ("TI_", "TI_N"),
    ("CH_", "CH_N"), ("RC_", "RC_N"), ("SG_", "SG_N"),
)


def validate(ns: Dict[str, Any] = None) -> None:
    """Assert column-constant density and uniqueness, and that every
    ROW_TS_COLS entry names a real column of its field.

    The cheapest invariant of the transition-surface checker
    (cadence_tpu/analysis/), also enforced at import time so a botched
    column renumber fails the FIRST import, not the next lint run. Cost
    is a few hundred dict lookups.
    """
    ns = ns if ns is not None else globals()
    for prefix, count_name in _COLUMN_GROUPS:
        n = ns[count_name]
        seen: Dict[int, str] = {}
        for k, v in ns.items():
            if not k.startswith(prefix) or k == count_name:
                continue
            if not isinstance(v, int) or isinstance(v, bool):
                continue
            if v in seen:
                raise AssertionError(
                    f"schema column collision: {seen[v]} and {k} both = {v}"
                )
            if not 0 <= v < n:
                raise AssertionError(
                    f"schema column {k} = {v} outside [0, {count_name}={n})"
                )
            seen[v] = k
        if len(seen) != n:
            missing = sorted(set(range(n)) - set(seen))
            raise AssertionError(
                f"schema columns not dense: {prefix}* has no constant for "
                f"value(s) {missing} under {count_name}={n}"
            )
    counts = {
        "exec_info": ns["X_N"], "activities": ns["AC_N"],
        "timers": ns["TI_N"], "children": ns["CH_N"],
        "cancels": ns["RC_N"], "signals": ns["SG_N"],
    }
    for field, cols in ns["ROW_TS_COLS"].items():
        for c in cols:
            if not 0 <= c < counts[field]:
                raise AssertionError(
                    f"ROW_TS_COLS[{field!r}] column {c} outside its table "
                    f"(N={counts[field]})"
                )
    for label, algebra in ns["UPDATE_ALGEBRA"].items():
        if algebra not in ns["ALGEBRAS"]:
            raise AssertionError(
                f"UPDATE_ALGEBRA[{label!r}] = {algebra!r} is not one of "
                f"{ns['ALGEBRAS']}"
            )
        kind, _, col = label.partition(":")
        if kind == "exec" and col not in ns:
            raise AssertionError(
                f"UPDATE_ALGEBRA names unknown exec column {col!r}"
            )


validate()


def empty_state(batch: int, caps: Capacities) -> StateTensors:
    """Fresh (pre-start) state for `batch` workflows, numpy int32.

    Sentinel initialization mirrors a fresh mutableStateBuilder: decision
    IDs empty, versions empty.
    """
    from cadence_tpu.core.ids import EMPTY_EVENT_ID, EMPTY_VERSION, FIRST_EVENT_ID

    ex = np.zeros((batch, X_N), dtype=np.int32)
    ex[:, X_NEXT_EVENT_ID] = FIRST_EVENT_ID
    ex[:, X_LAST_FIRST_EVENT_ID] = EMPTY_EVENT_ID
    ex[:, X_LAST_EVENT_TASK_ID] = EMPTY_EVENT_ID
    ex[:, X_LAST_PROCESSED_EVENT] = EMPTY_EVENT_ID
    ex[:, X_DEC_VERSION] = EMPTY_VERSION
    ex[:, X_DEC_SCHEDULE_ID] = EMPTY_EVENT_ID
    ex[:, X_DEC_STARTED_ID] = EMPTY_EVENT_ID
    ex[:, X_COMPLETION_EVENT_BATCH_ID] = EMPTY_EVENT_ID
    ex[:, X_PARENT_INITIATED_ID] = EMPTY_EVENT_ID
    ex[:, X_CUR_VERSION] = EMPTY_VERSION
    return StateTensors(
        exec_info=ex,
        activities=np.zeros((batch, caps.max_activities, AC_N), dtype=np.int32),
        timers=np.zeros((batch, caps.max_timers, TI_N), dtype=np.int32),
        children=np.zeros((batch, caps.max_children, CH_N), dtype=np.int32),
        cancels=np.zeros((batch, caps.max_request_cancels, RC_N), dtype=np.int32),
        signals=np.zeros((batch, caps.max_signals_ext, SG_N), dtype=np.int32),
        vh_items=np.zeros((batch, caps.max_version_items, 2), dtype=np.int32),
        vh_len=np.zeros((batch,), dtype=np.int32),
    )
