"""Unpack device state tensors back into host snapshots.

Two converters produce the same canonical "replay snapshot" dict:

  * ``state_row_to_snapshot`` — from kernel output (StateTensors row +
    packer side table),
  * ``mutable_state_to_snapshot`` — from the host oracle's MutableState,

so differential tests compare them with ``==``. The canonical form uses
second-granularity timestamps (the device ABI) and int31 hashes for
string-keyed fields; timer-task dedup status is excluded (refreshed
post-replay by ops/refresh.py on both paths — mirroring the reference's
taskRefresher after nDCStateRebuilder.rebuild).

``state_row_to_mutable_state`` additionally rehydrates a full MutableState
(strings from the side table) for the host runtime to persist — the device
path's equivalent of nDCStateRebuilder returning a rebuilt mutableState.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.mutable_state import (
    ActivityInfo,
    ChildExecutionInfo,
    MutableState,
    RequestCancelInfo,
    SignalInfo,
    TimerInfo,
)
from cadence_tpu.core.enums import CloseStatus, ParentClosePolicy, WorkflowState
from cadence_tpu.core.version_history import VersionHistories, VersionHistory, VersionHistoryItem
from cadence_tpu.utils.hashing import hash31

from . import schema as S
from .pack import SECONDS, WorkflowSideTable

# exec/table columns holding timestamps (relative-epoch encoded on device)
_EXEC_TS_KEYS = {
    "start_ts", "dec_scheduled_ts", "dec_started_ts",
    "dec_original_scheduled_ts", "wf_expiration_ts",
}


def _abs_s(v: int, epoch_s: int) -> int:
    """Inverse of the packer's rel_ts: 0 stays the unset sentinel."""
    return v + epoch_s - 1 if v > 0 else v


_EXEC_FIELDS = [
    ("state", S.X_STATE),
    ("close_status", S.X_CLOSE_STATUS),
    ("next_event_id", S.X_NEXT_EVENT_ID),
    ("last_first_event_id", S.X_LAST_FIRST_EVENT_ID),
    ("last_event_task_id", S.X_LAST_EVENT_TASK_ID),
    ("last_processed_event", S.X_LAST_PROCESSED_EVENT),
    ("start_ts", S.X_START_TS),
    ("workflow_timeout", S.X_WORKFLOW_TIMEOUT),
    ("decision_timeout_value", S.X_DECISION_TIMEOUT_VALUE),
    ("dec_version", S.X_DEC_VERSION),
    ("dec_schedule_id", S.X_DEC_SCHEDULE_ID),
    ("dec_started_id", S.X_DEC_STARTED_ID),
    ("dec_timeout", S.X_DEC_TIMEOUT),
    ("dec_attempt", S.X_DEC_ATTEMPT),
    ("dec_scheduled_ts", S.X_DEC_SCHEDULED_TS),
    ("dec_started_ts", S.X_DEC_STARTED_TS),
    ("dec_original_scheduled_ts", S.X_DEC_ORIGINAL_SCHEDULED_TS),
    ("cancel_requested", S.X_CANCEL_REQUESTED),
    ("signal_count", S.X_SIGNAL_COUNT),
    ("attempt", S.X_ATTEMPT),
    ("has_retry_policy", S.X_HAS_RETRY_POLICY),
    ("completion_event_batch_id", S.X_COMPLETION_EVENT_BATCH_ID),
    ("parent_initiated_id", S.X_PARENT_INITIATED_ID),
    ("wf_expiration_ts", S.X_WF_EXPIRATION_TS),
    ("cur_version", S.X_CUR_VERSION),
]


def state_row_to_snapshot(
    state: S.StateTensors, b: int, epoch_s: int = 0
) -> Dict[str, Any]:
    """Canonical snapshot of workflow ``b`` from kernel output."""
    ex = np.asarray(state.exec_info[b])
    snap: Dict[str, Any] = {
        "exec": {
            k: (_abs_s(int(ex[c]), epoch_s) if k in _EXEC_TS_KEYS else int(ex[c]))
            for k, c in _EXEC_FIELDS
        }
    }

    acts = {}
    for row in np.asarray(state.activities[b]):
        if row[S.AC_OCC]:
            acts[int(row[S.AC_SCHEDULE_ID])] = {
                "version": int(row[S.AC_VERSION]),
                "scheduled_event_batch_id": int(row[S.AC_SCHEDULED_BATCH_ID]),
                "scheduled_ts": _abs_s(int(row[S.AC_SCHEDULED_TS]), epoch_s),
                "started_id": int(row[S.AC_STARTED_ID]),
                "started_ts": _abs_s(int(row[S.AC_STARTED_TS]), epoch_s),
                "id_hash": int(row[S.AC_ID_HASH]),
                "schedule_to_start": int(row[S.AC_SCH_TO_START]),
                "schedule_to_close": int(row[S.AC_SCH_TO_CLOSE]),
                "start_to_close": int(row[S.AC_START_TO_CLOSE]),
                "heartbeat": int(row[S.AC_HEARTBEAT]),
                "cancel_requested": int(row[S.AC_CANCEL_REQUESTED]),
                "cancel_request_id": int(row[S.AC_CANCEL_REQUEST_ID]),
                "attempt": int(row[S.AC_ATTEMPT]),
                "has_retry": int(row[S.AC_HAS_RETRY]),
                "expiration_ts": _abs_s(int(row[S.AC_EXPIRATION_TS]), epoch_s),
                "last_hb_ts": _abs_s(int(row[S.AC_LAST_HB_TS]), epoch_s),
            }
    snap["activities"] = acts

    timers = {}
    for row in np.asarray(state.timers[b]):
        if row[S.TI_OCC]:
            timers[int(row[S.TI_STARTED_ID])] = {
                "version": int(row[S.TI_VERSION]),
                "id_hash": int(row[S.TI_ID_HASH]),
                "expiry_ts": _abs_s(int(row[S.TI_EXPIRY_TS]), epoch_s),
            }
    snap["timers"] = timers

    children = {}
    for row in np.asarray(state.children[b]):
        if row[S.CH_OCC]:
            children[int(row[S.CH_INITIATED_ID])] = {
                "version": int(row[S.CH_VERSION]),
                "initiated_event_batch_id": int(row[S.CH_INITIATED_BATCH_ID]),
                "started_id": int(row[S.CH_STARTED_ID]),
                "wf_id_hash": int(row[S.CH_WF_ID_HASH]),
                "run_id_hash": int(row[S.CH_RUN_ID_HASH]),
                "policy": int(row[S.CH_POLICY]),
            }
    snap["children"] = children

    for name, table, occ_col, init_col, ver_col, batch_col in (
        ("cancels", state.cancels, S.RC_OCC, S.RC_INITIATED_ID, S.RC_VERSION, S.RC_INITIATED_BATCH_ID),
        ("signals", state.signals, S.SG_OCC, S.SG_INITIATED_ID, S.SG_VERSION, S.SG_INITIATED_BATCH_ID),
    ):
        entries = {}
        for row in np.asarray(table[b]):
            if row[occ_col]:
                entries[int(row[init_col])] = {
                    "version": int(row[ver_col]),
                    "initiated_event_batch_id": int(row[batch_col]),
                }
        snap[name] = entries

    n = int(state.vh_len[b])
    snap["version_history"] = [
        (int(e), int(v)) for e, v in np.asarray(state.vh_items[b][:n])
    ]
    return snap


def mutable_state_to_snapshot(ms: MutableState) -> Dict[str, Any]:
    """Same canonical form, from the host oracle."""
    ei = ms.execution_info
    s = lambda ns: ns // SECONDS
    snap: Dict[str, Any] = {
        "exec": {
            "state": int(ei.state),
            "close_status": int(ei.close_status),
            "next_event_id": ei.next_event_id,
            "last_first_event_id": ei.last_first_event_id,
            "last_event_task_id": ei.last_event_task_id,
            "last_processed_event": ei.last_processed_event,
            "start_ts": s(ei.start_timestamp),
            "workflow_timeout": ei.workflow_timeout,
            "decision_timeout_value": ei.decision_timeout_value,
            "dec_version": ei.decision_version,
            "dec_schedule_id": ei.decision_schedule_id,
            "dec_started_id": ei.decision_started_id,
            "dec_timeout": ei.decision_timeout,
            "dec_attempt": ei.decision_attempt,
            "dec_scheduled_ts": s(ei.decision_scheduled_timestamp),
            "dec_started_ts": s(ei.decision_started_timestamp),
            "dec_original_scheduled_ts": s(ei.decision_original_scheduled_timestamp),
            "cancel_requested": int(ei.cancel_requested),
            "signal_count": ei.signal_count,
            "attempt": ei.attempt,
            "has_retry_policy": int(ei.has_retry_policy),
            "completion_event_batch_id": ei.completion_event_batch_id,
            "parent_initiated_id": ei.initiated_id,
            "wf_expiration_ts": s(ei.expiration_time),
            "cur_version": ms.current_version,
        },
        "activities": {
            sid: {
                "version": ai.version,
                "scheduled_event_batch_id": ai.scheduled_event_batch_id,
                "scheduled_ts": s(ai.scheduled_time),
                "started_id": ai.started_id,
                "started_ts": s(ai.started_time),
                "id_hash": hash31(ai.activity_id),
                "schedule_to_start": ai.schedule_to_start_timeout,
                "schedule_to_close": ai.schedule_to_close_timeout,
                "start_to_close": ai.start_to_close_timeout,
                "heartbeat": ai.heartbeat_timeout,
                "cancel_requested": int(ai.cancel_requested),
                "cancel_request_id": ai.cancel_request_id,
                "attempt": ai.attempt,
                "has_retry": int(ai.has_retry_policy),
                "expiration_ts": s(ai.expiration_time),
                "last_hb_ts": s(ai.last_heartbeat_updated_time),
            }
            for sid, ai in ms.pending_activities.items()
        },
        "timers": {
            ti.started_id: {
                "version": ti.version,
                "id_hash": hash31(ti.timer_id),
                "expiry_ts": s(ti.expiry_time),
            }
            for ti in ms.pending_timers.values()
        },
        "children": {
            cid: {
                "version": ci.version,
                "initiated_event_batch_id": ci.initiated_event_batch_id,
                "started_id": ci.started_id,
                "wf_id_hash": hash31(ci.started_workflow_id),
                "run_id_hash": hash31(ci.started_run_id) if ci.started_run_id else 0,
                "policy": int(ci.parent_close_policy),
            }
            for cid, ci in ms.pending_children.items()
        },
        "cancels": {
            rid: {
                "version": rc.version,
                "initiated_event_batch_id": rc.initiated_event_batch_id,
            }
            for rid, rc in ms.pending_request_cancels.items()
        },
        "signals": {
            sid: {
                "version": si.version,
                "initiated_event_batch_id": si.initiated_event_batch_id,
            }
            for sid, si in ms.pending_signals.items()
        },
        "version_history": (
            [
                (it.event_id, it.version)
                for it in ms.version_histories.get_current_version_history().items
            ]
            if ms.version_histories is not None
            else []
        ),
    }
    return snap


def split_lane_snapshots(packed, final: S.StateTensors) -> list:
    """Split a lane-packed replay's output back into per-history
    snapshots, in the packer's input order.

    ``packed``: the :class:`~cadence_tpu.ops.pack.PackedLanes` whose
    lanes were replayed; ``final``: the output StateTensors from
    ``replay_packed_lanes``/``replay_scan_packed`` (one row per
    history). The per-lane segment side tables are the source of truth
    for which output row belongs to which history — this walks them
    (rather than trusting row order) so a mis-scattered row surfaces as
    a snapshot mismatch, not silent misattribution.
    """
    n = packed.n_histories
    snaps = [None] * n
    for segs in packed.lane_segments:
        for out_row, _start, _end in segs:
            snaps[out_row] = state_row_to_snapshot(
                final, out_row, packed.epoch_s
            )
    missing = [i for i in range(n) if snaps[i] is None]
    if missing:
        raise ValueError(
            f"lane segment tables miss output rows {missing[:8]}"
        )
    return snaps


def state_row_to_mutable_state(
    state: S.StateTensors, b: int, side: WorkflowSideTable,
    domain_id: str = "",
    epoch_s: int = 0,
) -> MutableState:
    """Rehydrate a full MutableState from kernel output + side table."""

    def ns(v: int) -> int:
        return _abs_s(int(v), epoch_s) * SECONDS

    ex = np.asarray(state.exec_info[b])
    ms = MutableState(domain_id=domain_id, current_version=int(ex[S.X_CUR_VERSION]))
    ei = ms.execution_info
    ei.workflow_id = side.workflow_id
    ei.run_id = side.run_id
    ei.create_request_id = side.request_id
    ei.task_list = side.task_list
    ei.workflow_type_name = side.workflow_type
    ei.cron_schedule = side.cron_schedule
    ei.parent_domain_id = side.parent_domain
    ei.parent_workflow_id = side.parent_workflow_id
    ei.parent_run_id = side.parent_run_id
    ei.memo = dict(side.memo)
    ei.search_attributes = dict(side.search_attributes)
    ei.auto_reset_points = [dict(p) for p in side.auto_reset_points]
    ei.first_decision_backoff_deadline = (
        side.first_decision_backoff_deadline
    )
    ei.state = WorkflowState(int(ex[S.X_STATE]))
    ei.close_status = CloseStatus(int(ex[S.X_CLOSE_STATUS]))
    ei.next_event_id = int(ex[S.X_NEXT_EVENT_ID])
    ei.last_first_event_id = int(ex[S.X_LAST_FIRST_EVENT_ID])
    ei.last_event_task_id = int(ex[S.X_LAST_EVENT_TASK_ID])
    ei.last_processed_event = int(ex[S.X_LAST_PROCESSED_EVENT])
    ei.start_timestamp = ns(ex[S.X_START_TS])
    ei.workflow_timeout = int(ex[S.X_WORKFLOW_TIMEOUT])
    ei.decision_timeout_value = int(ex[S.X_DECISION_TIMEOUT_VALUE])
    ei.decision_version = int(ex[S.X_DEC_VERSION])
    ei.decision_schedule_id = int(ex[S.X_DEC_SCHEDULE_ID])
    ei.decision_started_id = int(ex[S.X_DEC_STARTED_ID])
    ei.decision_timeout = int(ex[S.X_DEC_TIMEOUT])
    ei.decision_attempt = int(ex[S.X_DEC_ATTEMPT])
    ei.decision_scheduled_timestamp = ns(ex[S.X_DEC_SCHEDULED_TS])
    ei.decision_started_timestamp = ns(ex[S.X_DEC_STARTED_TS])
    ei.decision_original_scheduled_timestamp = ns(ex[S.X_DEC_ORIGINAL_SCHEDULED_TS])
    ei.cancel_requested = bool(ex[S.X_CANCEL_REQUESTED])
    ei.signal_count = int(ex[S.X_SIGNAL_COUNT])
    ei.attempt = int(ex[S.X_ATTEMPT])
    ei.has_retry_policy = bool(ex[S.X_HAS_RETRY_POLICY])
    ei.completion_event_batch_id = int(ex[S.X_COMPLETION_EVENT_BATCH_ID])
    ei.initiated_id = int(ex[S.X_PARENT_INITIATED_ID])
    ei.expiration_time = ns(ex[S.X_WF_EXPIRATION_TS])

    for slot, row in enumerate(np.asarray(state.activities[b])):
        if not row[S.AC_OCC]:
            continue
        activity_id = side.activity_ids.get(slot, "")
        ai = ActivityInfo(
            version=int(row[S.AC_VERSION]),
            schedule_id=int(row[S.AC_SCHEDULE_ID]),
            scheduled_event_batch_id=int(row[S.AC_SCHEDULED_BATCH_ID]),
            scheduled_time=ns(row[S.AC_SCHEDULED_TS]),
            started_id=int(row[S.AC_STARTED_ID]),
            started_time=ns(row[S.AC_STARTED_TS]),
            activity_id=activity_id,
            schedule_to_start_timeout=int(row[S.AC_SCH_TO_START]),
            schedule_to_close_timeout=int(row[S.AC_SCH_TO_CLOSE]),
            start_to_close_timeout=int(row[S.AC_START_TO_CLOSE]),
            heartbeat_timeout=int(row[S.AC_HEARTBEAT]),
            cancel_requested=bool(row[S.AC_CANCEL_REQUESTED]),
            cancel_request_id=int(row[S.AC_CANCEL_REQUEST_ID]),
            attempt=int(row[S.AC_ATTEMPT]),
            has_retry_policy=bool(row[S.AC_HAS_RETRY]),
            expiration_time=ns(row[S.AC_EXPIRATION_TS]),
            last_heartbeat_updated_time=ns(row[S.AC_LAST_HB_TS]),
            task_list=side.activity_task_lists.get(slot, ""),
        )
        ms.pending_activities[ai.schedule_id] = ai
        ms.activity_by_id[ai.activity_id] = ai.schedule_id

    for slot, row in enumerate(np.asarray(state.timers[b])):
        if not row[S.TI_OCC]:
            continue
        timer_id = side.timer_ids.get(slot, "")
        ti = TimerInfo(
            version=int(row[S.TI_VERSION]),
            timer_id=timer_id,
            started_id=int(row[S.TI_STARTED_ID]),
            expiry_time=ns(row[S.TI_EXPIRY_TS]),
        )
        ms.pending_timers[timer_id] = ti
        ms.timer_by_started_id[ti.started_id] = timer_id

    for slot, row in enumerate(np.asarray(state.children[b])):
        if not row[S.CH_OCC]:
            continue
        ci = ChildExecutionInfo(
            version=int(row[S.CH_VERSION]),
            initiated_id=int(row[S.CH_INITIATED_ID]),
            initiated_event_batch_id=int(row[S.CH_INITIATED_BATCH_ID]),
            started_id=int(row[S.CH_STARTED_ID]),
            started_workflow_id=side.child_workflow_ids.get(slot, ""),
            started_run_id=side.child_run_ids.get(slot, ""),
            domain_name=side.child_domains.get(slot, ""),
            workflow_type_name=side.child_types.get(slot, ""),
            parent_close_policy=ParentClosePolicy(int(row[S.CH_POLICY])),
        )
        ms.pending_children[ci.initiated_id] = ci

    for slot, row in enumerate(np.asarray(state.cancels[b])):
        if row[S.RC_OCC]:
            tgt = side.cancel_targets.get(slot) or ("", "", "", False)
            rc = RequestCancelInfo(
                version=int(row[S.RC_VERSION]),
                initiated_id=int(row[S.RC_INITIATED_ID]),
                initiated_event_batch_id=int(row[S.RC_INITIATED_BATCH_ID]),
                target_domain_id=tgt[0],
                target_workflow_id=tgt[1],
                target_run_id=tgt[2],
                target_child_workflow_only=tgt[3],
            )
            ms.pending_request_cancels[rc.initiated_id] = rc

    for slot, row in enumerate(np.asarray(state.signals[b])):
        if row[S.SG_OCC]:
            tgt = side.signal_targets.get(slot) or ("", "", "", False)
            si = SignalInfo(
                version=int(row[S.SG_VERSION]),
                initiated_id=int(row[S.SG_INITIATED_ID]),
                initiated_event_batch_id=int(row[S.SG_INITIATED_BATCH_ID]),
                target_domain_id=tgt[0],
                target_workflow_id=tgt[1],
                target_run_id=tgt[2],
                target_child_workflow_only=tgt[3],
            )
            ms.pending_signals[si.initiated_id] = si

    n = int(state.vh_len[b])
    vh = VersionHistory(
        items=[
            VersionHistoryItem(int(e), int(v))
            for e, v in np.asarray(state.vh_items[b][:n])
        ]
    )
    ms.version_histories = VersionHistories([vh], 0)
    return ms
