"""Pallas TPU replay kernel — VMEM-resident state scan.

Why this exists: the XLA ``lax.scan`` kernel (ops/replay.py) round-trips
the full state carry through HBM several times per step (measured
~160us/step at B=8192 on v5e — ~10x the single-carry HBM cost), because
the step body compiles to multiple fusions. This kernel keeps the
entire mutable state of a batch tile resident in VMEM for the whole
scan and streams only event blocks from HBM, eliminating the carry
traffic altogether.

Design:

- **Row layout**: all state tensors of a batch tile are packed into one
  int32 ``[R, BT]`` matrix — batch is the lane (minor) dimension, so
  every row update is a fully-utilized 128-lane VPU op. R enumerates
  exec-info columns, version-history slots, then the flattened slot
  tables (see RowMap).
- **Grid** ``(B/BT, T/TB)`` with time as the inner sequential dimension;
  the output state block's index map ignores t, so Pallas keeps it in
  VMEM across the whole time axis (accumulator pattern) and flushes it
  once per batch tile.
- **Predication**: every event-type group and every slot's update is
  wrapped in ``@pl.when(jnp.any(mask))`` — a tile only pays for the
  event types (and slots) actually present at that timestep. Real
  replication storms are type-homogeneous across lanes at most steps,
  so this skips most of the transition table most of the time; the
  worst (fully mixed) case degrades to the branchless cost, never
  above it.

Semantics are identical to ops/replay.py (the oracle's, i.e. the
reference's stateBuilder.applyEvents,
/root/reference/service/history/stateBuilder.go:112-613);
tests/test_replay_pallas.py asserts bit-for-bit state parity against
the XLA kernel, which is itself differential-tested against the host
oracle.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# renamed TPUCompilerParams -> CompilerParams across jax releases
_CompilerParams = getattr(
    pltpu, "CompilerParams", getattr(pltpu, "TPUCompilerParams", None)
)

from cadence_tpu.core.enums import (
    CloseStatus, EventType as E, WorkflowState,
    WORKFLOW_CLOSE_STATUS, decision_attempt_increment,
)
from cadence_tpu.core.ids import EMPTY_EVENT_ID, EMPTY_VERSION

from . import schema as S


@dataclasses.dataclass(frozen=True)
class RowMap:
    """Static row offsets of each state tensor inside the [R, B] matrix."""

    caps: S.Capacities
    exec0: int = 0

    @property
    def vh0(self) -> int:  # vh_items rows: vh0 + i*2 + {0: event_id, 1: version}
        return self.exec0 + S.X_N

    @property
    def vhlen(self) -> int:
        return self.vh0 + 2 * self.caps.max_version_items

    @property
    def act0(self) -> int:
        return self.vhlen + 1

    @property
    def tim0(self) -> int:
        return self.act0 + self.caps.max_activities * S.AC_N

    @property
    def chd0(self) -> int:
        return self.tim0 + self.caps.max_timers * S.TI_N

    @property
    def rc0(self) -> int:
        return self.chd0 + self.caps.max_children * S.CH_N

    @property
    def sg0(self) -> int:
        return self.rc0 + self.caps.max_request_cancels * S.RC_N

    @property
    def rows(self) -> int:
        return self.sg0 + self.caps.max_signals_ext * S.SG_N

    @property
    def rows_padded(self) -> int:
        return ((self.rows + 7) // 8) * 8


def state_to_rows(state: S.StateTensors, rm: RowMap):
    """StateTensors -> [R, B] int32 (jnp), batch minor."""
    b = state.exec_info.shape[0]
    parts = [
        jnp.transpose(state.exec_info),                       # [X_N, B]
        jnp.transpose(state.vh_items.reshape(b, -1)),         # [2V, B]
        state.vh_len[None, :],                                # [1, B]
        jnp.transpose(state.activities.reshape(b, -1)),
        jnp.transpose(state.timers.reshape(b, -1)),
        jnp.transpose(state.children.reshape(b, -1)),
        jnp.transpose(state.cancels.reshape(b, -1)),
        jnp.transpose(state.signals.reshape(b, -1)),
    ]
    rows = jnp.concatenate(parts, axis=0).astype(jnp.int32)
    pad = rm.rows_padded - rm.rows
    if pad:
        rows = jnp.pad(rows, ((0, pad), (0, 0)))
    return rows


def rows_to_state(rows, rm: RowMap) -> S.StateTensors:
    caps = rm.caps
    b = rows.shape[1]

    def take(r0, n, shape):
        return jnp.transpose(rows[r0 : r0 + n]).reshape(shape)

    return S.StateTensors(
        exec_info=take(rm.exec0, S.X_N, (b, S.X_N)),
        vh_items=take(rm.vh0, 2 * caps.max_version_items,
                      (b, caps.max_version_items, 2)),
        vh_len=rows[rm.vhlen],
        activities=take(rm.act0, caps.max_activities * S.AC_N,
                        (b, caps.max_activities, S.AC_N)),
        timers=take(rm.tim0, caps.max_timers * S.TI_N,
                    (b, caps.max_timers, S.TI_N)),
        children=take(rm.chd0, caps.max_children * S.CH_N,
                      (b, caps.max_children, S.CH_N)),
        cancels=take(rm.rc0, caps.max_request_cancels * S.RC_N,
                     (b, caps.max_request_cancels, S.RC_N)),
        signals=take(rm.sg0, caps.max_signals_ext * S.SG_N,
                     (b, caps.max_signals_ext, S.SG_N)),
    )


def _kernel(presence_ref, base_ref, ev_ref, init_ref, st, *, rm: RowMap,
            tb: int, ablate: int = 0, narrow: bool = False,
            wide_cols: tuple = ()):
    """One (batch-tile, time-block) grid step.

    The batch tile is shaped (SL, 128) with SL a multiple of 8 — whole
    int32 VPU tiles — so every row update runs at full sublane x lane
    utilization (a flat [BT] row would occupy 1 of 8 sublanes). With
    forced-materialization timing the kernel is bound by streaming the
    event blocks from HBM, not by the step body: an empty-body ablation
    (ablate=5) measures the same wall time as the full FSM at B=65536
    (scripts/probe4.py, v5e, 2026-07), so SL mainly trades VMEM for
    fewer grid steps; bt=8192 (SL=64) measured best.

    presence_ref: [1, TB, 4] SMEM — per-step scalar gates for this
             tile: words 0-1 are the event-type bitmask (bit e of word
             e//32 set iff some lane has type e), word 2 is the
             slot-presence bitmask (bit s%32 set iff some lane's event
             touches slot s), word 3 is padding. Precomputed in
             parallel by XLA outside the kernel, so the sequential loop
             gates each type's (and slot's) block on a SCALAR bit test
             instead of a cross-lane ``jnp.any`` reduction.
    ev_ref:  [TB, EV_N, 1, SL, 128] — the time block's events
    init_ref:[R, 1, SL, 128] — initial state block (only read at t==0)
    st:      [R, 1, SL, 128] — output state block, VMEM-resident across t
    """
    caps = rm.caps
    t_idx = pl.program_id(1)

    @pl.when(t_idx == 0)
    def _():
        st[...] = init_ref[...]

    def rd(r):
        return st[r, 0]

    def wr(r, mask, val):
        st[r, 0] = jnp.where(mask, val, st[r, 0])

    def step(i, carry):
        w0 = presence_ref[0, i, 0]
        w1 = presence_ref[0, i, 1]
        w_slot = presence_ref[0, i, 2]

        def present(*types):
            """Scalar: any lane in this tile has one of these types."""
            out = None
            for t in types:
                t = int(t)
                bit = ((w0 if t < 32 else w1) >> (t % 32)) & 1
                out = bit if out is None else out | bit
            return out != 0

        ev = ev_ref[i]  # [EV_N(phys), 1, SL, 128]
        if narrow:
            # int16 stream (narrow_events_teb): affine columns
            # reconstruct as stored16 + base[c]; wide columns as
            # (lo16 & 0xffff) | hi16 << 16 — exact int32 either way.
            # The reconstruction ALU is VPU noise against the stream
            # the kernel is bound by (module docstring / ablation note)
            phys, _ = _phys_map(wide_cols)

            def fld(c):
                p = phys[c]
                if c in wide_cols:
                    lo16 = ev[p, 0].astype(jnp.int32) & 0xFFFF
                    return lo16 | (ev[p + 1, 0].astype(jnp.int32) << 16)
                return ev[p, 0].astype(jnp.int32) + base_ref[0, c]
        else:
            def fld(c):
                return ev[c, 0]

        et = fld(S.EV_TYPE)
        valid = et >= 0

        ev_id = fld(S.EV_ID)
        version = fld(S.EV_VERSION)
        ts = fld(S.EV_TS)
        batch_first = fld(S.EV_BATCH_FIRST)
        slot = fld(S.EV_SLOT)
        a0, a1 = fld(S.EV_A0), fld(S.EV_A1)
        a2, a3 = fld(S.EV_A2), fld(S.EV_A3)
        a4, a5 = fld(S.EV_A4), fld(S.EV_A5)
        a6, a7 = fld(S.EV_A6), fld(S.EV_A7)

        X = rm.exec0

        if ablate >= 5:
            return carry

        def m(*types):
            out = et == int(types[0])
            for t in types[1:]:
                out = out | (et == int(t))
            return valid & out

        # ---- preamble (stateBuilder.go:134-155)
        wr(X + S.X_LAST_EVENT_TASK_ID, valid, fld(S.EV_TASK_ID))
        wr(X + S.X_CUR_VERSION, valid, version)
        wr(X + S.X_NEXT_EVENT_ID, valid, ev_id + 1)
        wr(X + S.X_LAST_FIRST_EVENT_ID, valid, batch_first)

        if ablate >= 4:
            return carry

        # ---- version-history AddOrUpdateItem
        cap_v = caps.max_version_items
        vh_len = rd(rm.vhlen)
        last_idx = jnp.maximum(vh_len - 1, 0)
        # clamped read of the last materialized slot (see replay.py:
        # overflowed vh_len must compare against slot cap_v-1, not fall
        # through to the zero init); write_idx keeps the raw last_idx so
        # same-version writes past capacity still match no slot
        read_idx = jnp.minimum(last_idx, cap_v - 1)
        last_ver = jnp.zeros_like(vh_len)
        for i_v in range(cap_v):
            last_ver = jnp.where(read_idx == i_v, rd(rm.vh0 + 2 * i_v + 1),
                                 last_ver)
        same = (vh_len > 0) & (last_ver == version)
        write_idx = jnp.where(same, last_idx,
                              jnp.minimum(vh_len, cap_v - 1))
        for i_v in range(cap_v):
            wmask = valid & (write_idx == i_v)
            wr(rm.vh0 + 2 * i_v, wmask, ev_id)
            wr(rm.vh0 + 2 * i_v + 1, wmask, version)
        wr(rm.vhlen, valid & ~same, vh_len + 1)

        if ablate >= 3:
            return carry

        # ---- workflow lifecycle
        @pl.when(present(E.WorkflowExecutionStarted))
        def _():
            m_start = m(E.WorkflowExecutionStarted)
            wr(X + S.X_STATE, m_start, int(WorkflowState.Created))
            wr(X + S.X_CLOSE_STATUS, m_start, int(CloseStatus.NONE))
            wr(X + S.X_LAST_PROCESSED_EVENT, m_start, EMPTY_EVENT_ID)
            wr(X + S.X_START_TS, m_start, ts)
            wr(X + S.X_WORKFLOW_TIMEOUT, m_start, a0)
            wr(X + S.X_DECISION_TIMEOUT_VALUE, m_start, a1)
            wr(X + S.X_ATTEMPT, m_start, a2)
            wr(X + S.X_HAS_RETRY_POLICY, m_start, a3)
            wr(X + S.X_WF_EXPIRATION_TS, m_start, a4)
            wr(X + S.X_PARENT_INITIATED_ID, m_start, a7)
            wr(X + S.X_DEC_SCHEDULE_ID, m_start, EMPTY_EVENT_ID)
            wr(X + S.X_DEC_STARTED_ID, m_start, EMPTY_EVENT_ID)
            wr(X + S.X_DEC_VERSION, m_start, EMPTY_VERSION)
            for col in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT,
                        S.X_DEC_SCHEDULED_TS, S.X_DEC_STARTED_TS,
                        S.X_DEC_ORIGINAL_SCHEDULED_TS):
                wr(X + col, m_start, 0)

        @pl.when(present(*(t for t, _ in WORKFLOW_CLOSE_STATUS)))
        def _():
            close_status = sum(
                m(t) * int(cs) for t, cs in WORKFLOW_CLOSE_STATUS
            )
            m_close = close_status > 0
            wr(X + S.X_STATE, m_close, int(WorkflowState.Completed))
            wr(X + S.X_CLOSE_STATUS, m_close, close_status)
            wr(X + S.X_COMPLETION_EVENT_BATCH_ID, m_close, batch_first)

        @pl.when(present(E.WorkflowExecutionCancelRequested))
        def _():
            m_creq = m(E.WorkflowExecutionCancelRequested)
            wr(X + S.X_CANCEL_REQUESTED, m_creq, 1)

        @pl.when(present(E.WorkflowExecutionSignaled))
        def _():
            m_sig = m(E.WorkflowExecutionSignaled)
            wr(X + S.X_SIGNAL_COUNT, m_sig, rd(X + S.X_SIGNAL_COUNT) + 1)

        # ---- decision sub-FSM
        if ablate >= 2:
            return carry

        @pl.when(present(E.DecisionTaskScheduled))
        def _():
            m_dsch = m(E.DecisionTaskScheduled)
            wr(X + S.X_DEC_VERSION, m_dsch, version)
            wr(X + S.X_DEC_SCHEDULE_ID, m_dsch, ev_id)
            wr(X + S.X_DEC_STARTED_ID, m_dsch, EMPTY_EVENT_ID)
            wr(X + S.X_DEC_TIMEOUT, m_dsch, a0)
            wr(X + S.X_DEC_ATTEMPT, m_dsch, a1)
            wr(X + S.X_DEC_SCHEDULED_TS, m_dsch, ts)
            wr(X + S.X_DEC_ORIGINAL_SCHEDULED_TS, m_dsch, ts)
            wr(X + S.X_DEC_STARTED_TS, m_dsch, 0)

        @pl.when(present(E.DecisionTaskStarted))
        def _():
            m_dsta = m(E.DecisionTaskStarted)
            wr(X + S.X_STATE,
               m_dsta & (rd(X + S.X_STATE) == int(WorkflowState.Created)),
               int(WorkflowState.Running))
            wr(X + S.X_DEC_VERSION, m_dsta, version)
            wr(X + S.X_DEC_STARTED_ID, m_dsta, ev_id)
            wr(X + S.X_DEC_ATTEMPT, m_dsta, 0)
            wr(X + S.X_DEC_STARTED_TS, m_dsta, ts)

        @pl.when(present(E.DecisionTaskCompleted))
        def _():
            m_dcom = m(E.DecisionTaskCompleted)
            wr(X + S.X_DEC_VERSION, m_dcom, EMPTY_VERSION)
            wr(X + S.X_DEC_SCHEDULE_ID, m_dcom, EMPTY_EVENT_ID)
            wr(X + S.X_DEC_STARTED_ID, m_dcom, EMPTY_EVENT_ID)
            for col in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT,
                        S.X_DEC_SCHEDULED_TS, S.X_DEC_STARTED_TS):
                wr(X + col, m_dcom, 0)
            wr(X + S.X_LAST_PROCESSED_EVENT, m_dcom, a0)

        @pl.when(present(E.DecisionTaskTimedOut, E.DecisionTaskFailed))
        def _():
            m_dto = m(E.DecisionTaskTimedOut)
            m_dfail = m(E.DecisionTaskFailed)
            increment = decision_attempt_increment(m_dfail, m_dto, a0)
            no_increment = (m_dto | m_dfail) & ~increment
            new_attempt = rd(X + S.X_DEC_ATTEMPT) + 1
            wr(X + S.X_DEC_VERSION, increment, rd(X + S.X_CUR_VERSION))
            wr(X + S.X_DEC_SCHEDULE_ID, increment, batch_first)
            wr(X + S.X_DEC_STARTED_ID, increment, EMPTY_EVENT_ID)
            wr(X + S.X_DEC_TIMEOUT, increment,
               rd(X + S.X_DECISION_TIMEOUT_VALUE))
            wr(X + S.X_DEC_ATTEMPT, increment, new_attempt)
            wr(X + S.X_DEC_SCHEDULED_TS, increment, ts)
            wr(X + S.X_DEC_STARTED_TS, increment, 0)
            wr(X + S.X_DEC_ORIGINAL_SCHEDULED_TS, increment, 0)

            wr(X + S.X_DEC_VERSION, no_increment, EMPTY_VERSION)
            wr(X + S.X_DEC_SCHEDULE_ID, no_increment, EMPTY_EVENT_ID)
            wr(X + S.X_DEC_STARTED_ID, no_increment, EMPTY_EVENT_ID)
            for col in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT,
                        S.X_DEC_SCHEDULED_TS, S.X_DEC_STARTED_TS,
                        S.X_DEC_ORIGINAL_SCHEDULED_TS):
                wr(X + col, no_increment, 0)

        # ---- slot-table helper: per-slot predicated updates
        if ablate >= 1:
            return carry

        def for_slots(types, cap, fn):
            @pl.when(present(*types))
            def _():
                base_mask = m(*types)
                for s_i in range(cap):
                    # scalar slot-presence gate (bit aliases across slot
                    # tables and mod 32 — a false positive only runs the
                    # masked writes with an all-false mask, a no-op)
                    @pl.when((((w_slot >> (s_i % 32)) & 1) != 0))
                    def _(s_i=s_i):
                        mask_s = base_mask & (slot == s_i)
                        fn(s_i, mask_s)

        # ---- pending activities
        A = rm.act0

        def act_sched(s_i, mask_s):
            r = A + s_i * S.AC_N
            exp_interval = jnp.where((a5 > 0) & (a6 > a2), a6, a2)
            vals = {
                S.AC_OCC: 1, S.AC_VERSION: version,
                S.AC_SCHEDULE_ID: ev_id,
                S.AC_SCHEDULED_BATCH_ID: batch_first,
                S.AC_SCHEDULED_TS: ts, S.AC_STARTED_ID: EMPTY_EVENT_ID,
                S.AC_STARTED_TS: 0, S.AC_ID_HASH: a0,
                S.AC_SCH_TO_START: a1, S.AC_SCH_TO_CLOSE: a2,
                S.AC_START_TO_CLOSE: a3, S.AC_HEARTBEAT: a4,
                S.AC_CANCEL_REQUESTED: 0,
                S.AC_CANCEL_REQUEST_ID: EMPTY_EVENT_ID,
                S.AC_ATTEMPT: 0, S.AC_HAS_RETRY: a5,
                S.AC_EXPIRATION_TS: ts + exp_interval,
                S.AC_LAST_HB_TS: 0, S.AC_TIMER_STATUS: 0,
            }
            for col in range(S.AC_N):
                wr(r + col, mask_s, vals[col])

        for_slots((E.ActivityTaskScheduled,), caps.max_activities,
                  act_sched)

        def act_start(s_i, mask_s):
            r = A + s_i * S.AC_N
            wr(r + S.AC_VERSION, mask_s, version)
            wr(r + S.AC_STARTED_ID, mask_s, ev_id)
            wr(r + S.AC_STARTED_TS, mask_s, ts)
            wr(r + S.AC_LAST_HB_TS, mask_s, ts)
            wr(r + S.AC_ATTEMPT, mask_s, a1)

        for_slots((E.ActivityTaskStarted,), caps.max_activities,
                  act_start)

        def act_close(s_i, mask_s):
            r = A + s_i * S.AC_N
            for col in range(S.AC_N):
                wr(r + col, mask_s, 0)

        for_slots(
            (E.ActivityTaskCompleted, E.ActivityTaskFailed,
             E.ActivityTaskTimedOut, E.ActivityTaskCanceled),
            caps.max_activities, act_close,
        )

        def act_creq(s_i, mask_s):
            r = A + s_i * S.AC_N
            wr(r + S.AC_VERSION, mask_s, version)
            wr(r + S.AC_CANCEL_REQUESTED, mask_s, 1)
            wr(r + S.AC_CANCEL_REQUEST_ID, mask_s, ev_id)

        for_slots((E.ActivityTaskCancelRequested,), caps.max_activities,
                  act_creq)

        # ---- pending timers
        T_ = rm.tim0

        def tim_start(s_i, mask_s):
            r = T_ + s_i * S.TI_N
            wr(r + S.TI_OCC, mask_s, 1)
            wr(r + S.TI_VERSION, mask_s, version)
            wr(r + S.TI_STARTED_ID, mask_s, ev_id)
            wr(r + S.TI_ID_HASH, mask_s, a0)
            wr(r + S.TI_EXPIRY_TS, mask_s, ts + a1)
            wr(r + S.TI_STATUS, mask_s, 0)

        for_slots((E.TimerStarted,), caps.max_timers, tim_start)

        def tim_close(s_i, mask_s):
            r = T_ + s_i * S.TI_N
            for col in range(S.TI_N):
                wr(r + col, mask_s, 0)

        for_slots((E.TimerFired, E.TimerCanceled), caps.max_timers,
                  tim_close)

        # ---- pending children
        C_ = rm.chd0

        def chd_init(s_i, mask_s):
            r = C_ + s_i * S.CH_N
            vals = {
                S.CH_OCC: 1, S.CH_VERSION: version,
                S.CH_INITIATED_ID: ev_id,
                S.CH_INITIATED_BATCH_ID: batch_first,
                S.CH_STARTED_ID: EMPTY_EVENT_ID, S.CH_WF_ID_HASH: a0,
                S.CH_RUN_ID_HASH: 0, S.CH_POLICY: a1,
            }
            for col in range(S.CH_N):
                wr(r + col, mask_s, vals[col])

        for_slots((E.StartChildWorkflowExecutionInitiated,),
                  caps.max_children, chd_init)

        def chd_start(s_i, mask_s):
            r = C_ + s_i * S.CH_N
            wr(r + S.CH_STARTED_ID, mask_s, ev_id)
            wr(r + S.CH_RUN_ID_HASH, mask_s, a1)

        for_slots((E.ChildWorkflowExecutionStarted,), caps.max_children,
                  chd_start)

        def chd_close(s_i, mask_s):
            r = C_ + s_i * S.CH_N
            for col in range(S.CH_N):
                wr(r + col, mask_s, 0)

        for_slots(
            (E.StartChildWorkflowExecutionFailed,
             E.ChildWorkflowExecutionCompleted,
             E.ChildWorkflowExecutionFailed,
             E.ChildWorkflowExecutionCanceled,
             E.ChildWorkflowExecutionTimedOut,
             E.ChildWorkflowExecutionTerminated),
            caps.max_children, chd_close,
        )

        # ---- pending external cancels / signals
        def rc_init(s_i, mask_s):
            r = rm.rc0 + s_i * S.RC_N
            wr(r + 0, mask_s, 1)
            wr(r + 1, mask_s, version)
            wr(r + 2, mask_s, ev_id)
            wr(r + 3, mask_s, batch_first)

        for_slots((E.RequestCancelExternalWorkflowExecutionInitiated,),
                  caps.max_request_cancels, rc_init)

        def rc_close(s_i, mask_s):
            r = rm.rc0 + s_i * S.RC_N
            for col in range(S.RC_N):
                wr(r + col, mask_s, 0)

        for_slots(
            (E.RequestCancelExternalWorkflowExecutionFailed,
             E.ExternalWorkflowExecutionCancelRequested),
            caps.max_request_cancels, rc_close,
        )

        def sg_init(s_i, mask_s):
            r = rm.sg0 + s_i * S.SG_N
            wr(r + 0, mask_s, 1)
            wr(r + 1, mask_s, version)
            wr(r + 2, mask_s, ev_id)
            wr(r + 3, mask_s, batch_first)

        for_slots((E.SignalExternalWorkflowExecutionInitiated,),
                  caps.max_signals_ext, sg_init)

        def sg_close(s_i, mask_s):
            r = rm.sg0 + s_i * S.SG_N
            for col in range(S.SG_N):
                wr(r + col, mask_s, 0)

        for_slots(
            (E.SignalExternalWorkflowExecutionFailed,
             E.ExternalWorkflowExecutionSignaled),
            caps.max_signals_ext, sg_close,
        )
        return carry

    lax.fori_loop(0, tb, step, 0)


BT = 4096  # default batch tile = one (32, 128) int32 block per row


def _phys_map(wide_cols):
    """Logical column -> physical int16 column start; wide columns
    occupy two physical columns (lo16, hi16)."""
    phys = {}
    p = 0
    for c in range(S.EV_N):
        phys[c] = p
        p += 2 if c in wide_cols else 1
    return phys, p


def narrow_events_teb(events_teb, force_wide=()):
    """Narrow an int32 [T, EV_N, B] event tensor to an int16 stream.

    The kernel is bound by streaming the event tensor from HBM (the
    empty-body ablation measures the same wall time as the full FSM —
    module docstring), so shrinking the stream's bytes is the per-tile
    throughput lever. Each column whose value span fits int16 is stored
    affine (``ev - base[c]``, base = column midrange); a wide column
    (hash-valued attributes, raw timestamps) is stored EXACTLY as two
    int16 halves (low 16 bits, high 16 bits). The kernel reconstructs
    exact int32 values either way, so the state output is bit-identical
    to the int32 path. Typical mix: 1-3 wide columns of 16 -> ~45-50%
    of the original stream bytes.

    ``force_wide``: columns stored wide regardless of this tensor's
    span. Repeat callers (the serving dispatcher) pass their running
    union so the static wide set — a jit/Mosaic specialization key —
    grows monotonically instead of flapping per batch, which would
    recompile the kernel mid-storm.

    Returns (ev16 [T, P, B] int16, base [EV_N] int32, wide_cols tuple),
    or None when EV_TYPE/EV_SLOT would be wide (they gate presence
    masks; enum-bounded in practice) — callers keep the int32 path,
    correctness never depends on narrowing.
    """
    ev = np.asarray(events_teb)
    lo = ev.min(axis=(0, 2)).astype(np.int64)
    hi = ev.max(axis=(0, 2)).astype(np.int64)
    wide_cols = tuple(sorted(set(
        int(c) for c in range(S.EV_N) if hi[c] - lo[c] > 65000
    ) | set(int(c) for c in force_wide)))
    if S.EV_TYPE in wide_cols or S.EV_SLOT in wide_cols:
        return None
    base64 = ((lo + hi) // 2)
    base64[list(wide_cols)] = 0
    phys, P = _phys_map(wide_cols)
    T, _, B = ev.shape
    out = np.empty((T, P, B), np.int16)
    # no widening staging needed: the wide lo-half is exactly the
    # two's-complement int16 truncation, and the affine subtraction
    # cannot overflow int32 (|col - base| <= ~32.5k by construction)
    for c in range(S.EV_N):
        p = phys[c]
        col = ev[:, c, :]
        if c in wide_cols:
            out[:, p, :] = col.astype(np.int16)          # low 16 bits
            out[:, p + 1, :] = (col >> 16).astype(np.int16)
        else:
            out[:, p, :] = (col - np.int32(base64[c])).astype(np.int16)
    return out, base64.astype(np.int32), wide_cols


def _replay_rows_pallas(events_teb, rows0, caps: S.Capacities,
                        tb: int, interpret: bool, bt: int = BT,
                        ablate: int = 0, presence=None, base=None,
                        wide_cols: tuple = ()):
    """Dispatch wrapper: concrete interpret-mode calls (the CPU parity
    path — tests and CPU serving, never the TPU hot path) go through a
    cached AOT lower/compile at XLA opt level 0. Interpret tracing +
    optimizing the emulated kernel costs tens of seconds per call and
    an eager invocation never hits the jit executable cache (fresh
    closure identity each call); runtime of the emulated kernel is
    negligible either way, so the optimizer pays for nothing."""
    if interpret and not any(
        isinstance(a, jax.core.Tracer)
        for a in (events_teb, rows0, presence, base)
    ):
        args = (jnp.asarray(events_teb), jnp.asarray(rows0),
                None if presence is None else jnp.asarray(presence),
                None if base is None else jnp.asarray(base))
        exe = _interp_rows_exec(
            caps, tb, bt, ablate, tuple(wide_cols),
            tuple(_avkey(a) for a in args))
        return exe(*args)
    return _replay_rows_pallas_jit(
        events_teb, rows0, caps, tb, interpret, bt, ablate, presence,
        base, tuple(wide_cols))


def _avkey(x):
    return None if x is None else (tuple(x.shape), x.dtype.name)


@functools.lru_cache(maxsize=64)
def _interp_rows_exec(caps, tb, bt, ablate, wide_cols, avkey):
    avals = [
        None if k is None else jax.ShapeDtypeStruct(k[0], k[1])
        for k in avkey
    ]
    low = _replay_rows_pallas_jit.lower(
        avals[0], avals[1], caps, tb, True, bt, ablate, avals[2],
        avals[3], wide_cols)
    return low.compile({"xla_backend_optimization_level": 0})


@functools.partial(jax.jit,
                   static_argnames=("caps", "tb", "interpret", "bt",
                                    "ablate", "wide_cols"))
def _replay_rows_pallas_jit(events_teb, rows0, caps: S.Capacities,
                            tb: int, interpret: bool, bt: int = BT,
                            ablate: int = 0, presence=None, base=None,
                            wide_cols: tuple = ()):
    """events_teb: [T, EV_N, B] int32 — or the int16 narrow stream from
    ``narrow_events_teb`` (physical layout, with ``base`` [EV_N] int32
    and the static ``wide_cols`` tuple); rows0: [R, B]. Returns [R, B].

    B must be a multiple of ``bt``; each batch tile is viewed as
    (bt//128, 128). ``tb * EV_N * bt * 4`` bytes of events are VMEM-
    resident per grid step (double-buffered by Pallas) — keep it under
    ~4MB (tb=16 at bt=4096).
    """
    if bt % 1024:
        raise ValueError(
            f"bt={bt} must be a multiple of 1024: each batch tile is viewed "
            "as (bt//128, 128) and bt//128 must be a multiple of 8 (whole "
            "int32 VPU tiles, the kernel's layout assumption)")
    narrow = events_teb.dtype == jnp.int16
    if narrow and base is None:
        raise ValueError("int16 events need their affine base vector")
    rm = RowMap(caps)
    sl = bt // 128
    T, ev_n, B = events_teb.shape
    R = rm.rows_padded
    n_bt = B // bt
    ev5 = events_teb.reshape(T, ev_n, n_bt, sl, 128)
    rows5 = rows0.reshape(R, n_bt, sl, 128)
    if base is None:
        base = jnp.zeros((ev_n,), jnp.int32)
    base2 = jnp.asarray(base, jnp.int32)[None, :]

    if presence is None:
        # per-(step, tile) event-type presence bitmask, computed in
        # parallel here so the kernel's sequential loop reads scalars
        # from SMEM. Callers that pack host-side pass it precomputed
        # (PackedHistories.presence) — the XLA reduction over the full
        # event tensor is a measurable share of replay time.
        phys, _ = _phys_map(wide_cols) if narrow else ({c: c for c in
                                                        range(S.EV_N)}, 0)
        et = ev5[:, phys[S.EV_TYPE]].astype(jnp.int32)
        slot_v = ev5[:, phys[S.EV_SLOT]].astype(jnp.int32)
        if narrow:
            et = et + base2[0, S.EV_TYPE]
            slot_v = slot_v + base2[0, S.EV_SLOT]
        et_valid = et >= 0
        word = jnp.where(et_valid, et // 32, 0)
        bit = jnp.where(et_valid, jnp.left_shift(1, et % 32), 0)
        slot_ok = et_valid & (slot_v >= 0)
        slot_bit = jnp.where(slot_ok, jnp.left_shift(1, slot_v % 32), 0)
        words = [
            lax.reduce(
                jnp.where(et_valid & (word == w), bit, 0),
                jnp.int32(0), lax.bitwise_or, (2, 3),
            )
            for w in (0, 1)
        ]
        words.append(
            lax.reduce(slot_bit, jnp.int32(0), lax.bitwise_or, (2, 3)))
        words.append(jnp.zeros_like(words[0]))
        presence = jnp.stack(words, axis=-1).astype(jnp.int32)
        presence = jnp.transpose(presence, (1, 0, 2))  # [n_bt, T, 4]

    grid = (n_bt, T // tb)
    out = pl.pallas_call(
        functools.partial(_kernel, rm=rm, tb=tb, ablate=ablate,
                          narrow=narrow, wide_cols=wide_cols),
        out_shape=jax.ShapeDtypeStruct((R, n_bt, sl, 128), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, tb, 4), lambda b, t: (b, t, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, S.EV_N), lambda b, t: (0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((tb, ev_n, 1, sl, 128),
                         lambda b, t: (t, 0, b, 0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1, sl, 128), lambda b, t: (0, b, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((R, 1, sl, 128), lambda b, t: (0, b, 0, 0),
                               memory_space=pltpu.VMEM),
        # double-buffered blocks (events x2, init x2, out x2) exceed the
        # 16MiB default scoped-vmem budget once n_bt > 1; v5e has 128MiB
        compiler_params=_CompilerParams(
            vmem_limit_bytes=100 * 1024 * 1024),
        interpret=interpret,
    )(presence, base2, ev5, rows5)
    return out.reshape(R, B)


def replay_scan_pallas_teb(
    state: S.StateTensors,
    events_teb,
    caps: S.Capacities,
    tb: int = 16,
    interpret: bool | None = None,
    bt: int = BT,
    ablate: int = 0,
    presence=None,
    base=None,
    wide_cols: tuple = (),
) -> S.StateTensors:
    """Replay on the Pallas kernel from the field-major event layout.

    events_teb: [T, EV_N, B] (``PackedHistories.teb()``) — the kernel's
    native operand layout; no device-side transpose happens here, which
    matters: at large B transposing the event tensor costs more HBM
    traffic than the entire replay scan. May be int16 with ``base``
    [EV_N] int32 (the affine narrow stream from ``narrow_events_teb`` —
    halves the HBM traffic the kernel is bound by). Pads B to a
    multiple of ``bt`` (invalid events + empty state) and T to a
    multiple of ``tb`` (invalid events are no-ops).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    events_teb = jnp.asarray(events_teb)
    narrow = events_teb.dtype == jnp.int16
    T, ev_n, B = events_teb.shape
    rm = RowMap(caps)
    b_pad = (-B) % bt
    t_pad = (-T) % tb

    if t_pad or b_pad:
        if narrow:
            # padding must reconstruct EV_TYPE == -1 through the base;
            # wide columns pad as 0 halves (reconstruct 0, and invalid
            # rows never read past the type anyway)
            phys, _ = _phys_map(wide_cols)
            pad_type = jnp.int16(-1 - int(np.asarray(base)[S.EV_TYPE]))
            fill = jnp.zeros((t_pad + T, ev_n, B + b_pad), jnp.int16)
            fill = fill.at[:, phys[S.EV_TYPE], :].set(pad_type)
        else:
            fill = jnp.zeros((t_pad + T, ev_n, B + b_pad), jnp.int32)
            fill = fill.at[:, S.EV_TYPE, :].set(-1)
        events_teb = fill.at[:T, :, :B].set(events_teb)

    if presence is not None:
        presence = jnp.asarray(presence)
        if b_pad:   # host masks don't cover the padded tiles
            presence = None
        elif t_pad:
            presence = jnp.pad(presence, ((0, 0), (0, t_pad), (0, 0)))

    rows0 = state_to_rows(state, rm)
    if b_pad:
        pad_state = S.empty_state(b_pad, caps)
        pad_state = jax.tree_util.tree_map(jnp.asarray, pad_state)
        rows0 = jnp.concatenate(
            [rows0, state_to_rows(pad_state, rm)], axis=1
        )

    rows = _replay_rows_pallas(events_teb, rows0, caps, tb, interpret, bt,
                               ablate, presence, base,
                               wide_cols=tuple(wide_cols))
    return rows_to_state(rows[:, :B], rm)


def replay_scan_pallas_packed(
    state: S.StateTensors,
    out0: S.StateTensors,
    events_teb,
    seg_end,
    out_row,
    caps: S.Capacities,
    tb: int = 16,
    interpret: bool | None = None,
    bt: int = BT,
    base=None,
    wide_cols: tuple = (),
    init: S.StateTensors | None = None,
    reset_row=None,
):
    """Lane-packed replay on the Pallas kernel (mirror of
    ops.replay.replay_scan_packed).

    The VMEM-resident kernel has no cross-lane scatter, so segment
    flush/reset happens *between* time blocks: histories must be packed
    with ``seg_align`` a multiple of ``tb`` (pack_lanes(seg_align=tb)),
    which pins every segment boundary to a block-final step. The scan
    then alternates: kernel advances one tb-step block with the lane
    tile in VMEM → XLA scatters flagged lanes' state columns into their
    output rows and resets them to empty. Relative to the unpacked
    kernel this flushes state per block instead of once per batch tile —
    the price of emitting mid-scan snapshots — while the event stream
    (the bound) is unchanged.

    ``events_teb``: [T, EV_N, L]; ``seg_end``/``out_row``: [L, T];
    ``out0``: [n_out] empty_state buffer (same contract as the XLA
    packed scan). May be the int16 narrow stream from
    ``narrow_events_teb`` (pass its ``base`` [EV_N] int32 and static
    ``wide_cols``) — exact int32 reconstruction in-kernel, bit-identical
    output, about half the event-stream bytes the kernel is bound by.

    ``init``/``reset_row``: checkpoint resume (same contract as
    ops.replay.replay_scan_packed) — ``init`` is the [n_init] initial
    carries and ``reset_row`` [L, T] indexes it at segment-end steps
    (sentinel ``n_init`` = the appended empty row); ``state`` should
    then be ``PackedLanes.lane_state0()``. Segment boundaries are
    tb-aligned, so the between-block flush/reset needs only the
    block-final column of ``reset_row``.
    Returns (final_lane_state, out).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    events_teb = jnp.asarray(events_teb)
    narrow = events_teb.dtype == jnp.int16
    if narrow and base is None:
        raise ValueError("int16 events need their affine base vector")
    T, ev_n, L = events_teb.shape
    if T % tb:
        raise ValueError(f"packed scan length {T} not a multiple of tb={tb}")
    try:  # concrete inputs only — tracers skip the host-side check
        seg_np = np.asarray(seg_end)
    except Exception:
        seg_np = None
    if seg_np is not None:
        interior = seg_np.reshape(L, T // tb, tb)[:, :, : tb - 1]
        if interior.any():
            raise ValueError(
                "segment boundaries must be tb-aligned for the Pallas "
                "packed path — pack with pack_lanes(seg_align=tb)"
            )
    rm = RowMap(caps)
    b_pad = (-L) % bt
    if b_pad:
        if narrow:
            # padding must reconstruct EV_TYPE == -1 through the base
            # (same trick as replay_scan_pallas_teb)
            phys, _ = _phys_map(wide_cols)
            pad_type = jnp.int16(-1 - int(np.asarray(base)[S.EV_TYPE]))
            fill = jnp.zeros((T, ev_n, L + b_pad), jnp.int16)
            fill = fill.at[:, phys[S.EV_TYPE], :].set(pad_type)
        else:
            fill = jnp.zeros((T, ev_n, L + b_pad), jnp.int32)
            fill = fill.at[:, S.EV_TYPE, :].set(-1)
        events_teb = fill.at[:, :, :L].set(events_teb)
        pad_state = jax.tree_util.tree_map(
            jnp.asarray, S.empty_state(b_pad, caps)
        )
        rows0 = jnp.concatenate(
            [state_to_rows(state, rm), state_to_rows(pad_state, rm)], axis=1
        )
        seg_end = jnp.concatenate(
            [jnp.asarray(seg_end),
             jnp.zeros((b_pad, T), dtype=jnp.asarray(seg_end).dtype)],
            axis=0,
        )
        out_row = jnp.concatenate(
            [jnp.asarray(out_row), jnp.zeros((b_pad, T), jnp.int32)], axis=0
        )
    else:
        rows0 = state_to_rows(state, rm)
    lb = L + b_pad
    n_out = out0.exec_info.shape[0]
    out_rows0 = state_to_rows(out0, rm)
    empty_col = state_to_rows(
        jax.tree_util.tree_map(jnp.asarray, S.empty_state(1, caps)), rm
    )
    if init is None:
        # single empty template column; every reset gathers column 0
        init_rows = empty_col
        reset_b = jnp.zeros((T // tb, lb), jnp.int32)
    else:
        if reset_row is None:
            raise ValueError("init requires reset_row")
        n_init = init.exec_info.shape[0]
        init_rows = jnp.concatenate(
            [state_to_rows(jax.tree_util.tree_map(jnp.asarray, init),
                           rm), empty_col],
            axis=1,
        )
        rr = jnp.asarray(reset_row)
        if b_pad:
            rr = jnp.concatenate(
                [rr, jnp.full((b_pad, T), n_init, jnp.int32)], axis=0
            )
        reset_b = jnp.transpose(rr[:, tb - 1 :: tb])  # [nb, lb]
    nb = T // tb
    ev_blocks = events_teb.reshape(nb, tb, ev_n, lb)
    seg_b = jnp.transpose(jnp.asarray(seg_end)[:, tb - 1 :: tb])  # [nb, lb]
    row_b = jnp.transpose(jnp.asarray(out_row)[:, tb - 1 :: tb])
    # base normalized to a concrete vector: the kernel only reads it on
    # the narrow path, and zeros reproduce the None default bit-for-bit
    base_arr = (jnp.zeros((ev_n,), jnp.int32) if base is None
                else jnp.asarray(base, jnp.int32))
    args = (ev_blocks, rows0, out_rows0, seg_b, row_b, reset_b,
            init_rows, base_arr)
    if interpret and not any(isinstance(a, jax.core.Tracer) for a in args):
        exe = _interp_packed_exec(
            caps, tb, bt, tuple(wide_cols),
            tuple(_avkey(jnp.asarray(a)) for a in args))
        rows, out = exe(*args)
    else:
        rows, out = _packed_scan_core(
            *args, caps=caps, tb=tb, bt=bt, interpret=interpret,
            wide_cols=tuple(wide_cols))
    return (
        rows_to_state(rows[:, :L], rm),
        rows_to_state(out, rm),
    )


@functools.partial(jax.jit,
                   static_argnames=("caps", "tb", "bt", "interpret",
                                    "wide_cols"))
def _packed_scan_core(ev_blocks, rows0, out_rows0, seg_b, row_b,
                      reset_b, init_rows, base, *, caps, tb, bt,
                      interpret, wide_cols):
    """The packed block scan as one stable-identity jitted computation:
    eager per-batch calls reuse the executable cache instead of
    re-tracing a fresh closure every invocation (the serving pump calls
    this once per lane-packed batch)."""
    n_out = out_rows0.shape[1]

    def body(carry, xs):
        rows, out = carry
        evb, seg, orow, rrow = xs
        rows = _replay_rows_pallas(
            evb, rows, caps, tb, interpret, bt, base=base,
            wide_cols=wide_cols,
        )

        def flush(args):
            rows, out = args
            idx = jnp.where(seg, orow, n_out)
            out = out.at[:, idx].set(rows, mode="drop")
            rows = jnp.where(seg[None, :], init_rows[:, rrow], rows)
            return rows, out

        rows, out = lax.cond(
            jnp.any(seg), flush, lambda args: args, (rows, out)
        )
        return (rows, out), None

    (rows, out), _ = jax.lax.scan(
        body, (rows0, out_rows0), (ev_blocks, seg_b, row_b, reset_b)
    )
    return rows, out


@functools.lru_cache(maxsize=64)
def _interp_packed_exec(caps, tb, bt, wide_cols, avkey):
    avals = [jax.ShapeDtypeStruct(k[0], k[1]) for k in avkey]
    low = _packed_scan_core.lower(
        *avals, caps=caps, tb=tb, bt=bt, interpret=True,
        wide_cols=wide_cols)
    return low.compile({"xla_backend_optimization_level": 0})


def replay_scan_pallas(
    state: S.StateTensors,
    events_tm,
    caps: S.Capacities,
    tb: int = 16,
    interpret: bool | None = None,
    bt: int = BT,
    ablate: int = 0,
) -> S.StateTensors:
    """Drop-in equivalent of ops.replay.replay_scan on the Pallas kernel.

    events_tm: [T, B, EV_N] (the packer's time-major layout). Transposes
    on device to the kernel's field-major layout — callers that can pack
    field-major directly should use ``replay_scan_pallas_teb`` and skip
    that cost.
    """
    events_teb = jnp.transpose(jnp.asarray(events_tm), (0, 2, 1))
    return replay_scan_pallas_teb(
        state, events_teb, caps, tb=tb, interpret=interpret, bt=bt,
        ablate=ablate,
    )


# --------------------------------------------------------------------------
# Blocked associative combine for the parallel-in-time replay
# (ops/assoc.py). Composes per-step affine updates (mul, add) into
# inclusive segmented prefixes: each grid step holds one tb-long time
# block VMEM-resident, walks it sequentially on-chip, and carries the
# running composition across blocks in scratch — the O(T) HBM traffic
# of the composition stream is paid exactly once, block by block,
# instead of lax.associative_scan's strided multi-level passes.
# --------------------------------------------------------------------------


def _affine_scan_kernel(mul_ref, add_ref, rst_ref, om_ref, oa_ref,
                        mc, ac, *, tb: int):
    """One time block: mul/add [TB, L, C], rst [TB, L]; scratch carries
    the running (mul, add) composition [L, C] across grid steps."""

    @pl.when(pl.program_id(0) == 0)
    def _():
        mc[...] = jnp.ones(mc.shape, jnp.int32)
        ac[...] = jnp.zeros(ac.shape, jnp.int32)

    def step(i, carry):
        m = mul_ref[i]
        a = add_ref[i]
        rb = (rst_ref[i] != 0)[:, None]
        # segment starts absorb the carry (the segmented combine)
        pm = jnp.where(rb, m, mc[...] * m)
        pa = jnp.where(rb, a, ac[...] * m + a)
        mc[...] = pm
        ac[...] = pa
        om_ref[i] = pm
        oa_ref[i] = pa
        return carry

    lax.fori_loop(0, tb, step, 0)


def affine_segscan_pallas(mul, add, rst, tb: int = 8,
                          interpret: bool | None = None):
    """Segmented inclusive prefix composition of affine updates.

    mul/add: [T, L, C] int32; rst: [T, L] (nonzero = step begins a new
    segment). Returns (mul, add) prefixes — bit-identical to
    ops.assoc.affine_segscan over the same stream
    (tests/test_replay_pallas.py). ``T`` must be a multiple of ``tb``.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    T, L, C = mul.shape
    if T % tb:
        raise ValueError(f"T={T} not a multiple of tb={tb}")
    grid = (T // tb,)
    om, oa = pl.pallas_call(
        functools.partial(_affine_scan_kernel, tb=tb),
        grid=grid,
        in_specs=[
            pl.BlockSpec((tb, L, C), lambda t: (t, 0, 0)),
            pl.BlockSpec((tb, L, C), lambda t: (t, 0, 0)),
            pl.BlockSpec((tb, L), lambda t: (t, 0)),
        ],
        out_specs=[
            pl.BlockSpec((tb, L, C), lambda t: (t, 0, 0)),
            pl.BlockSpec((tb, L, C), lambda t: (t, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, L, C), jnp.int32),
            jax.ShapeDtypeStruct((T, L, C), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((L, C), jnp.int32),
            pltpu.VMEM((L, C), jnp.int32),
        ],
        interpret=interpret,
    )(jnp.asarray(mul, jnp.int32), jnp.asarray(add, jnp.int32),
      jnp.asarray(rst, jnp.int32))
    return om, oa
