"""Compiled-shape grid policy — ONE place every device caller sizes
jit-specialization keys from.

Scan length, batch width, lane count, and the resume tensor's batch dim
are all jit specialization keys: every distinct value compiles a fresh
executable. The policy here bounds that set two ways:

* :func:`round_scan_len` rounds any size up to the ``{2^k, 3*2^(k-1)}``
  geometric grid (<= 2 shapes per octave, < 50% padding worst case),
  so a storm of arbitrary-sized batches — or a serving tick's arbitrary
  Δ widths — forces only a logarithmic executable set;
* :func:`staging_depth` sizes a dispatcher's staged-batch queue to the
  work that actually exists, so a one-batch caller (the common serving
  shape) doesn't allocate double-buffer headroom it can never use.

Both the storm rebuild path (ops/dispatch.py → runtime rebuild_many)
and the continuous-batching serving tick (cadence_tpu/serving/) import
their shape decisions from here — the executable-set-boundedness test
(tests/test_serving.py) pins that the two planes pick IDENTICAL grid
points for identical inputs, so they cannot drift on compiled-shape
selection.
"""

from __future__ import annotations


def round_scan_len(n: int, floor: int = 8) -> int:
    """Round ``n`` up to the {2^k, 3·2^(k-1)} geometric grid.

    Scan length and batch width are jit specialization keys: rounding
    them to this grid bounds how many executables a storm of
    arbitrary-sized batches can force (≤ 2 per octave) at < 50% padding
    worst case (just past a power of two), ~20% expected.
    """
    if n <= floor:
        return floor
    k = (n - 1).bit_length()
    p = 1 << k
    if 3 * (p >> 2) >= n:
        return 3 * (p >> 2)
    return p


def grid_points(lo: int, hi: int, floor: int = 8):
    """Every grid value in [lo, hi] — the full executable set a caller
    sweeping arbitrary sizes through :func:`round_scan_len` can compile
    (the boundedness tests enumerate against this)."""
    out = []
    n = floor
    while n <= hi:
        if n >= lo:
            out.append(n)
        # next grid point: 8, 12, 16, 24, 32, ...
        n = round_scan_len(n + 1, floor)
    return out


def staging_depth(n_batches: int, depth: int = 2) -> int:
    """Staged-batch queue depth for a dispatcher about to receive
    ``n_batches`` submissions: classic double buffering (``depth``)
    capped at the batch count — a single-batch stream (the serving /
    small-rebuild shape) gets a one-slot buffer instead of idle
    headroom sized for a storm."""
    return max(1, min(depth, max(n_batches, 1)))
