"""Parallel-in-time replay: segmented associative composition of affine
transition updates.

The sequential replay scan (ops/replay.py) pays O(T) *depth*: one
``lax.scan`` step per event, each a full pass over the state carry.
BENCH_r05 shows per-step cost is ~flat in batch width on CPU, so deep
histories (retry_deep at 1k events, ndc_storm) are bound by scan depth
alone. But the transition function is composable: for every event the
kernel's update to each state cell is an *affine* map

    x  ->  mul * x + add          (mul, add event-local, mul in {0, 1})

— plain writes are the ``mul=0`` (last-writer-wins) case, counters are
``mul=1, add=delta`` — plus two small non-diagonal algebras:

  * ``fsm``  — X_STATE's Created->Running promotion on DecisionTaskStarted
    reads the prior state. Its update set {identity, promote, const c}
    is closed under composition (promote is idempotent), so it scans as
    a 2-int (kind, value) algebra.
  * ``rle``  — the version-history add_or_update appends on version
    *change*: a run-length encoding of the version stream, recovered
    from a segmented prefix count of change flags.

Affine maps compose associatively, so a whole history collapses in
O(log T) depth. Two evaluation strategies, bit-identical to each other
and to the sequential scan (tests/test_fuzz_differential.py):

  * ``impl="segscan"`` — the direct form: Phase A emits per-column
    ``(mul, add)`` updates for every [T, L] cell, Phase B composes them
    with one segmented ``lax.associative_scan`` (segment starts absorb
    the left operand, so lane-packed histories never leak state across
    the seg-end resets of ops/pack.py).
  * ``impl="resolve"`` (default) — the factored form: because every mul
    is 0 or 1, the composed map over a segment factors into *write
    provenance* (the position of the last mul=0 writer, found with a
    per-lane ``lax.cummax`` over write positions — itself an associative
    scan) plus prefix sums of the add-stream after it (``cumsum``).
    Slot-table cells resolve the same way via scatter-max provenance
    keyed by (history, slot). This form is pure cumulative primitives +
    gathers — no per-column O(T log T) combine traffic — and is what the
    dispatcher serves.

Cross-column reads are resolved in dependency order: the one genuine
case (DecisionTask fail/timeout reads X_DECISION_TIMEOUT_VALUE, written
only by WorkflowExecutionStarted) is answered by the provenance of the
start write before the reading event; reads of columns written earlier
in the *same* step (X_CUR_VERSION) reduce to event-local values.

Events whose transition the classifier cannot prove affine
(``classify_types``) fall back to short sequential scans between
nonlinear events — ``replay_assoc`` chunks the time axis at those steps
and runs the associative path over the affine runs in between. Every
event type the current kernel handles is provably affine, so the hybrid
path is a forward-compatibility seam; the ``ASSOC-UNPROVEN`` static-
analysis rule (cadence_tpu/analysis/transition_surface.py) fails CI
when a new transition block writes a column this module's declared
coverage (``ASSOC_COVERAGE``) does not prove.

Checkpoint resume: a resumed history's snapshot row is the leading
segment element — ``init`` seeds per-segment base states x0, version-
history prefill, and slot-table base cells, exactly as the sequential
packed scan seeds lane carries from ``PackedLanes.initial``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from cadence_tpu.core.enums import (
    CloseStatus, EventType as E, WorkflowState,
    WORKFLOW_CLOSE_STATUS, decision_attempt_increment,
)
from cadence_tpu.core.ids import EMPTY_EVENT_ID, EMPTY_VERSION

from . import schema as S
from .pack import PackedLanes, round_scan_len

_CREATED = int(WorkflowState.Created)
_RUNNING = int(WorkflowState.Running)
_COMPLETED = int(WorkflowState.Completed)


# --------------------------------------------------------------------------
# Classifier: which event types the affine decomposition proves
# --------------------------------------------------------------------------

# Packable types with no kernel transition block: the preamble + version
# history still apply (they apply to EVERY valid event) and both are
# covered algebras, so these are affine by construction.
NOOP_TYPES = frozenset({
    int(E.MarkerRecorded),
    int(E.UpsertWorkflowSearchAttributes),
    int(E.RequestCancelActivityTaskFailed),
    int(E.CancelTimerFailed),
})


def assoc_types() -> frozenset:
    """Event types whose transitions this module proves affine: the
    types declared in ``ASSOC_COVERAGE`` (each backed by a derived
    update emission below) plus ``NOOP_TYPES``. Deliberately NOT
    derived from the kernel's ``_type_groups()`` — a new transition
    block is nonaffine until its coverage is declared here, so the
    runtime classifier routes it through the sequential/hybrid fallback
    while ASSOC-UNPROVEN flags the missing declaration."""
    out = set(NOOP_TYPES)
    for key in ASSOC_COVERAGE:
        out.update(key)
    return frozenset(out)


def classify_types(
    present, affine_types: Optional[frozenset] = None
) -> Tuple[Tuple[int, ...], Tuple[int, ...]]:
    """Split a batch's present event types into (affine, nonaffine)."""
    ok = affine_types if affine_types is not None else assoc_types()
    aff, non = [], []
    for t in sorted({int(t) for t in present}):
        (aff if t in ok else non).append(t)
    return tuple(aff), tuple(non)


# --------------------------------------------------------------------------
# Declared coverage for the ASSOC-UNPROVEN static-analysis rule:
# per transition group (keyed like replay._type_groups entries), the
# state labels whose updates the emission below derives. Slot tables
# are covered at table granularity (whole-row masked writes). The
# checker diffs this against the *traced* write matrix of
# replay_step_cols — a new xset in the kernel without a matching entry
# (and emission) here fails CI instead of silently diverging.
# --------------------------------------------------------------------------

_DEC_COLS = (
    "exec:X_DEC_VERSION", "exec:X_DEC_SCHEDULE_ID", "exec:X_DEC_STARTED_ID",
    "exec:X_DEC_TIMEOUT", "exec:X_DEC_ATTEMPT", "exec:X_DEC_SCHEDULED_TS",
    "exec:X_DEC_STARTED_TS", "exec:X_DEC_ORIGINAL_SCHEDULED_TS",
)

# labels written for every valid event (preamble + version history)
ASSOC_COMMON = frozenset({
    "exec:X_LAST_EVENT_TASK_ID", "exec:X_CUR_VERSION",
    "exec:X_NEXT_EVENT_ID", "exec:X_LAST_FIRST_EVENT_ID",
    "vh:event_id", "vh:version", "vh:len",
})

ASSOC_COVERAGE = {
    (int(E.WorkflowExecutionStarted),): frozenset({
        "exec:X_STATE", "exec:X_CLOSE_STATUS",
        "exec:X_LAST_PROCESSED_EVENT", "exec:X_START_TS",
        "exec:X_WORKFLOW_TIMEOUT", "exec:X_DECISION_TIMEOUT_VALUE",
        "exec:X_ATTEMPT", "exec:X_HAS_RETRY_POLICY",
        "exec:X_WF_EXPIRATION_TS", "exec:X_PARENT_INITIATED_ID",
        *_DEC_COLS,
    }),
    tuple(sorted(int(t) for t, _ in WORKFLOW_CLOSE_STATUS)): frozenset({
        "exec:X_STATE", "exec:X_CLOSE_STATUS",
        "exec:X_COMPLETION_EVENT_BATCH_ID",
    }),
    (int(E.WorkflowExecutionCancelRequested),): frozenset({
        "exec:X_CANCEL_REQUESTED",
    }),
    (int(E.WorkflowExecutionSignaled),): frozenset({
        "exec:X_SIGNAL_COUNT",
    }),
    (int(E.DecisionTaskScheduled),): frozenset(_DEC_COLS),
    (int(E.DecisionTaskStarted),): frozenset({
        "exec:X_STATE", "exec:X_DEC_VERSION", "exec:X_DEC_STARTED_ID",
        "exec:X_DEC_ATTEMPT", "exec:X_DEC_STARTED_TS",
    }),
    # completion clears the decision but KEEPS original-scheduled ts
    # (replay.py "delete decision, keep original-scheduled ts") — not
    # declaring it keeps ASSOC-UNPROVEN armed if the kernel ever starts
    # writing it here without a matching emission
    (int(E.DecisionTaskCompleted),): frozenset({
        "exec:X_LAST_PROCESSED_EVENT", *_DEC_COLS,
    }) - {"exec:X_DEC_ORIGINAL_SCHEDULED_TS"},
    tuple(sorted((int(E.DecisionTaskTimedOut), int(E.DecisionTaskFailed)))):
        frozenset(_DEC_COLS),
    (int(E.ActivityTaskScheduled),): frozenset({"activities"}),
    (int(E.ActivityTaskStarted),): frozenset({"activities"}),
    tuple(sorted(int(t) for t in (
        E.ActivityTaskCompleted, E.ActivityTaskFailed,
        E.ActivityTaskTimedOut, E.ActivityTaskCanceled,
    ))): frozenset({"activities"}),
    (int(E.ActivityTaskCancelRequested),): frozenset({"activities"}),
    (int(E.TimerStarted),): frozenset({"timers"}),
    tuple(sorted((int(E.TimerFired), int(E.TimerCanceled)))):
        frozenset({"timers"}),
    (int(E.StartChildWorkflowExecutionInitiated),): frozenset({"children"}),
    (int(E.ChildWorkflowExecutionStarted),): frozenset({"children"}),
    tuple(sorted(int(t) for t in (
        E.StartChildWorkflowExecutionFailed,
        E.ChildWorkflowExecutionCompleted, E.ChildWorkflowExecutionFailed,
        E.ChildWorkflowExecutionCanceled, E.ChildWorkflowExecutionTimedOut,
        E.ChildWorkflowExecutionTerminated,
    ))): frozenset({"children"}),
    (int(E.RequestCancelExternalWorkflowExecutionInitiated),):
        frozenset({"cancels"}),
    tuple(sorted((
        int(E.RequestCancelExternalWorkflowExecutionFailed),
        int(E.ExternalWorkflowExecutionCancelRequested),
    ))): frozenset({"cancels"}),
    (int(E.SignalExternalWorkflowExecutionInitiated),):
        frozenset({"signals"}),
    tuple(sorted((
        int(E.SignalExternalWorkflowExecutionFailed),
        int(E.ExternalWorkflowExecutionSignaled),
    ))): frozenset({"signals"}),
}


# --------------------------------------------------------------------------
# Generic segmented associative scan over affine updates (Phase B,
# direct form). Also the reference the Pallas blocked combine
# (ops/replay_pallas.py affine_segscan_pallas) mirrors.
# --------------------------------------------------------------------------


def affine_combine(a, b):
    """Compose affine updates: ``a`` earlier, ``b`` later. A set reset
    flag on ``b`` absorbs ``a`` (segment boundary)."""
    ma, aa, ra = a
    mb, ab, rb = b
    m = jnp.where(rb, mb, ma * mb)
    ad = jnp.where(rb, ab, aa * mb + ab)
    return m, ad, ra | rb


def affine_segscan(mul, add, rst, axis: int = 1):
    """Inclusive segmented prefix composition of per-step affine updates.

    mul/add: int32 with the time axis at ``axis``; rst: bool (same
    shape), True where the step begins a new segment. Returns
    (mul, add) prefix pairs; the state after step t of a segment with
    base x0 is ``mul[t]*x0+add[t]``.
    """
    m, a, _ = lax.associative_scan(
        affine_combine, (mul, add, rst), axis=axis)
    return m, a


def fsm_combine(a, b):
    """Compose X_STATE updates (kind 0=identity, 1=promote, 2=const).

    promote is Created->Running, identity elsewhere — idempotent, so the
    set {identity, promote, const c} is closed under composition."""
    ka, va, ra = a
    kb, vb, rb = b
    promoted = jnp.where(va == _CREATED, _RUNNING, va)
    k = jnp.where(kb == 2, 2, jnp.where(kb == 1, jnp.where(ka == 2, 2, 1), ka))
    v = jnp.where(kb == 2, vb, jnp.where((kb == 1) & (ka == 2), promoted, va))
    # segment boundary: b alone survives
    k = jnp.where(rb, kb, k)
    v = jnp.where(rb, vb, v)
    return k, v, ra | rb


def fsm_apply(kind, val, x0):
    promoted = jnp.where(x0 == _CREATED, _RUNNING, x0)
    return jnp.where(kind == 2, val, jnp.where(kind == 1, promoted, x0))


# --------------------------------------------------------------------------
# Shared emission helpers.
#
# Everything on-device is batch-major: the event tensor arrives as
# EV_N contiguous [L, T] column planes (``events_fm`` [EV_N, L, T]) and
# every mask/reduction runs along the minor time axis. XLA:CPU executes
# minor-axis reductions over contiguous planes ~7x faster than the
# strided per-consumer slices of a [T, L, EV_N] operand (measured on
# the retry_deep shape), and the planes come straight out of the
# packer's batch-major layout.
# --------------------------------------------------------------------------


def _or(*masks):
    out = None
    for m in masks:
        if m is None:
            continue
        out = m if out is None else (out | m)
    return out


def _mask_of(et, valid, type_set, *query):
    """[L, T] bool mask for the given event types, or None when every
    queried type is statically absent (mirrors replay_step_cols.m)."""
    if type_set is not None:
        query = [t for t in query if int(t) in type_set]
        if not query:
            return None
    out = jnp.zeros_like(valid)
    for t in query:
        out = out | (et == int(t))
    return valid & out


def _resolve(base, *cands):
    """Last-writer-wins resolution: each candidate is (t, val) with t the
    (-1 = never) position of that writer class's last write; the
    greatest t wins, base when none wrote. Write positions of distinct
    classes never tie — an event has exactly one type."""
    best_t = jnp.full(jnp.shape(base), -1, jnp.int32)
    best_v = base
    for t, v in cands:
        if t is None or v is None:
            continue
        take = t > best_t
        best_v = jnp.where(take, v, best_v)
        best_t = jnp.maximum(best_t, t)
    return best_v


def _resolve_tv(base, *cands):
    """Like _resolve but also returns the winning position (-1 = base)."""
    best_t = jnp.full(jnp.shape(base), -1, jnp.int32)
    best_v = base
    for t, v in cands:
        if t is None or v is None:
            continue
        take = t > best_t
        best_v = jnp.where(take, v, best_v)
        best_t = jnp.maximum(best_t, t)
    return best_t, best_v


class _Ctx:
    """Per-call tensors shared by the emission and resolution stages.

    ``trivial`` marks the unpacked layout (lane i == history i, one
    segment spanning the whole time axis): provenance then collapses to
    plain per-lane reductions — no scatters, no cumulative scans — which
    is the fast path the deep-history bench configs ride. The packed
    layout keeps the general segmented forms (cummax prefix + gather at
    segment ends, scatter-max keyed by history)."""

    def __init__(self, events_fm, hist_bm, seg_pos, seg_lane, seg_start,
                 init, type_set, trivial=False):
        self.evf = events_fm                     # [EV_N, L, T]
        L, T = events_fm.shape[1], events_fm.shape[2]
        self.T, self.L = T, L
        self.n_out = init.exec_info.shape[0]
        self.trivial = trivial
        self.hist = hist_bm                      # [L, T]
        self.seg_pos = seg_pos                   # [n_out]
        self.seg_lane = seg_lane
        self.seg_start = seg_start
        self.init = init
        self.valid_h = seg_pos >= 0              # [n_out] real history rows
        self.pos_c = jnp.maximum(seg_pos, 0)
        self.type_set = type_set
        self.iota_t = lax.broadcasted_iota(jnp.int32, (L, T), 1)
        self.et = events_fm[S.EV_TYPE]
        self.valid = self.et >= 0
        if trivial:
            self.sstep = None
        else:
            # per-step segment start / init gathers route through one
            # appended sentinel row (hist == n_out for padding steps)
            self.seg_start_ext = jnp.concatenate(
                [seg_start, jnp.full((1,), T, jnp.int32)]
            )
            self.sstep = self.seg_start_ext[hist_bm]      # [L, T]

    def m(self, *query):
        return _mask_of(self.et, self.valid, self.type_set, *query)

    def col(self, c):
        return self.evf[c]

    # -- history-granularity gathers ------------------------------------

    def at_end(self, arr_bm):
        """arr[seg_lane, seg_pos] with -1 for padding rows."""
        v = arr_bm[self.seg_lane, self.pos_c]
        return jnp.where(self.valid_h, v, -1)

    def ev_at(self, t, c):
        """Event column ``c`` at per-history positions ``t`` (clamped;
        callers guard with t >= 0). None-safe: a statically absent
        writer class contributes no candidate."""
        if t is None:
            return None
        return self.evf[c][self.seg_lane, jnp.maximum(t, 0)]

    def ev_at2(self, t, c):
        """Event column ``c`` at [n_out, cap] positions (clamped)."""
        return self.evf[c][self.seg_lane[:, None], jnp.maximum(t, 0)]

    # -- provenance / counter primitives, layout-specialized ------------

    def last_pos(self, mask):
        """[n_out] last write position of one writer class within each
        history's segment (-1 = never)."""
        if mask is None:
            return None
        if self.trivial:
            return jnp.max(mask * (self.iota_t + 1), axis=1) - 1
        cmx = lax.cummax(jnp.where(mask, self.iota_t, -1), axis=1)
        t = self.at_end(cmx)
        return jnp.where(t >= self.seg_start, t, -1)

    def count_after(self, mask, t_lo):
        """[n_out] events of ``mask`` in (t_lo, seg end]; t_lo=-1 counts
        the whole segment — the composed add of a mul=1 counter run."""
        if mask is None:
            return jnp.zeros_like(self.seg_pos)
        if self.trivial:
            return jnp.sum(
                mask & (self.iota_t > t_lo[:, None]), axis=1,
                dtype=jnp.int32,
            )
        cum = jnp.cumsum(mask.astype(jnp.int32), axis=1)
        lo = jnp.where(t_lo >= 0, t_lo, self.seg_start - 1)
        c_lo = jnp.where(
            lo >= 0, cum[self.seg_lane, jnp.maximum(lo, 0)], 0
        )
        c_hi = jnp.where(
            self.valid_h, cum[self.seg_lane, self.pos_c], 0
        )
        return jnp.where(self.valid_h, c_hi - c_lo, 0)

    def count_in_seg(self, mask):
        if mask is None:
            return jnp.zeros_like(self.seg_pos)
        return self.count_after(mask, jnp.full_like(self.seg_pos, -1))

    def last_before(self, mask, t_at):
        """[n_out] last write of ``mask`` strictly before position
        ``t_at`` within the same segment (-1 = none) — the dependency-
        ordered answer to a cross-column read at ``t_at``."""
        if mask is None or t_at is None:
            return None
        if self.trivial:
            return jnp.max(
                (mask & (self.iota_t < t_at[:, None]))
                * (self.iota_t + 1),
                axis=1,
            ) - 1
        cmx = lax.cummax(jnp.where(mask, self.iota_t, -1), axis=1)
        j = jnp.where(
            t_at > 0, cmx[self.seg_lane, jnp.maximum(t_at - 1, 0)], -1)
        return jnp.where(j >= self.seg_start, j, -1)

    def table_last(self, mask, slot, cap):
        """[n_out, cap] last write position of one writer class per
        (history, slot) — a one-hot slot reduction on the unpacked
        layout, scatter-max provenance keyed by history on the packed
        one."""
        if mask is None:
            return None
        if self.trivial:
            # unrolled per-slot masked reduces: XLA:CPU fuses each into
            # one contiguous minor-axis pass, ~4x faster than a 3-D
            # one-hot reduction at cap=32 (measured)
            return jnp.stack(
                [
                    jnp.max((mask & (slot == k)) * (self.iota_t + 1),
                            axis=1) - 1
                    for k in range(cap)
                ],
                axis=-1,
            )
        size = self.n_out * cap
        ok = mask & (slot >= 0) & (slot < cap) & (self.hist < self.n_out)
        flat = jnp.where(ok, self.hist * cap + slot, size)
        key = jnp.where(ok, self.iota_t, -1)
        buf = jnp.full((size + 1,), -1, jnp.int32)
        buf = buf.at[flat.reshape(-1)].max(
            key.reshape(-1), mode="promise_in_bounds"
        )
        return buf[:size].reshape(self.n_out, cap)


# --------------------------------------------------------------------------
# Exec columns — factored evaluation (impl="resolve")
# --------------------------------------------------------------------------


def _exec_resolve(cx: _Ctx):
    """Final exec_info [n_out, X_N] via write provenance + prefix sums."""
    base = cx.init.exec_info

    m_start = cx.m(E.WorkflowExecutionStarted)
    m_close = cx.m(*(t for t, _ in WORKFLOW_CLOSE_STATUS))
    m_creq = cx.m(E.WorkflowExecutionCancelRequested)
    m_sig = cx.m(E.WorkflowExecutionSignaled)
    m_dsch = cx.m(E.DecisionTaskScheduled)
    m_dsta = cx.m(E.DecisionTaskStarted)
    m_dcom = cx.m(E.DecisionTaskCompleted)
    m_dto = cx.m(E.DecisionTaskTimedOut)
    m_dfail = cx.m(E.DecisionTaskFailed)
    m_inc = m_noinc = None
    if m_dto is not None or m_dfail is not None:
        fill = jnp.zeros_like(cx.valid)
        dto = fill if m_dto is None else m_dto
        dfail = fill if m_dfail is None else m_dfail
        m_inc = decision_attempt_increment(dfail, dto, cx.col(S.EV_A0))
        m_noinc = (dto | dfail) & ~m_inc

    # write provenance per writer class: last position within each
    # history's segment
    t_v = cx.last_pos(cx.valid)
    t_start = cx.last_pos(m_start)
    t_close = cx.last_pos(m_close)
    t_creq = cx.last_pos(m_creq)
    t_dsch = cx.last_pos(m_dsch)
    t_dsta = cx.last_pos(m_dsta)
    t_dcom = cx.last_pos(m_dcom)
    t_inc = cx.last_pos(m_inc)
    t_noinc = cx.last_pos(m_noinc)

    ev_at = cx.ev_at
    out = [None] * S.X_N
    EMPTY = jnp.int32(EMPTY_EVENT_ID)
    EMPTY_V = jnp.int32(EMPTY_VERSION)
    zero = jnp.int32(0)

    def b(c):
        return base[:, c]

    # ---- preamble (every valid event)
    out[S.X_LAST_EVENT_TASK_ID] = _resolve(
        b(S.X_LAST_EVENT_TASK_ID), (t_v, ev_at(t_v, S.EV_TASK_ID)))
    out[S.X_CUR_VERSION] = _resolve(
        b(S.X_CUR_VERSION), (t_v, ev_at(t_v, S.EV_VERSION)))
    nid = ev_at(t_v, S.EV_ID)
    out[S.X_NEXT_EVENT_ID] = _resolve(
        b(S.X_NEXT_EVENT_ID), (t_v, None if nid is None else nid + 1))
    out[S.X_LAST_FIRST_EVENT_ID] = _resolve(
        b(S.X_LAST_FIRST_EVENT_ID), (t_v, ev_at(t_v, S.EV_BATCH_FIRST)))

    # ---- X_STATE (fsm): last const write, promoted iff a
    # DecisionTaskStarted landed after it (promote is idempotent)
    t_const, v_const = _resolve_tv(
        b(S.X_STATE),
        (t_start, jnp.int32(_CREATED)),
        (t_close, jnp.int32(_COMPLETED)),
    )
    if t_dsta is not None:
        promoted = jnp.where(v_const == _CREATED, _RUNNING, v_const)
        out[S.X_STATE] = jnp.where(t_dsta > t_const, promoted, v_const)
    else:
        out[S.X_STATE] = v_const

    # ---- close status
    cs = None
    if t_close is not None:
        etc = ev_at(t_close, S.EV_TYPE)
        cs = jnp.int32(0)
        for t, v in WORKFLOW_CLOSE_STATUS:
            cs = jnp.where(etc == int(t), int(v), cs)
    out[S.X_CLOSE_STATUS] = _resolve(
        b(S.X_CLOSE_STATUS),
        (t_start, jnp.int32(int(CloseStatus.NONE))),
        (t_close, cs),
    )
    out[S.X_COMPLETION_EVENT_BATCH_ID] = _resolve(
        b(S.X_COMPLETION_EVENT_BATCH_ID),
        (t_close, ev_at(t_close, S.EV_BATCH_FIRST)),
    )
    out[S.X_LAST_PROCESSED_EVENT] = _resolve(
        b(S.X_LAST_PROCESSED_EVENT),
        (t_start, EMPTY), (t_dcom, ev_at(t_dcom, S.EV_A0)),
    )

    # ---- start-only columns
    for c, a in (
        (S.X_START_TS, S.EV_TS), (S.X_WORKFLOW_TIMEOUT, S.EV_A0),
        (S.X_DECISION_TIMEOUT_VALUE, S.EV_A1), (S.X_ATTEMPT, S.EV_A2),
        (S.X_HAS_RETRY_POLICY, S.EV_A3), (S.X_WF_EXPIRATION_TS, S.EV_A4),
        (S.X_PARENT_INITIATED_ID, S.EV_A7),
    ):
        out[c] = _resolve(b(c), (t_start, ev_at(t_start, a)))

    out[S.X_CANCEL_REQUESTED] = _resolve(
        b(S.X_CANCEL_REQUESTED), (t_creq, jnp.int32(1)))

    # ---- X_SIGNAL_COUNT: counter (mul=1, add=1 per signal); the
    # composed map over a segment is base + count
    out[S.X_SIGNAL_COUNT] = b(S.X_SIGNAL_COUNT) + cx.count_in_seg(m_sig)

    # ---- decision sub-FSM columns (all mul=0 writes except the
    # attempt counter under increment)
    out[S.X_DEC_VERSION] = _resolve(
        b(S.X_DEC_VERSION),
        (t_start, EMPTY_V), (t_dsch, ev_at(t_dsch, S.EV_VERSION)),
        (t_dsta, ev_at(t_dsta, S.EV_VERSION)), (t_dcom, EMPTY_V),
        # the increment branch reads exc[X_CUR_VERSION] which the
        # preamble set to this event's version earlier in the step
        (t_inc, ev_at(t_inc, S.EV_VERSION)), (t_noinc, EMPTY_V),
    )
    out[S.X_DEC_SCHEDULE_ID] = _resolve(
        b(S.X_DEC_SCHEDULE_ID),
        (t_start, EMPTY), (t_dsch, ev_at(t_dsch, S.EV_ID)),
        (t_dcom, EMPTY), (t_inc, ev_at(t_inc, S.EV_BATCH_FIRST)),
        (t_noinc, EMPTY),
    )
    out[S.X_DEC_STARTED_ID] = _resolve(
        b(S.X_DEC_STARTED_ID),
        (t_start, EMPTY), (t_dsch, EMPTY),
        (t_dsta, ev_at(t_dsta, S.EV_ID)), (t_dcom, EMPTY),
        (t_inc, EMPTY), (t_noinc, EMPTY),
    )
    # X_DEC_TIMEOUT's increment write is the one genuine cross-column
    # read: exc[X_DECISION_TIMEOUT_VALUE] *before* the reading step =
    # the start write strictly before t_inc (same segment), else base.
    dtv_prior = None
    if t_inc is not None:
        j = cx.last_before(m_start, t_inc)
        if j is None:
            # no start events in-batch: the prior is always the base row
            dtv_prior = b(S.X_DECISION_TIMEOUT_VALUE)
        else:
            dtv_prior = jnp.where(
                j >= 0, cx.ev_at(j, S.EV_A1),
                b(S.X_DECISION_TIMEOUT_VALUE))
    out[S.X_DEC_TIMEOUT] = _resolve(
        b(S.X_DEC_TIMEOUT),
        (t_start, zero), (t_dsch, ev_at(t_dsch, S.EV_A0)),
        (t_dcom, zero), (t_inc, dtv_prior), (t_noinc, zero),
    )
    # X_DEC_ATTEMPT: last plain write + the increments after it
    t_set, set_val = _resolve_tv(
        b(S.X_DEC_ATTEMPT),
        (t_start, zero), (t_dsch, ev_at(t_dsch, S.EV_A1)),
        (t_dsta, zero), (t_dcom, zero), (t_noinc, zero),
    )
    out[S.X_DEC_ATTEMPT] = set_val + cx.count_after(m_inc, t_set)
    out[S.X_DEC_SCHEDULED_TS] = _resolve(
        b(S.X_DEC_SCHEDULED_TS),
        (t_start, zero), (t_dsch, ev_at(t_dsch, S.EV_TS)),
        (t_dcom, zero), (t_inc, ev_at(t_inc, S.EV_TS)), (t_noinc, zero),
    )
    out[S.X_DEC_STARTED_TS] = _resolve(
        b(S.X_DEC_STARTED_TS),
        (t_start, zero), (t_dsch, zero),
        (t_dsta, ev_at(t_dsta, S.EV_TS)), (t_dcom, zero),
        (t_inc, zero), (t_noinc, zero),
    )
    out[S.X_DEC_ORIGINAL_SCHEDULED_TS] = _resolve(
        b(S.X_DEC_ORIGINAL_SCHEDULED_TS),
        (t_start, zero), (t_dsch, ev_at(t_dsch, S.EV_TS)),
        (t_inc, zero), (t_noinc, zero),
    )

    exec_out = jnp.stack(out, axis=1)
    return jnp.where(cx.valid_h[:, None], exec_out, base)


# --------------------------------------------------------------------------
# Version history — rle algebra (run-length encoding of the version
# stream, recovered from a segmented prefix count of change flags)
# --------------------------------------------------------------------------


def _vh_resolve(cx: _Ctx):
    """(vh_items [n_out, V, 2], vh_len [n_out]) matching the sequential
    add_or_update semantics bit-for-bit, including the overflow write
    drop (same-version writes past capacity match no slot).

    Relies on the packer's layout contract: valid events are contiguous
    from each segment's start (padding only at segment tails), so the
    previous valid event of step t is step t-1 — a shift, not a scan.
    Every producer in the tree (pack_histories, pack_lanes, the bench
    tilers) satisfies it; the differential suites pin the equivalence.
    """
    capv = cx.init.vh_items.shape[1]
    version = cx.col(S.EV_VERSION)
    len0 = cx.init.vh_len
    seed_idx = jnp.clip(len0 - 1, 0, capv - 1)
    seed_ver = jnp.take_along_axis(
        cx.init.vh_items[:, :, 1], seed_idx[:, None], axis=1
    )[:, 0]
    has0 = len0 > 0

    # previous valid event's version (shift), seeded at segment starts
    # from the init row — what the kernel reads via vh_v[clip(len-1)]
    # (dropped overflow writes were same-version, so the fill still
    # matches that slot)
    ver_prev = jnp.concatenate(
        [jnp.zeros((cx.L, 1), jnp.int32), version[:, :-1]], axis=1)
    valid_prev = jnp.concatenate(
        [jnp.zeros((cx.L, 1), bool), cx.valid[:, :-1]], axis=1)
    if cx.trivial:
        at_start = cx.iota_t == 0
        seed_ver_step = seed_ver[:, None]
        has0_step = has0[:, None]
        len0_step = len0[:, None]
    else:
        at_start = cx.iota_t == cx.sstep
        seed_ver_ext = jnp.concatenate(
            [seed_ver, jnp.zeros((1,), jnp.int32)])
        has0_ext = jnp.concatenate([has0, jnp.zeros((1,), bool)])
        len0_ext = jnp.concatenate([len0, jnp.zeros((1,), jnp.int32)])
        seed_ver_step = seed_ver_ext[cx.hist]
        has0_step = has0_ext[cx.hist]
        len0_step = len0_ext[cx.hist]
    prev_has = jnp.where(at_start, has0_step, valid_prev)
    prev_ver = jnp.where(at_start, seed_ver_step, ver_prev)
    change = cx.valid & (~prev_has | (prev_ver != version))

    chcum = jnp.cumsum(change.astype(jnp.int32), axis=1)
    if cx.trivial:
        c_t = chcum
    else:
        chstart = jnp.where(
            cx.sstep > 0,
            jnp.take_along_axis(
                chcum, jnp.maximum(cx.sstep - 1, 0), axis=1),
            0,
        )
        c_t = chcum - chstart             # inclusive changes in segment
    widx = len0_step + c_t - 1
    widx = jnp.where(change, jnp.minimum(widx, capv - 1), widx)
    wr = cx.valid & (widx >= 0) & (widx < capv)

    # last writer per (history, vh slot) — widx is the slot stream
    t_vh = cx.table_last(wr, widx, capv)
    vh_e = jnp.where(
        t_vh >= 0, cx.ev_at2(t_vh, S.EV_ID), cx.init.vh_items[:, :, 0]
    )
    vh_v = jnp.where(
        t_vh >= 0, cx.ev_at2(t_vh, S.EV_VERSION),
        cx.init.vh_items[:, :, 1],
    )
    vh_len = len0 + cx.count_in_seg(change)
    return jnp.stack([vh_e, vh_v], axis=-1), vh_len


# --------------------------------------------------------------------------
# Slot tables — pure mul=0 (last-writer-wins) cells resolved by write
# provenance per writer class, then per-column gathers at the winning
# positions.
# --------------------------------------------------------------------------


def _stack_table(base, cols):
    """cols: list over table columns of candidate lists [(t, val), ...];
    resolves each against base[:, :, c] and stacks to [n_out, cap, N]."""
    out = []
    for c, cands in enumerate(cols):
        out.append(_resolve(base[:, :, c], *cands))
    return jnp.stack(out, axis=-1)


def _activities_resolve(cx: _Ctx):
    cap = cx.init.activities.shape[1]
    slot = cx.col(S.EV_SLOT)
    m_sch = cx.m(E.ActivityTaskScheduled)
    m_sta = cx.m(E.ActivityTaskStarted)
    m_clr = cx.m(E.ActivityTaskCompleted, E.ActivityTaskFailed,
                 E.ActivityTaskTimedOut, E.ActivityTaskCanceled)
    m_crq = cx.m(E.ActivityTaskCancelRequested)
    t_full = cx.table_last(_or(m_sch, m_clr), slot, cap)
    t_sta = cx.table_last(m_sta, slot, cap)
    t_crq = cx.table_last(m_crq, slot, cap)
    base = cx.init.activities
    if t_full is None and t_sta is None and t_crq is None:
        return base
    EMPTY = jnp.int32(EMPTY_EVENT_ID)
    fv = None
    if t_full is not None:
        sched = cx.ev_at2(t_full, S.EV_TYPE) == int(E.ActivityTaskScheduled)
        ver_f = cx.ev_at2(t_full, S.EV_VERSION)
        id_f = cx.ev_at2(t_full, S.EV_ID)
        bf_f = cx.ev_at2(t_full, S.EV_BATCH_FIRST)
        ts_f = cx.ev_at2(t_full, S.EV_TS)
        a0_f = cx.ev_at2(t_full, S.EV_A0)
        a1_f = cx.ev_at2(t_full, S.EV_A1)
        a2_f = cx.ev_at2(t_full, S.EV_A2)
        a3_f = cx.ev_at2(t_full, S.EV_A3)
        a4_f = cx.ev_at2(t_full, S.EV_A4)
        a5_f = cx.ev_at2(t_full, S.EV_A5)
        a6_f = cx.ev_at2(t_full, S.EV_A6)
        # mutableStateBuilder.go:2012-2022 expiration interval
        exp_f = jnp.where((a5_f > 0) & (a6_f > a2_f), a6_f, a2_f)

        def fv(expr):
            # scheduled writes the blend value, the close classes clear
            return jnp.where(sched, expr, 0)

    def full(expr_fn):
        return None if t_full is None else (t_full, expr_fn())

    def sta(c):
        return None if t_sta is None else (t_sta, cx.ev_at2(t_sta, c))

    def crq_v(expr_fn):
        return None if t_crq is None else (t_crq, expr_fn())

    def cands(*items):
        return [i for i in items if i is not None]

    cols = [None] * S.AC_N
    cols[S.AC_OCC] = cands(full(lambda: fv(1)))
    cols[S.AC_VERSION] = cands(
        full(lambda: fv(ver_f)), sta(S.EV_VERSION),
        crq_v(lambda: cx.ev_at2(t_crq, S.EV_VERSION)),
    )
    cols[S.AC_SCHEDULE_ID] = cands(full(lambda: fv(id_f)))
    cols[S.AC_SCHEDULED_BATCH_ID] = cands(full(lambda: fv(bf_f)))
    cols[S.AC_SCHEDULED_TS] = cands(full(lambda: fv(ts_f)))
    cols[S.AC_STARTED_ID] = cands(full(lambda: fv(EMPTY)), sta(S.EV_ID))
    cols[S.AC_STARTED_TS] = cands(full(lambda: fv(0)), sta(S.EV_TS))
    cols[S.AC_ID_HASH] = cands(full(lambda: fv(a0_f)))
    cols[S.AC_SCH_TO_START] = cands(full(lambda: fv(a1_f)))
    cols[S.AC_SCH_TO_CLOSE] = cands(full(lambda: fv(a2_f)))
    cols[S.AC_START_TO_CLOSE] = cands(full(lambda: fv(a3_f)))
    cols[S.AC_HEARTBEAT] = cands(full(lambda: fv(a4_f)))
    cols[S.AC_CANCEL_REQUESTED] = cands(
        full(lambda: fv(0)), crq_v(lambda: jnp.int32(1)))
    cols[S.AC_CANCEL_REQUEST_ID] = cands(
        full(lambda: fv(EMPTY)), crq_v(lambda: cx.ev_at2(t_crq, S.EV_ID)))
    cols[S.AC_ATTEMPT] = cands(full(lambda: fv(0)), sta(S.EV_A1))
    cols[S.AC_HAS_RETRY] = cands(full(lambda: fv(a5_f)))
    cols[S.AC_EXPIRATION_TS] = cands(full(lambda: fv(ts_f + exp_f)))
    cols[S.AC_LAST_HB_TS] = cands(full(lambda: fv(0)), sta(S.EV_TS))
    cols[S.AC_TIMER_STATUS] = cands(full(lambda: fv(0)))
    return _stack_table(base, cols)


def _timers_resolve(cx: _Ctx):
    cap = cx.init.timers.shape[1]
    slot = cx.col(S.EV_SLOT)
    t_full = cx.table_last(
        _or(cx.m(E.TimerStarted), cx.m(E.TimerFired, E.TimerCanceled)),
        slot, cap,
    )
    base = cx.init.timers
    if t_full is None:
        return base
    started = cx.ev_at2(t_full, S.EV_TYPE) == int(E.TimerStarted)

    def fv(expr):
        return jnp.where(started, expr, 0)

    cols = [None] * S.TI_N
    cols[S.TI_OCC] = [(t_full, fv(1))]
    cols[S.TI_VERSION] = [(t_full, fv(cx.ev_at2(t_full, S.EV_VERSION)))]
    cols[S.TI_STARTED_ID] = [(t_full, fv(cx.ev_at2(t_full, S.EV_ID)))]
    cols[S.TI_ID_HASH] = [(t_full, fv(cx.ev_at2(t_full, S.EV_A0)))]
    cols[S.TI_EXPIRY_TS] = [(t_full, fv(
        cx.ev_at2(t_full, S.EV_TS) + cx.ev_at2(t_full, S.EV_A1)))]
    cols[S.TI_STATUS] = [(t_full, fv(0))]
    return _stack_table(base, cols)


def _children_resolve(cx: _Ctx):
    cap = cx.init.children.shape[1]
    slot = cx.col(S.EV_SLOT)
    m_ini = cx.m(E.StartChildWorkflowExecutionInitiated)
    m_clr = cx.m(
        E.StartChildWorkflowExecutionFailed,
        E.ChildWorkflowExecutionCompleted, E.ChildWorkflowExecutionFailed,
        E.ChildWorkflowExecutionCanceled, E.ChildWorkflowExecutionTimedOut,
        E.ChildWorkflowExecutionTerminated,
    )
    t_full = cx.table_last(_or(m_ini, m_clr), slot, cap)
    t_sta = cx.table_last(cx.m(E.ChildWorkflowExecutionStarted), slot, cap)
    base = cx.init.children
    if t_full is None and t_sta is None:
        return base
    EMPTY = jnp.int32(EMPTY_EVENT_ID)
    fv = None
    if t_full is not None:
        ini = cx.ev_at2(t_full, S.EV_TYPE) == int(
            E.StartChildWorkflowExecutionInitiated)

        def fv(expr):
            return jnp.where(ini, expr, 0)

    def full(expr_fn):
        return None if t_full is None else (t_full, expr_fn())

    def sta(c):
        return None if t_sta is None else (t_sta, cx.ev_at2(t_sta, c))

    def cands(*items):
        return [i for i in items if i is not None]

    cols = [None] * S.CH_N
    cols[S.CH_OCC] = cands(full(lambda: fv(1)))
    cols[S.CH_VERSION] = cands(
        full(lambda: fv(cx.ev_at2(t_full, S.EV_VERSION))))
    cols[S.CH_INITIATED_ID] = cands(
        full(lambda: fv(cx.ev_at2(t_full, S.EV_ID))))
    cols[S.CH_INITIATED_BATCH_ID] = cands(
        full(lambda: fv(cx.ev_at2(t_full, S.EV_BATCH_FIRST))))
    cols[S.CH_STARTED_ID] = cands(full(lambda: fv(EMPTY)), sta(S.EV_ID))
    cols[S.CH_WF_ID_HASH] = cands(
        full(lambda: fv(cx.ev_at2(t_full, S.EV_A0))))
    cols[S.CH_RUN_ID_HASH] = cands(full(lambda: fv(0)), sta(S.EV_A1))
    cols[S.CH_POLICY] = cands(
        full(lambda: fv(cx.ev_at2(t_full, S.EV_A1))))
    return _stack_table(base, cols)


def _initonly_resolve(cx: _Ctx, base, init_type, *clear_types):
    """Cancels/signals: 4-column tables written by one init blend and
    cleared by the close pair."""
    cap = base.shape[1]
    slot = cx.col(S.EV_SLOT)
    t_full = cx.table_last(
        _or(cx.m(init_type), cx.m(*clear_types)), slot, cap)
    if t_full is None:
        return base
    ini = cx.ev_at2(t_full, S.EV_TYPE) == int(init_type)

    def fv(expr):
        return jnp.where(ini, expr, 0)

    cols = [
        [(t_full, fv(1))],
        [(t_full, fv(cx.ev_at2(t_full, S.EV_VERSION)))],
        [(t_full, fv(cx.ev_at2(t_full, S.EV_ID)))],
        [(t_full, fv(cx.ev_at2(t_full, S.EV_BATCH_FIRST)))],
    ]
    return _stack_table(base, cols)


# --------------------------------------------------------------------------
# Exec columns — direct segmented associative scan (impl="segscan").
# Phase A emits per-column (mul, add) for every [L, T] cell; Phase B is
# one lax.associative_scan with the segmented affine+fsm combine.
# --------------------------------------------------------------------------

AFFINE_EXEC_COLS = tuple(c for c in range(S.X_N) if c != S.X_STATE)


def _emit_affine_exec(cx: _Ctx):
    """Phase A: per-column (mul, add) affine updates [L, T, C] for
    AFFINE_EXEC_COLS, plus the fsm stream (kind, kval) for X_STATE and
    the per-step segment reset flags."""
    ev_id, version = cx.col(S.EV_ID), cx.col(S.EV_VERSION)
    ts, bf = cx.col(S.EV_TS), cx.col(S.EV_BATCH_FIRST)
    a0, a1 = cx.col(S.EV_A0), cx.col(S.EV_A1)

    m_start = cx.m(E.WorkflowExecutionStarted)
    m_close = cx.m(*(t for t, _ in WORKFLOW_CLOSE_STATUS))
    m_creq = cx.m(E.WorkflowExecutionCancelRequested)
    m_sig = cx.m(E.WorkflowExecutionSignaled)
    m_dsch = cx.m(E.DecisionTaskScheduled)
    m_dsta = cx.m(E.DecisionTaskStarted)
    m_dcom = cx.m(E.DecisionTaskCompleted)
    m_dto = cx.m(E.DecisionTaskTimedOut)
    m_dfail = cx.m(E.DecisionTaskFailed)
    m_inc = m_noinc = None
    if m_dto is not None or m_dfail is not None:
        fill = jnp.zeros_like(cx.valid)
        dto = fill if m_dto is None else m_dto
        dfail = fill if m_dfail is None else m_dfail
        m_inc = decision_attempt_increment(dfail, dto, a0)
        m_noinc = (dto | dfail) & ~m_inc

    # per-step prior of X_DECISION_TIMEOUT_VALUE for the increment
    # write: the start write strictly before this step (same segment),
    # else the init row's value — the dependency-ordered resolution of
    # the one cross-column read
    if cx.trivial:
        dtv_base_step = cx.init.exec_info[
            :, S.X_DECISION_TIMEOUT_VALUE][:, None]
    else:
        init_dtv_ext = jnp.concatenate([
            cx.init.exec_info[:, S.X_DECISION_TIMEOUT_VALUE],
            jnp.zeros((1,), jnp.int32),
        ])
        dtv_base_step = init_dtv_ext[cx.hist]
    if m_inc is not None and m_start is not None:
        cmx_start = lax.cummax(
            jnp.where(m_start, cx.iota_t, -1), axis=1)
        jst = jnp.concatenate(
            [jnp.full((cx.L, 1), -1, jnp.int32), cmx_start[:, :-1]],
            axis=1,
        )
        if not cx.trivial:
            jst = jnp.where(jst >= cx.sstep, jst, -1)
        dtv_prior = jnp.where(
            jst >= 0,
            jnp.take_along_axis(a1, jnp.maximum(jst, 0), axis=1),
            dtv_base_step,
        )
    else:
        dtv_prior = dtv_base_step

    one = jnp.ones((cx.L, cx.T), jnp.int32)
    zero2 = jnp.zeros((cx.L, cx.T), jnp.int32)
    EMPTY = jnp.int32(EMPTY_EVENT_ID)
    EMPTY_V = jnp.int32(EMPTY_VERSION)

    muls, adds = {}, {}

    def w_set(c, mask, val):
        if mask is None:
            return
        m, a = muls.get(c, one), adds.get(c, zero2)
        muls[c] = jnp.where(mask, 0, m)
        adds[c] = jnp.where(mask, val, a)

    def w_add(c, mask, delta):
        if mask is None:
            return
        a = adds.get(c, zero2)
        adds[c] = jnp.where(mask, delta, a)
        muls.setdefault(c, one)

    # preamble (every valid event)
    w_set(S.X_LAST_EVENT_TASK_ID, cx.valid, cx.col(S.EV_TASK_ID))
    w_set(S.X_CUR_VERSION, cx.valid, version)
    w_set(S.X_NEXT_EVENT_ID, cx.valid, ev_id + 1)
    w_set(S.X_LAST_FIRST_EVENT_ID, cx.valid, bf)

    # lifecycle
    w_set(S.X_CLOSE_STATUS, m_start, int(CloseStatus.NONE))
    w_set(S.X_LAST_PROCESSED_EVENT, m_start, EMPTY)
    w_set(S.X_START_TS, m_start, ts)
    w_set(S.X_WORKFLOW_TIMEOUT, m_start, a0)
    w_set(S.X_DECISION_TIMEOUT_VALUE, m_start, a1)
    w_set(S.X_ATTEMPT, m_start, cx.col(S.EV_A2))
    w_set(S.X_HAS_RETRY_POLICY, m_start, cx.col(S.EV_A3))
    w_set(S.X_WF_EXPIRATION_TS, m_start, cx.col(S.EV_A4))
    w_set(S.X_PARENT_INITIATED_ID, m_start, cx.col(S.EV_A7))
    for c in (S.X_DEC_SCHEDULE_ID, S.X_DEC_STARTED_ID):
        w_set(c, m_start, EMPTY)
    w_set(S.X_DEC_VERSION, m_start, EMPTY_V)
    for c in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT, S.X_DEC_SCHEDULED_TS,
              S.X_DEC_STARTED_TS, S.X_DEC_ORIGINAL_SCHEDULED_TS):
        w_set(c, m_start, 0)

    if m_close is not None:
        cs = zero2
        for t, v in WORKFLOW_CLOSE_STATUS:
            cs = jnp.where(cx.et == int(t), int(v), cs)
        w_set(S.X_CLOSE_STATUS, m_close, cs)
        w_set(S.X_COMPLETION_EVENT_BATCH_ID, m_close, bf)
    w_set(S.X_CANCEL_REQUESTED, m_creq, 1)
    w_add(S.X_SIGNAL_COUNT, m_sig, 1)

    # decision sub-FSM
    w_set(S.X_DEC_VERSION, m_dsch, version)
    w_set(S.X_DEC_SCHEDULE_ID, m_dsch, ev_id)
    w_set(S.X_DEC_STARTED_ID, m_dsch, EMPTY)
    w_set(S.X_DEC_TIMEOUT, m_dsch, a0)
    w_set(S.X_DEC_ATTEMPT, m_dsch, a1)
    w_set(S.X_DEC_SCHEDULED_TS, m_dsch, ts)
    w_set(S.X_DEC_ORIGINAL_SCHEDULED_TS, m_dsch, ts)
    w_set(S.X_DEC_STARTED_TS, m_dsch, 0)

    w_set(S.X_DEC_VERSION, m_dsta, version)
    w_set(S.X_DEC_STARTED_ID, m_dsta, ev_id)
    w_set(S.X_DEC_ATTEMPT, m_dsta, 0)
    w_set(S.X_DEC_STARTED_TS, m_dsta, ts)

    w_set(S.X_DEC_VERSION, m_dcom, EMPTY_V)
    w_set(S.X_DEC_SCHEDULE_ID, m_dcom, EMPTY)
    w_set(S.X_DEC_STARTED_ID, m_dcom, EMPTY)
    for c in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT, S.X_DEC_SCHEDULED_TS,
              S.X_DEC_STARTED_TS):
        w_set(c, m_dcom, 0)
    w_set(S.X_LAST_PROCESSED_EVENT, m_dcom, a0)

    # fail/timeout: increment re-schedules a transient decision, the
    # non-increment branch deletes the decision
    w_set(S.X_DEC_VERSION, m_inc, version)
    w_set(S.X_DEC_SCHEDULE_ID, m_inc, bf)
    w_set(S.X_DEC_STARTED_ID, m_inc, EMPTY)
    w_set(S.X_DEC_TIMEOUT, m_inc, dtv_prior)
    w_add(S.X_DEC_ATTEMPT, m_inc, 1)
    w_set(S.X_DEC_SCHEDULED_TS, m_inc, ts)
    w_set(S.X_DEC_STARTED_TS, m_inc, 0)
    w_set(S.X_DEC_ORIGINAL_SCHEDULED_TS, m_inc, 0)

    w_set(S.X_DEC_VERSION, m_noinc, EMPTY_V)
    w_set(S.X_DEC_SCHEDULE_ID, m_noinc, EMPTY)
    w_set(S.X_DEC_STARTED_ID, m_noinc, EMPTY)
    for c in (S.X_DEC_TIMEOUT, S.X_DEC_ATTEMPT, S.X_DEC_SCHEDULED_TS,
              S.X_DEC_STARTED_TS, S.X_DEC_ORIGINAL_SCHEDULED_TS):
        w_set(c, m_noinc, 0)

    mul = jnp.stack(
        [muls.get(c, one) for c in AFFINE_EXEC_COLS], axis=-1)
    add = jnp.stack(
        [adds.get(c, zero2) for c in AFFINE_EXEC_COLS], axis=-1)

    # fsm stream for X_STATE
    kind = zero2
    kval = zero2
    if m_start is not None:
        kind = jnp.where(m_start, 2, kind)
        kval = jnp.where(m_start, _CREATED, kval)
    if m_close is not None:
        kind = jnp.where(m_close, 2, kind)
        kval = jnp.where(m_close, _COMPLETED, kval)
    if m_dsta is not None:
        kind = jnp.where(m_dsta, 1, kind)

    if cx.trivial:
        rst = cx.iota_t == 0
    else:
        rst = cx.iota_t == cx.sstep
    return mul, add, kind, kval, rst


def _segscan_combine(a, b):
    m, ad, r = affine_combine((a[0], a[1], a[2]), (b[0], b[1], b[2]))
    k, v, r2 = fsm_combine((a[3], a[4], a[5]), (b[3], b[4], b[5]))
    return m, ad, r, k, v, r2


def _exec_segscan(cx: _Ctx):
    """Final exec_info via the direct segmented associative scan.

    On TPU the affine stream rides the blocked VMEM-resident combine
    (ops/replay_pallas.py affine_segscan_pallas); the 2-leaf fsm stream
    stays on lax.associative_scan. Elsewhere one fused associative scan
    composes both algebras."""
    mul, add, kind, kval, rst = _emit_affine_exec(cx)
    on_tpu = jax.default_backend() == "tpu"
    if on_tpu and cx.T % 8 == 0:
        from .replay_pallas import affine_segscan_pallas

        pm_t, pa_t = affine_segscan_pallas(
            jnp.transpose(mul, (1, 0, 2)), jnp.transpose(add, (1, 0, 2)),
            jnp.transpose(rst, (1, 0)),
        )
        pm = jnp.transpose(pm_t, (1, 0, 2))
        pa = jnp.transpose(pa_t, (1, 0, 2))
        pk, pv, _ = lax.associative_scan(
            fsm_combine, (kind, kval, rst), axis=1)
    else:
        rst3 = jnp.broadcast_to(rst[:, :, None], mul.shape)
        pm, pa, _, pk, pv, _ = lax.associative_scan(
            _segscan_combine, (mul, add, rst3, kind, kval, rst), axis=1
        )
    # prefix composition at each history's segment end, applied to its
    # init row
    m_end = pm[cx.seg_lane, cx.pos_c]            # [n_out, C]
    a_end = pa[cx.seg_lane, cx.pos_c]
    k_end = pk[cx.seg_lane, cx.pos_c]            # [n_out]
    v_end = pv[cx.seg_lane, cx.pos_c]
    base = cx.init.exec_info
    out = [None] * S.X_N
    for i, c in enumerate(AFFINE_EXEC_COLS):
        out[c] = m_end[:, i] * base[:, c] + a_end[:, i]
    out[S.X_STATE] = fsm_apply(k_end, v_end, base[:, S.X_STATE])
    exec_out = jnp.stack(out, axis=1)
    return jnp.where(cx.valid_h[:, None], exec_out, base)


# --------------------------------------------------------------------------
# Core + entry points
# --------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("types", "impl"))
def _assoc_core(events_fm, init, hist_bm=None, seg_pos=None,
                seg_lane=None, seg_start=None, *, types=None,
                impl="resolve"):
    """One parallel-in-time replay over field-major [EV_N, L, T] events.

    ``init``: [n_out] StateTensors — each history's segment base state
    (checkpoint resume rows become the leading segment element; padding
    rows pass through untouched). Segment geometry arrives as host
    precomputes (``assoc_aux``); when omitted, lane i is history i over
    the whole time axis (the unpacked layout, n_out == L).
    Returns [n_out] StateTensors.
    """
    T = events_fm.shape[2]
    L = events_fm.shape[1]
    n_out = init.exec_info.shape[0]
    trivial = hist_bm is None
    if trivial:
        seg_pos = jnp.full((n_out,), T - 1, jnp.int32)
        seg_lane = lax.iota(jnp.int32, n_out)
        seg_start = jnp.zeros((n_out,), jnp.int32)
    type_set = None if types is None else frozenset(types)
    cx = _Ctx(events_fm, hist_bm, seg_pos, seg_lane, seg_start, init,
              type_set, trivial=trivial)
    if impl == "segscan":
        exec_out = _exec_segscan(cx)
    else:
        exec_out = _exec_resolve(cx)
    vh_items, vh_len = _vh_resolve(cx)
    return S.StateTensors(
        exec_info=exec_out,
        activities=_activities_resolve(cx),
        timers=_timers_resolve(cx),
        children=_children_resolve(cx),
        cancels=_initonly_resolve(
            cx, cx.init.cancels,
            E.RequestCancelExternalWorkflowExecutionInitiated,
            E.RequestCancelExternalWorkflowExecutionFailed,
            E.ExternalWorkflowExecutionCancelRequested,
        ),
        signals=_initonly_resolve(
            cx, cx.init.signals,
            E.SignalExternalWorkflowExecutionInitiated,
            E.SignalExternalWorkflowExecutionFailed,
            E.ExternalWorkflowExecutionSignaled,
        ),
        vh_items=vh_items,
        vh_len=vh_len,
    )


def events_fm_of(events_bm: np.ndarray) -> np.ndarray:
    """[B, T, EV_N] batch-major events → [EV_N, B, T] field-major
    contiguous column planes (the core's operand layout; host-side, so
    the copy overlaps device work in the dispatch pipeline)."""
    return np.ascontiguousarray(np.transpose(np.asarray(events_bm),
                                             (2, 0, 1)))


def assoc_aux(packed: PackedLanes, n_out: int):
    """Host-side segment geometry for the packed layout: per-step
    history ids [L, T] (``n_out`` = padding sentinel) plus per-history
    seg-end position, lane, and segment start (seg_pos -1 marks padding
    rows of the grid-rounded output)."""
    T, L = packed.scan_len, packed.lanes
    hist = np.full((L, T), n_out, np.int32)
    seg_pos = np.full((n_out,), -1, np.int32)
    seg_lane = np.zeros((n_out,), np.int32)
    seg_start = np.zeros((n_out,), np.int32)
    for ln, segs in enumerate(packed.lane_segments):
        for row, start, end in segs:
            hist[ln, start:end] = row
            seg_pos[row] = end - 1
            seg_lane[row] = ln
            seg_start[row] = start
    return hist, seg_pos, seg_lane, seg_start


def assoc_lanes_operands(
    packed: PackedLanes, initial: Optional[S.StateTensors] = None,
):
    """Grid-rounded initial rows + segment geometry for a lane-packed
    assoc replay: ``(init, hist_bm, seg_pos, seg_lane, seg_start)``
    where ``init`` is the [n_out] numpy state seeded from ``initial``
    (default ``packed.initial``). Shared by :func:`replay_assoc_lanes`
    and the dispatcher's lanes_assoc staging so the two can't diverge."""
    if initial is None:
        initial = packed.initial
    n_out = round_scan_len(max(packed.n_histories, 1))
    init = S.empty_state(n_out, packed.caps)
    if initial is not None:
        k = min(initial.exec_info.shape[0], n_out)
        for f in S.STATE_ROW_FIELDS:
            np.asarray(getattr(init, f))[:k] = np.asarray(
                getattr(initial, f))[:k]
    return (init,) + assoc_aux(packed, n_out)


@functools.lru_cache(maxsize=None)
def _step_jit(types):
    """Jitted single sequential step for the hybrid fallback."""
    from .replay import replay_step

    return jax.jit(lambda s, e: replay_step(s, e, types))


def replay_assoc_fm(state: S.StateTensors, events_fm, types=None,
                    impl: str = "resolve") -> S.StateTensors:
    """Associative replay of a field-major [EV_N, B, T] tensor whose
    present types are all provably affine. ``state`` is the [B] initial
    carry (empty or checkpoint-resume rows)."""
    state = jax.tree_util.tree_map(jnp.asarray, state)
    return _assoc_core(
        jnp.asarray(events_fm), state, types=types, impl=impl)


def replay_assoc(state: S.StateTensors, events_tm=None, types=None,
                 affine_types: Optional[frozenset] = None,
                 impl: str = "resolve", *,
                 events_fm=None) -> S.StateTensors:
    """Chunked hybrid replay of an unpacked event tensor — time-major
    [T, B, EV_N] (``events_tm``) or the field-major [EV_N, B, T] column
    planes directly (``events_fm``; callers already holding field-major
    skip a round-trip pair of whole-tensor host transposes).

    Steps carrying only affine-provable types ride ``_assoc_core`` in
    O(log chunk) depth; a step where any lane holds a nonlinear type
    runs as one sequential ``replay_step`` between chunks. With the
    current kernel every handled type is affine, so the whole tensor is
    normally a single chunk; ``affine_types`` lets tests (and future
    nonlinear transitions) exercise the seam."""
    if (events_tm is None) == (events_fm is None):
        raise ValueError("pass exactly one of events_tm / events_fm")
    if events_fm is None:
        evf = np.ascontiguousarray(
            np.transpose(np.asarray(events_tm), (2, 1, 0)))
    else:
        evf = np.asarray(events_fm)
    et = evf[S.EV_TYPE]                                  # [B, T]
    present = [int(t) for t in np.unique(et) if t >= 0]
    _, non = classify_types(present, affine_types)
    state = jax.tree_util.tree_map(jnp.asarray, state)
    if not non:
        return _assoc_core(jnp.asarray(evf), state, types=types, impl=impl)
    nl = np.any(np.isin(et, list(non)), axis=0)          # [T]
    T = evf.shape[2]
    t = 0
    while t < T:
        if nl[t]:
            state = _step_jit(types)(
                state,
                jnp.asarray(np.ascontiguousarray(evf[:, :, t].T)),
            )
            t += 1
            continue
        e = t
        while e < T and not nl[e]:
            e += 1
        tc = round_scan_len(e - t)
        chunk = evf[:, :, t:e]
        if tc > e - t:
            pad = np.zeros(
                (evf.shape[0], evf.shape[1], tc - (e - t)), np.int32)
            pad[S.EV_TYPE] = -1
            chunk = np.concatenate([chunk, pad], axis=2)
        state = _assoc_core(
            jnp.asarray(np.ascontiguousarray(chunk)), state,
            types=types, impl=impl)
        t = e
    return state


def replay_assoc_lanes(
    packed: PackedLanes,
    initial: Optional[S.StateTensors] = None,
    specialize: bool = True,
    types=None,
    impl: str = "resolve",
) -> S.StateTensors:
    """Associative replay of a lane-packed batch; returns numpy state
    with one row per history in input order — the drop-in parallel of
    ops.replay.replay_packed_lanes. Raises ValueError when the batch
    carries a type the classifier cannot prove affine (callers fall
    back to the sequential packed scan)."""
    from .replay import type_signature

    _, non = classify_types(packed.present_types)
    if non:
        raise ValueError(
            f"non-affine event types {non} in lane-packed batch; "
            "use the sequential packed scan"
        )
    init, hist_bm, seg_pos, seg_lane, seg_start = assoc_lanes_operands(
        packed, initial)
    if types is None and specialize:
        types = type_signature(packed.present_types)
    out = _assoc_core(
        jnp.asarray(events_fm_of(packed.events)),
        jax.tree_util.tree_map(jnp.asarray, init),
        jnp.asarray(hist_bm), jnp.asarray(seg_pos),
        jnp.asarray(seg_lane), jnp.asarray(seg_start),
        types=types, impl=impl,
    )
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x)[: packed.n_histories], out
    )
