"""Double-buffered host→device replay dispatch.

SURVEY §2.8 maps the reference's intra-shard pipelining (worker pools
draining queue tasks concurrently, replicationTaskProcessor.go's
sequential batch pump) to a host→device pipeline: while the device
replays batch k, the host packs batch k+1 (the C++ sidecar scatter,
native/sidecar.cpp) and stages its event tensor for transfer. JAX's
async dispatch makes a single extra thread sufficient: ``device_put``
and the jitted replay call return immediately, so pack(k+1) runs on the
CPU while replay(k) runs on the device, and the bounded stage queue
(``depth``) provides the double-buffer backpressure.

Two storm levers ride on top of the pipeline:

* **ragged lane packing** (``lane_pack=True``): the pack pump calls
  ops/pack.pack_lanes so several whole histories share each scan lane,
  and the run pump uses the packed scan (segment-end scatter + lane
  reset) — effective scan length per history is its own depth, not
  ``max(depth)`` over the chunk;
* **depth bucketing** (``replay_stream(bucket=True)`` /
  ``depth_buckets``): histories sort into geometric depth classes
  first, so a few deep stragglers don't stretch every lane.

Batch width, scan length, and the packed scan's static event-type
signature are all rounded/grown monotonically (``round_scan_len``,
``_type_set``) so a storm of arbitrary chunk shapes compiles a bounded
set of executables.

Used by the replication rebuild path for storm-sized request streams
(runtime/replication/rebuilder.py rebuild_many) and usable standalone::

    with DeviceDispatcher(caps) as d:
        for i, batch in enumerate(batches):
            d.submit(i, batch)
        d.finish()
        for batch_id, packed, final in d.results():
            ...  # final is a device StateTensors, fetch/unpack at will
"""

from __future__ import annotations

import queue
import threading
import time as _time
from typing import Iterator, List, Optional, Sequence, Tuple

from cadence_tpu.utils.metrics import NOOP, Scope

from . import schema as S
from .grid import round_scan_len, staging_depth


def _jit_cache_total() -> int:
    """Total compiled-executable count across the replay kernels a
    dispatcher can route to (jax keeps a per-jit cache; its growth IS a
    retrace). -1 when the introspection API is unavailable — telemetry
    must degrade, never break dispatch."""
    total = 0
    try:
        from .assoc import _assoc_core
        from .replay import replay_scan_jit, replay_scan_packed_jit

        for fn in (replay_scan_jit, replay_scan_packed_jit, _assoc_core):
            size = getattr(fn, "_cache_size", None)
            if size is not None:
                total += int(size())
    except Exception:
        return -1
    return total


# retrace baseline at MODULE scope, matching the process-global jit
# caches it reads: serving builds a fresh dispatcher per rebuild_many
# call, and a per-dispatcher baseline would re-seed every call — a
# retrace storm crossing dispatcher lifetimes (the common one-batch
# serving shape) would never increment jit_retraces
_jit_baseline_lock = threading.Lock()
_jit_entries_prev: Optional[int] = None


def _jit_retrace_delta(entries: int) -> int:
    global _jit_entries_prev
    with _jit_baseline_lock:
        prev = _jit_entries_prev
        _jit_entries_prev = entries
    if prev is not None and entries > prev:
        return entries - prev
    return 0


class DispatchError(Exception):
    def __init__(self, batch_id, cause: BaseException) -> None:
        super().__init__(f"batch {batch_id}: {cause!r}")
        self.batch_id = batch_id
        self.cause = cause


def history_depth(batches) -> int:
    """Total event count of one history (its replay depth)."""
    return sum(len(b) for b in batches)


def depth_buckets(
    histories: Sequence[Tuple],
) -> List[Tuple[Tuple[int, ...], List[Tuple]]]:
    """Sort histories by depth and group them into geometric depth
    buckets (``round_scan_len`` grid), shallowest first.

    A handful of deep stragglers in a mixed batch no longer stretch
    every lane: each bucket packs lanes sized for its own depth class.
    Returns ``[(original_indices, bucket_histories), ...]`` so callers
    can reassemble results in submission order.
    """
    keyed = sorted(
        range(len(histories)),
        key=lambda i: (round_scan_len(history_depth(histories[i][2])), i),
    )
    out: List[Tuple[Tuple[int, ...], List[Tuple]]] = []
    cur_key = None
    for i in keyed:
        key = round_scan_len(history_depth(histories[i][2]))
        if key != cur_key:
            out.append(((), []))
            cur_key = key
        idxs, hs = out[-1]
        out[-1] = (idxs + (i,), hs)
        hs.append(histories[i])
    return out


class DeviceDispatcher:
    """Pipelines pack (host, C++ sidecar) → H2D → replay (device).

    depth bounds how many packed batches may be staged ahead of the
    device — 2 is classic double buffering. Results come back in
    submission order from :meth:`results`.
    """

    def __init__(
        self,
        caps: Optional[S.Capacities] = None,
        depth: int = 2,
        kernel: str = "auto",
        narrow: bool = True,
        domain_resolver=None,
        bt: int = 4096,
        tb: int = 16,
        lane_pack: bool = False,
        lane_len: Optional[int] = None,
        scan_mode: str = "auto",
        metrics: Optional[Scope] = None,
    ) -> None:
        self.caps = caps or S.Capacities()
        # device-step telemetry (utils/metrics_defs.py DEVICE_METRICS):
        # per-batch stage/step timings, padding waste, lane occupancy,
        # batch-width histogram and jit-cache growth, tagged by kernel
        # and staging mode. None OR the shared NOOP sentinel (both mean
        # "no metrics wired") disables the whole plane — the pumps
        # check one bool and skip every measurement, including the
        # block_until_ready that honest device timing needs (the run
        # pump otherwise rides async dispatch; a caller passing NOOP
        # must not pay that pipelining loss for discarded data).
        self._telemetry = metrics is not None and metrics is not NOOP
        self._metrics = (metrics if metrics is not None else NOOP).tagged(
            layer="device"
        )
        # which time-axis kernel the run pump uses:
        #   "scan"  — the sequential O(T)-depth kernels everywhere.
        #   "assoc" — the parallel-in-time associative path
        #             (ops/assoc.py) for both unpacked and lane-packed
        #             batches (lane-packed falls back per batch when a
        #             type is not provably affine).
        #   "auto"  — assoc for both unpacked AND lane-packed XLA
        #             batches when every present type is provably
        #             affine (unpacked: scan depth is the cost, ~10x on
        #             retry_deep/ndc_storm; lane-packed: the former
        #             provenance-scatter regression on shallow batches
        #             is gone — batch-major planes + the flat
        #             scatter-max provenance measure 0.3-1.0x the
        #             sequential packed scan across shallow shapes,
        #             winning past ~128 histories), sequential for the
        #             Pallas serving path on TPU.
        if scan_mode not in ("auto", "scan", "assoc"):
            raise ValueError(
                "scan_mode must be 'auto', 'scan', or 'assoc' "
                f"(got {scan_mode!r})"
            )
        self.scan_mode = scan_mode
        # threaded into pack_workflow: side-table target domains must
        # be RESOLVED ids, matching the host oracle (StateBuilder)
        self.domain_resolver = domain_resolver
        # pallas tile shape (serving deployments set the measured-best;
        # tests shrink it for interpret mode)
        self.bt, self.tb = bt, tb
        # ragged lane packing (ops/pack.py pack_lanes): several whole
        # histories per scan lane; effective scan length becomes
        # ≈ total_events / lanes instead of max(depth). lane_len is the
        # lane capacity in events (None = one history per lane density,
        # i.e. the longest history in each batch)
        self.lane_pack = lane_pack
        self.lane_len = lane_len
        # int16 narrow event stream (replay_pallas.narrow_events_teb):
        # halves both the H2D transfer and the HBM stream the kernel is
        # bound by; falls back per batch when a gating column is wide.
        # The wide set only GROWS across batches (passed as force_wide)
        # so the kernel specialization key stays stable mid-storm
        self.narrow = narrow
        self._wide_set: set = set()
        # present-event-type union across batches: the packed scan's
        # static specialization key (replay.type_signature) — grows
        # monotonically like _wide_set so it can't recompile mid-storm
        self._type_set: set = set()
        self._in: "queue.Queue" = queue.Queue()
        self._staged: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._out: "queue.Queue" = queue.Queue()
        self._kernel = kernel
        self._packer = threading.Thread(
            target=self._pack_pump, name="dispatch-pack", daemon=True
        )
        self._runner = threading.Thread(
            target=self._run_pump, name="dispatch-run", daemon=True
        )
        self._started = False
        self._finished = False
        self._drained = False

    # -- producer side --------------------------------------------------

    def submit(
        self, batch_id, histories: Sequence[Tuple], resume=None,
    ) -> None:
        """Enqueue one batch of (workflow_id, run_id, event_batches).

        ``resume``: optional per-history sequence of
        Optional[ops.pack.ResumeState] — resumed histories' events are
        their SUFFIX from the snapshot; the packed scan seeds their
        segment carries from the snapshot rows (checkpointed
        incremental replay)."""
        if not self._started:
            self._packer.start()
            self._runner.start()
            self._started = True
        self._in.put((batch_id, histories, resume))

    def finish(self) -> None:
        """No more submits; results() ends after the queued work.
        Idempotent."""
        if not self._finished:
            self._finished = True
            self._in.put(None)

    # -- pipeline stages -------------------------------------------------

    def _pack_pump(self) -> None:
        try:
            import jax
            import jax.numpy as jnp

            from .pack import pack_histories
        except Exception as e:
            # no usable jax on this host: every queued batch fails fast
            # (the rebuilder falls back per batch) instead of the pump
            # dying silently and results() hanging forever
            while True:
                item = self._in.get()
                if item is None:
                    self._staged.put(None)
                    return
                self._staged.put(DispatchError(item[0], e))

        use_pallas = self._use_pallas()
        while True:
            item = self._in.get()
            if item is None:
                self._staged.put(None)
                return
            batch_id, histories, resume = item
            try:
                t0 = _time.perf_counter()
                if self.lane_pack:
                    staged = self._pack_lanes_item(
                        batch_id, histories, use_pallas, jax, jnp,
                        resume=resume,
                    )
                else:
                    staged = self._pack_hist_item(
                        batch_id, histories, use_pallas, jax, jnp,
                        resume=resume,
                    )
                if self._telemetry:
                    self._emit_stage_telemetry(
                        staged, histories, use_pallas,
                        _time.perf_counter() - t0,
                    )
                # blocks when `depth` batches are already staged — the
                # double-buffer backpressure
                self._staged.put(staged)
            except Exception as e:
                self._staged.put(DispatchError(batch_id, e))

    def _device_scope(self, mode: str, use_pallas: bool) -> Scope:
        return self._metrics.tagged(
            kernel="pallas" if use_pallas else "xla", mode=mode,
        )

    def _emit_stage_telemetry(
        self, staged, histories, use_pallas: bool, stage_s: float,
    ) -> None:
        """Per-batch staging telemetry (pack + H2D build time, padding
        waste, lane occupancy, width histogram) — only reached when a
        metrics scope was wired (``self._telemetry``)."""
        mode, packed = staged[0], staged[2]
        scope = self._device_scope(mode, use_pallas)
        scope.inc("device_batches")
        scope.record("host_stage_seconds", stage_s)
        if mode.startswith("lanes"):
            # the packer's own waste/occupancy definitions — one source
            # of truth with bench.py and the PackedLanes properties
            padding = packed.padding_frac
            width = packed.lanes
            if packed.lanes:
                scope.gauge(
                    "lane_occupancy", packed.n_histories / packed.lanes
                )
        else:
            cells = packed.batch * packed.events.shape[1]
            real = sum(history_depth(h[2]) for h in histories)
            padding = (cells - real) / max(real, 1)
            width = packed.batch
        scope.gauge("padding_frac", padding)
        # batches counted per grid-rounded width: the compiled-
        # executable set in action (width cardinality is bounded by the
        # round_scan_len geometric grid, so the tag can't explode)
        scope.tagged(width=str(width)).inc("batch_width")

    def _emit_step_telemetry(
        self, mode: str, use_pallas: bool, final, t0: float,
    ) -> None:
        """Per-batch device-step telemetry. Blocks on ``final`` so the
        recorded duration is device time, not async-dispatch time —
        the documented cost of enabling device telemetry (the pack pump
        still overlaps; only kernel-launch pipelining is lost)."""
        try:
            import jax

            jax.block_until_ready(final)
        except Exception:
            pass
        scope = self._device_scope(mode, use_pallas)
        scope.record("device_step_seconds", _time.perf_counter() - t0)
        entries = _jit_cache_total()
        if entries >= 0:
            self._metrics.gauge("jit_cache_entries", entries)
            delta = _jit_retrace_delta(entries)
            if delta:
                self._metrics.inc("jit_retraces", delta)

    def _assoc_enabled(self, use_pallas: bool) -> bool:
        """Can any batch ride the associative kernels on this host?
        Mirrors the serving facades' gate (replay_packed /
        replay_packed_lanes): off-TPU only — a forced ``kernel="xla"``
        on a TPU host must not route the never-TPU-validated assoc
        kernel onto the TPU backend (the Pallas/TPU assoc path is an
        open ROADMAP item)."""
        if use_pallas or self.scan_mode == "scan":
            return False
        try:
            import jax

            return jax.default_backend() != "tpu"
        except Exception:
            return False

    def _assoc_hist(self, use_pallas: bool, present) -> bool:
        """Should this unpacked batch ride the associative kernel?
        ``present`` is THIS batch's type set, not the monotone
        ``_type_set`` — one batch carrying a (future) non-affine type
        must not downgrade every later affine batch in the stream."""
        if not self._assoc_enabled(use_pallas):
            return False
        from .replay import assoc_classify_types

        _, non = assoc_classify_types(present)
        return not non

    def _assoc_lanes(self, use_pallas: bool, present) -> bool:
        """Lane-packed twin of _assoc_hist: ``auto`` routes affine
        batches to the associative kernel too (mirroring the serving
        facade replay_packed_lanes — the dispatcher used to hold lanes
        back on the since-fixed shallow-batch provenance-scatter
        regression; see the scan_mode comment above)."""
        if not self._assoc_enabled(use_pallas):
            return False
        from .replay import assoc_classify_types

        _, non = assoc_classify_types(present)
        return not non

    def _pack_hist_item(self, batch_id, histories, use_pallas, jax, jnp,
                        resume=None):
        import numpy as _np

        from .pack import pack_histories

        b = len(histories)
        # grid-rounded batch: distinct stream chunk sizes would
        # otherwise each compile a fresh replay executable mid-storm
        packed = pack_histories(
            histories, caps=self.caps, pad_batch_to=round_scan_len(b),
            domain_resolver=self.domain_resolver,
            resume=resume,
        )
        # present-type scan is a full [B, T] host pass; skip it when the
        # assoc path is statically off (scan/pallas/TPU backend) —
        # _assoc_hist would ignore the result and the "hist" branch
        # replays unspecialized
        present = None
        if self._assoc_enabled(use_pallas):
            present = [
                int(t)
                for t in _np.unique(packed.events[:, :, S.EV_TYPE])
                if t >= 0
            ]
            self._type_set.update(present)
        if present is not None and self._assoc_hist(use_pallas, present):
            from .assoc import events_fm_of
            from .replay import type_signature

            # field-major column planes — the assoc kernel's operand
            # layout; built host-side so the copy overlaps device work
            events = jax.device_put(
                jnp.asarray(events_fm_of(packed.events)))
            state0 = jax.tree_util.tree_map(
                jnp.asarray,
                packed.initial if packed.initial is not None
                else S.empty_state(packed.batch, self.caps),
            )
            sig = type_signature(self._type_set)
            return ("hist_assoc", batch_id, packed, events, state0, sig, b)
        narrow_meta = None
        if use_pallas:
            teb = packed.teb()
            narrowed = None
            if self.narrow:
                from .replay_pallas import narrow_events_teb

                narrowed = narrow_events_teb(
                    teb, force_wide=tuple(sorted(self._wide_set))
                )
            if narrowed is not None:
                ev16, nbase, nwide = narrowed
                self._wide_set.update(nwide)
                events = jax.device_put(jnp.asarray(ev16))
                narrow_meta = (nbase, nwide)
            else:
                events = jax.device_put(jnp.asarray(teb))
        else:
            events = jax.device_put(jnp.asarray(packed.time_major()))
        # checkpoint resume seeds the initial carries; padding rows of
        # packed.initial are empty_state, so the grid pad is unchanged
        state0 = jax.tree_util.tree_map(
            jnp.asarray,
            packed.initial if packed.initial is not None
            else S.empty_state(packed.batch, self.caps),
        )
        return ("hist", batch_id, packed, events, narrow_meta, state0, b)

    def _pack_lanes_item(self, batch_id, histories, use_pallas, jax, jnp,
                         resume=None):
        from .pack import pack_lanes
        from .replay import type_signature

        packed = pack_lanes(
            histories, caps=self.caps, target_lane_len=self.lane_len,
            seg_align=self.tb if use_pallas else 1,
            domain_resolver=self.domain_resolver,
            resume=resume,
        )
        self._type_set.update(packed.present_types)
        sig = type_signature(self._type_set)
        if self._assoc_lanes(use_pallas, packed.present_types):
            from .assoc import assoc_lanes_operands, events_fm_of

            init, hist_bm, seg_pos, seg_lane, seg_start = (
                assoc_lanes_operands(packed))
            arrays = (
                jax.device_put(jnp.asarray(events_fm_of(packed.events))),
                jnp.asarray(hist_bm), jnp.asarray(seg_pos),
                jnp.asarray(seg_lane), jnp.asarray(seg_start),
            )
            init = jax.tree_util.tree_map(jnp.asarray, init)
            return ("lanes_assoc", batch_id, packed, arrays, init, sig)
        narrow_meta = None
        if use_pallas:
            teb = packed.teb()
            narrowed = None
            if self.narrow:
                from .replay_pallas import narrow_events_teb

                narrowed = narrow_events_teb(
                    teb, force_wide=tuple(sorted(self._wide_set))
                )
            if narrowed is not None:
                ev16, nbase, nwide = narrowed
                self._wide_set.update(nwide)
                events = jax.device_put(jnp.asarray(ev16))
                narrow_meta = (nbase, nwide)
            else:
                events = jax.device_put(jnp.asarray(teb))
            arrays = (
                events,
                jnp.asarray(packed.seg_end),
                jnp.asarray(packed.out_row),
            )
        else:
            ev_tm, seg_tm, row_tm = packed.time_major()
            arrays = (
                jax.device_put(jnp.asarray(ev_tm)),
                jnp.asarray(seg_tm),
                jnp.asarray(row_tm),
            )
        # checkpoint resume: lanes whose first segment resumes seed from
        # the snapshot row; segment-end resets gather the NEXT segment's
        # initial row via the reset table (ops/replay.replay_scan_packed)
        state0 = jax.tree_util.tree_map(
            jnp.asarray, packed.lane_state0()
        )
        resume_extra = None
        if packed.initial is not None:
            import numpy as _np

            reset = packed.reset_rows()                       # [L, T]
            resume_extra = (
                jax.tree_util.tree_map(jnp.asarray, packed.initial),
                jnp.asarray(reset),
                jnp.asarray(_np.ascontiguousarray(reset.T)),  # [T, L]
            )
        out0 = jax.tree_util.tree_map(
            jnp.asarray,
            S.empty_state(round_scan_len(packed.n_histories), self.caps),
        )
        return (
            "lanes", batch_id, packed, arrays, state0, out0, sig,
            narrow_meta, resume_extra,
        )

    def _run_pump(self) -> None:
        use_pallas = self._use_pallas()
        while True:
            item = self._staged.get()
            if item is None:
                self._out.put(None)
                return
            if isinstance(item, DispatchError):
                self._out.put(item)
                continue
            mode, batch_id = item[0], item[1]
            try:
                t0 = _time.perf_counter()
                if mode == "hist_assoc":
                    _, _, packed, events, state0, sig, b = item
                    from .assoc import _assoc_core

                    final = _assoc_core(events, state0, types=sig)
                    if b < packed.batch:
                        import jax

                        final = jax.tree_util.tree_map(
                            lambda x: x[:b], final
                        )
                elif mode == "lanes_assoc":
                    _, _, packed, arrays, init, sig = item
                    from .assoc import _assoc_core

                    evf, hist_bm, seg_pos, seg_lane, seg_start = arrays
                    final = _assoc_core(
                        evf, init, hist_bm, seg_pos, seg_lane,
                        seg_start, types=sig,
                    )
                    import jax

                    final = jax.tree_util.tree_map(
                        lambda x: x[: packed.n_histories], final
                    )
                elif mode == "lanes":
                    (_, _, packed, arrays, state0, out0, sig,
                     narrow_meta, resume_extra) = item
                    if use_pallas:
                        from .replay_pallas import replay_scan_pallas_packed

                        nbase, nwide = (
                            narrow_meta if narrow_meta is not None
                            else (None, ())
                        )
                        kw = {}
                        if resume_extra is not None:
                            kw = dict(init=resume_extra[0],
                                      reset_row=resume_extra[1])
                        _, final = replay_scan_pallas_packed(
                            state0, out0, *arrays, self.caps,
                            tb=self.tb, bt=self.bt, base=nbase,
                            wide_cols=nwide, **kw,
                        )
                    else:
                        from .replay import replay_scan_packed_jit

                        kw = {}
                        if resume_extra is not None:
                            kw = dict(init=resume_extra[0],
                                      reset_row_tm=resume_extra[2])
                        _, final = replay_scan_packed_jit(
                            state0, out0, *arrays, types=sig, **kw
                        )
                    import jax

                    final = jax.tree_util.tree_map(
                        lambda x: x[: packed.n_histories], final
                    )
                else:
                    _, _, packed, events, narrow_meta, state0, b = item
                    if use_pallas:
                        from .replay_pallas import replay_scan_pallas_teb

                        nbase, nwide = (
                            narrow_meta if narrow_meta is not None
                            else (None, ())
                        )
                        final = replay_scan_pallas_teb(
                            state0, events, self.caps, base=nbase,
                            wide_cols=nwide, bt=self.bt, tb=self.tb,
                        )
                    else:
                        from .replay import replay_scan_jit

                        # the jitted form donates state0's buffer and
                        # skips per-batch retracing on this hot
                        # storm-drain path
                        final = replay_scan_jit(state0, events)
                    if b < packed.batch:
                        import jax

                        # grid padding is an implementation detail; the
                        # consumer sees exactly its submitted batch
                        final = jax.tree_util.tree_map(
                            lambda x: x[:b], final
                        )
                # async dispatch: the call returns while the device
                # works; the next H2D/pack proceeds immediately
                # (telemetry mode trades that for honest step timing)
                if self._telemetry:
                    self._emit_step_telemetry(mode, use_pallas, final, t0)
                self._out.put((batch_id, packed, final))
            except Exception as e:
                self._out.put(DispatchError(batch_id, e))

    def _use_pallas(self) -> bool:
        if self._kernel == "auto":
            try:
                import jax

                return jax.default_backend() == "tpu"
            except Exception:
                return False
        return self._kernel == "pallas"

    # -- consumer side ----------------------------------------------------

    def results(self, strict: bool = True) -> Iterator[Tuple]:
        """Yields (batch_id, packed, final_state) in submission order.

        A failed batch raises its DispatchError when its turn comes
        (strict, default) or is yielded as the DispatchError itself
        (strict=False) so the caller can fall back per batch and keep
        consuming. On a strict raise the remaining staged/out queues are
        drained in the background first — the consumer abandons the
        iterator at the raise, and without the drain the pack pump
        could block forever on a full ``_staged`` queue.
        """
        while True:
            item = self._out.get()
            if item is None:
                self._drained = True
                return
            if isinstance(item, DispatchError):
                if strict:
                    self._drain_async()
                    raise item
                yield item
                continue
            yield item

    def _drain_async(self) -> None:
        """Consume everything still in flight on a daemon thread so the
        pumps run to completion and exit; idempotent."""
        if self._drained:
            return
        self._drained = True
        self.finish()

        def _run() -> None:
            while self._out.get() is not None:
                pass

        threading.Thread(
            target=_run, name="dispatch-drain", daemon=True
        ).start()

    def __enter__(self) -> "DeviceDispatcher":
        return self

    def __exit__(self, *exc) -> None:
        if not self._started or self._drained:
            return
        self.finish()
        # drain so the pumps exit even on abnormal exit
        while self._out.get() is not None:
            pass
        self._drained = True


def replay_stream(
    histories: Sequence[Tuple],
    caps: Optional[S.Capacities] = None,
    batch_size: int = 4096,
    depth: int = 2,
    kernel: str = "auto",
    lane_pack: bool = False,
    lane_len: Optional[int] = None,
    bucket: bool = False,
    resume: Optional[Sequence] = None,
    scan_mode: str = "auto",
    metrics: Optional[Scope] = None,
) -> List[Tuple]:
    """Replay a large history stream through the pipelined dispatcher.

    Splits ``histories`` into ``batch_size`` chunks and returns
    [(packed, final_state), ...] in order — the storm-drain entry the
    replication rebuilder uses.

    ``bucket=True`` (implies lane packing) sorts the stream into
    geometric depth buckets first, so mixed-depth storms don't pad every
    lane to the deepest straggler; the return value then carries the
    original indices per batch: [(indices, packed, final_state), ...]
    where row j of ``final_state`` is history ``indices[j]``.

    ``resume``: optional per-history Optional[ops.pack.ResumeState]
    aligned with ``histories`` — resumed entries carry their event
    SUFFIX and replay from the snapshot row (checkpointed incremental
    replay); a resumed run buckets by its suffix depth.
    """
    out: List[Tuple] = []
    resume = list(resume) if resume is not None else [None] * len(histories)
    if len(resume) != len(histories):
        raise ValueError("resume list must align with histories")
    any_resume = any(r is not None for r in resume)
    if bucket:
        # plan the chunking FIRST so the staging queue is sized to the
        # batches that exist (staging_depth) — a one-chunk stream (the
        # common serving / small-rebuild shape) must not allocate
        # double-buffer headroom it can never use
        plan: List[Tuple] = []
        for idxs, hs in depth_buckets(histories):
            for j in range(0, len(hs), batch_size):
                plan.append((idxs[j : j + batch_size],
                             hs[j : j + batch_size]))
        if not plan:
            return out
        d = DeviceDispatcher(
            caps=caps, depth=staging_depth(len(plan), depth),
            kernel=kernel, lane_pack=True,
            lane_len=lane_len, scan_mode=scan_mode, metrics=metrics,
        )
        for sub, hs in plan:
            d.submit(
                sub, hs,
                resume=[resume[i] for i in sub] if any_resume else None,
            )
        d.finish()
        for idxs, packed, final in d.results():
            out.append((idxs, packed, final))
        return out
    if not histories:
        return out
    n_batches = -(-len(histories) // batch_size)
    d = DeviceDispatcher(
        caps=caps, depth=staging_depth(n_batches, depth), kernel=kernel,
        lane_pack=lane_pack,
        lane_len=lane_len, scan_mode=scan_mode, metrics=metrics,
    )
    for i in range(0, len(histories), batch_size):
        d.submit(
            i, histories[i : i + batch_size],
            resume=(
                resume[i : i + batch_size] if any_resume else None
            ),
        )
    d.finish()
    for _, packed, final in d.results():
        out.append((packed, final))
    return out
