"""Double-buffered host→device replay dispatch.

SURVEY §2.8 maps the reference's intra-shard pipelining (worker pools
draining queue tasks concurrently, replicationTaskProcessor.go's
sequential batch pump) to a host→device pipeline: while the device
replays batch k, the host packs batch k+1 (the C++ sidecar scatter,
native/sidecar.cpp) and stages its event tensor for transfer. JAX's
async dispatch makes a single extra thread sufficient: ``device_put``
and the jitted replay call return immediately, so pack(k+1) runs on the
CPU while replay(k) runs on the device, and the bounded stage queue
(``depth``) provides the double-buffer backpressure.

Used by the replication rebuild path for storm-sized request streams
(runtime/replication/rebuilder.py rebuild_many) and usable standalone::

    with DeviceDispatcher(caps) as d:
        for i, batch in enumerate(batches):
            d.submit(i, batch)
        d.finish()
        for batch_id, packed, final in d.results():
            ...  # final is a device StateTensors, fetch/unpack at will
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

from . import schema as S


class DispatchError(Exception):
    def __init__(self, batch_id, cause: BaseException) -> None:
        super().__init__(f"batch {batch_id}: {cause!r}")
        self.batch_id = batch_id
        self.cause = cause


class DeviceDispatcher:
    """Pipelines pack (host, C++ sidecar) → H2D → replay (device).

    depth bounds how many packed batches may be staged ahead of the
    device — 2 is classic double buffering. Results come back in
    submission order from :meth:`results`.
    """

    def __init__(
        self,
        caps: Optional[S.Capacities] = None,
        depth: int = 2,
        kernel: str = "auto",
        narrow: bool = True,
        domain_resolver=None,
        bt: int = 4096,
        tb: int = 16,
    ) -> None:
        self.caps = caps or S.Capacities()
        # threaded into pack_workflow: side-table target domains must
        # be RESOLVED ids, matching the host oracle (StateBuilder)
        self.domain_resolver = domain_resolver
        # pallas tile shape (serving deployments set the measured-best;
        # tests shrink it for interpret mode)
        self.bt, self.tb = bt, tb
        # int16 narrow event stream (replay_pallas.narrow_events_teb):
        # halves both the H2D transfer and the HBM stream the kernel is
        # bound by; falls back per batch when a gating column is wide.
        # The wide set only GROWS across batches (passed as force_wide)
        # so the kernel specialization key stays stable mid-storm
        self.narrow = narrow
        self._wide_set: set = set()
        self._in: "queue.Queue" = queue.Queue()
        self._staged: "queue.Queue" = queue.Queue(maxsize=max(1, depth))
        self._out: "queue.Queue" = queue.Queue()
        self._kernel = kernel
        self._packer = threading.Thread(
            target=self._pack_pump, name="dispatch-pack", daemon=True
        )
        self._runner = threading.Thread(
            target=self._run_pump, name="dispatch-run", daemon=True
        )
        self._started = False
        self._finished = False
        self._drained = False

    # -- producer side --------------------------------------------------

    def submit(self, batch_id, histories: Sequence[Tuple]) -> None:
        """Enqueue one batch of (workflow_id, run_id, event_batches)."""
        if not self._started:
            self._packer.start()
            self._runner.start()
            self._started = True
        self._in.put((batch_id, histories))

    def finish(self) -> None:
        """No more submits; results() ends after the queued work.
        Idempotent."""
        if not self._finished:
            self._finished = True
            self._in.put(None)

    # -- pipeline stages -------------------------------------------------

    def _pack_pump(self) -> None:
        try:
            import jax
            import jax.numpy as jnp

            from .pack import pack_histories
        except Exception as e:
            # no usable jax on this host: every queued batch fails fast
            # (the rebuilder falls back per batch) instead of the pump
            # dying silently and results() hanging forever
            while True:
                item = self._in.get()
                if item is None:
                    self._staged.put(None)
                    return
                self._staged.put(DispatchError(item[0], e))

        use_pallas = self._use_pallas()
        while True:
            item = self._in.get()
            if item is None:
                self._staged.put(None)
                return
            batch_id, histories = item
            try:
                packed = pack_histories(
                    histories, caps=self.caps,
                    domain_resolver=self.domain_resolver,
                )
                narrow_meta = None
                if use_pallas:
                    teb = packed.teb()
                    narrowed = None
                    if self.narrow:
                        from .replay_pallas import narrow_events_teb

                        narrowed = narrow_events_teb(
                            teb, force_wide=tuple(sorted(self._wide_set))
                        )
                    if narrowed is not None:
                        ev16, nbase, nwide = narrowed
                        self._wide_set.update(nwide)
                        events = jax.device_put(jnp.asarray(ev16))
                        narrow_meta = (nbase, nwide)
                    else:
                        events = jax.device_put(jnp.asarray(teb))
                else:
                    events = jax.device_put(
                        jnp.asarray(packed.time_major())
                    )
                state0 = jax.tree_util.tree_map(
                    jnp.asarray,
                    S.empty_state(packed.batch, self.caps),
                )
                # blocks when `depth` batches are already staged — the
                # double-buffer backpressure
                self._staged.put(
                    (batch_id, packed, events, narrow_meta, state0)
                )
            except Exception as e:
                self._staged.put(DispatchError(batch_id, e))

    def _run_pump(self) -> None:
        use_pallas = self._use_pallas()
        while True:
            item = self._staged.get()
            if item is None:
                self._out.put(None)
                return
            if isinstance(item, DispatchError):
                self._out.put(item)
                continue
            batch_id, packed, events, narrow_meta, state0 = item
            try:
                if use_pallas:
                    from .replay_pallas import replay_scan_pallas_teb

                    nbase, nwide = (
                        narrow_meta if narrow_meta is not None
                        else (None, ())
                    )
                    final = replay_scan_pallas_teb(
                        state0, events, self.caps, base=nbase,
                        wide_cols=nwide, bt=self.bt, tb=self.tb,
                    )
                else:
                    from .replay import replay_scan_jit

                    # the jitted form donates state0's buffer and skips
                    # per-batch retracing on this hot storm-drain path
                    final = replay_scan_jit(state0, events)
                # async dispatch: the call returns while the device
                # works; the next H2D/pack proceeds immediately
                self._out.put((batch_id, packed, final))
            except Exception as e:
                self._out.put(DispatchError(batch_id, e))

    def _use_pallas(self) -> bool:
        if self._kernel == "auto":
            try:
                import jax

                return jax.default_backend() == "tpu"
            except Exception:
                return False
        return self._kernel == "pallas"

    # -- consumer side ----------------------------------------------------

    def results(self, strict: bool = True) -> Iterator[Tuple]:
        """Yields (batch_id, packed, final_state) in submission order.

        A failed batch raises its DispatchError when its turn comes
        (strict, default) or is yielded as the DispatchError itself
        (strict=False) so the caller can fall back per batch and keep
        consuming.
        """
        while True:
            item = self._out.get()
            if item is None:
                self._drained = True
                return
            if isinstance(item, DispatchError):
                if strict:
                    raise item
                yield item
                continue
            yield item

    def __enter__(self) -> "DeviceDispatcher":
        return self

    def __exit__(self, *exc) -> None:
        if not self._started or self._drained:
            return
        self.finish()
        # drain so the pumps exit even on abnormal exit
        while self._out.get() is not None:
            pass
        self._drained = True


def replay_stream(
    histories: Sequence[Tuple],
    caps: Optional[S.Capacities] = None,
    batch_size: int = 4096,
    depth: int = 2,
    kernel: str = "auto",
) -> List[Tuple]:
    """Replay a large history stream through the pipelined dispatcher.

    Splits ``histories`` into ``batch_size`` chunks and returns
    [(packed, final_state), ...] in order — the storm-drain entry the
    replication rebuilder uses.
    """
    out: List[Tuple] = []
    d = DeviceDispatcher(caps=caps, depth=depth, kernel=kernel)
    n = 0
    for i in range(0, len(histories), batch_size):
        d.submit(i, histories[i : i + batch_size])
        n += 1
    if n == 0:
        return out
    d.finish()
    for _, packed, final in d.results():
        out.append((packed, final))
    return out
