"""Vectorized task refresh: outstanding queue tasks from final state.

Device twin of cadence_tpu/core/task_refresher.py (itself the twin of the
reference's mutableStateTaskRefresher). Runs as a jitted post-pass after
the replay scan, so the rebuild pipeline — scan → refresh — stays on
device; outputs are compact int32 arrays the host hydrates into
TransferTask/TimerTask records (sentinel -1 = absent).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from cadence_tpu.core.enums import (
    TimeoutType,
    TimerTaskType,
    TransferTaskType,
    WorkflowState,
)
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core import tasks as T
from cadence_tpu.core.mutable_state import SECOND

from . import schema as S
from .pack import PackedHistories

_BIG = jnp.int32(2**31 - 1)


@dataclasses.dataclass
class RefreshedTasks:
    """Compact task arrays; -1 marks absent entries."""

    close_transfer: Any          # [B] bool
    workflow_timeout_ts: Any     # [B] int32 (-1 if closed)
    decision_transfer: Any       # [B] schedule_id or -1
    decision_timer: Any          # [B, 3] (vis_ts, schedule_id, attempt) or -1s
    activity_transfer: Any       # [B, A] schedule_id or -1
    activity_timer: Any          # [B, 5] (vis_ts, timeout_type, schedule_id, attempt, version) or -1s
    user_timer: Any              # [B, 3] (vis_ts, started_id, version) or -1s
    child_transfer: Any          # [B, C] initiated_id or -1
    cancel_transfer: Any         # [B, RC] initiated_id or -1
    signal_transfer: Any         # [B, SG] initiated_id or -1
    # [B] bool: running, no pending decision, first decision not yet
    # processed — hydrate applies the side table's backoff deadline to
    # re-arm the WorkflowBackoffTimer (host twin: task_refresher)
    first_decision_pending: Any = None
    # [B] relative start ts (device encoding) — hydrate computes the
    # backoff extension of the timeout window from it
    start_ts: Any = None

    def tree_flatten(self):
        return (
            (
                self.close_transfer, self.workflow_timeout_ts,
                self.decision_transfer, self.decision_timer,
                self.activity_transfer, self.activity_timer, self.user_timer,
                self.child_transfer, self.cancel_transfer, self.signal_transfer,
                self.first_decision_pending, self.start_ts,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    RefreshedTasks, lambda s: s.tree_flatten(), RefreshedTasks.tree_unflatten
)


def refresh_tasks_device(state: S.StateTensors) -> RefreshedTasks:
    ex = state.exec_info
    running = (ex[:, S.X_STATE] == int(WorkflowState.Created)) | (
        ex[:, S.X_STATE] == int(WorkflowState.Running)
    )
    neg1 = jnp.int32(-1)

    close_transfer = ~running
    workflow_timeout_ts = jnp.where(
        running, ex[:, S.X_START_TS] + ex[:, S.X_WORKFLOW_TIMEOUT], neg1
    )

    has_pending_dec = running & (ex[:, S.X_DEC_SCHEDULE_ID] != EMPTY_EVENT_ID)
    decision_transfer = jnp.where(has_pending_dec, ex[:, S.X_DEC_SCHEDULE_ID], neg1)
    inflight = has_pending_dec & (ex[:, S.X_DEC_STARTED_ID] > 0)
    decision_timer = jnp.stack([
        jnp.where(inflight, ex[:, S.X_DEC_STARTED_TS] + ex[:, S.X_DEC_TIMEOUT], neg1),
        jnp.where(inflight, ex[:, S.X_DEC_SCHEDULE_ID], neg1),
        jnp.where(inflight, ex[:, S.X_DEC_ATTEMPT], neg1),
    ], axis=-1)

    # activity transfer: occupied & unstarted
    acts = state.activities
    a_occ = acts[:, :, S.AC_OCC] > 0
    a_unstarted = a_occ & (acts[:, :, S.AC_STARTED_ID] == EMPTY_EVENT_ID)
    activity_transfer = jnp.where(
        a_unstarted & running[:, None], acts[:, :, S.AC_SCHEDULE_ID], neg1
    )

    # activity timeout argmin over (slot, kind) candidates — mirrors
    # TimerSequence._activity_timeout_candidates ordering (expiry,
    # schedule_id, timeout_type)
    started = a_occ & (acts[:, :, S.AC_STARTED_ID] != EMPTY_EVENT_ID)
    sched_ts = acts[:, :, S.AC_SCHEDULED_TS]
    cands = []  # (armed, expiry, timeout_type)
    cands.append((
        a_unstarted & (acts[:, :, S.AC_SCH_TO_START] > 0),
        sched_ts + acts[:, :, S.AC_SCH_TO_START],
        int(TimeoutType.ScheduleToStart),
    ))
    cands.append((
        a_unstarted & (acts[:, :, S.AC_SCH_TO_CLOSE] > 0),
        sched_ts + acts[:, :, S.AC_SCH_TO_CLOSE],
        int(TimeoutType.ScheduleToClose),
    ))
    cands.append((
        started & (acts[:, :, S.AC_SCH_TO_CLOSE] > 0),
        sched_ts + acts[:, :, S.AC_SCH_TO_CLOSE],
        int(TimeoutType.ScheduleToClose),
    ))
    cands.append((
        started & (acts[:, :, S.AC_START_TO_CLOSE] > 0),
        acts[:, :, S.AC_STARTED_TS] + acts[:, :, S.AC_START_TO_CLOSE],
        int(TimeoutType.StartToClose),
    ))
    cands.append((
        started & (acts[:, :, S.AC_HEARTBEAT] > 0),
        acts[:, :, S.AC_LAST_HB_TS] + acts[:, :, S.AC_HEARTBEAT],
        int(TimeoutType.Heartbeat),
    ))
    # lexicographic argmin on (expiry, schedule_id, timeout_type): exact
    # two-stage reductions (min expiry, then min schedule_id among ties)
    best = None
    for armed, expiry, tt in cands:
        armed = armed & running[:, None]
        expiry_m = jnp.where(armed, expiry, _BIG)
        sid_m = jnp.where(armed, acts[:, :, S.AC_SCHEDULE_ID], _BIG)
        k_exp = jnp.min(expiry_m, axis=1)
        sid_tie = jnp.where(expiry_m == k_exp[:, None], sid_m, _BIG)
        k_sid = jnp.min(sid_tie, axis=1)
        winner = sid_tie == k_sid[:, None]  # [B, A] unique occupied slot
        k_attempt = jnp.max(
            jnp.where(winner, acts[:, :, S.AC_ATTEMPT], 0), axis=1
        )
        k_version = jnp.max(
            jnp.where(winner, acts[:, :, S.AC_VERSION], jnp.int32(-(2**31))), axis=1
        )
        key = (k_exp, k_sid, jnp.full_like(k_exp, tt), k_attempt, k_version)
        if best is None:
            best = key
        else:
            better = (key[0] < best[0]) | (
                (key[0] == best[0]) & (key[1] < best[1])
            ) | (
                (key[0] == best[0]) & (key[1] == best[1]) & (key[2] < best[2])
            )
            best = tuple(jnp.where(better, k, b) for k, b in zip(key, best))
    a_exp, a_sid, a_tt, a_att, a_ver = best
    has_at = a_exp < _BIG
    activity_timer = jnp.stack([
        jnp.where(has_at, a_exp, neg1),
        jnp.where(has_at, a_tt, neg1),
        jnp.where(has_at, a_sid, neg1),
        jnp.where(has_at, a_att, neg1),
        jnp.where(has_at, a_ver, neg1),
    ], axis=-1)

    # earliest user timer (expiry, started_id)
    tmr = state.timers
    t_occ = (tmr[:, :, S.TI_OCC] > 0) & running[:, None]
    t_exp = jnp.where(t_occ, tmr[:, :, S.TI_EXPIRY_TS], _BIG)
    t_sid = jnp.where(t_occ, tmr[:, :, S.TI_STARTED_ID], _BIG)
    u_exp = jnp.min(t_exp, axis=1)
    sid_tie = jnp.where(t_exp == u_exp[:, None], t_sid, _BIG)
    u_sid = jnp.min(sid_tie, axis=1)
    u_ver = jnp.max(
        jnp.where(sid_tie == u_sid[:, None], tmr[:, :, S.TI_VERSION],
                  jnp.int32(-(2**31))),
        axis=1,
    )
    has_ut = u_exp < _BIG
    user_timer = jnp.stack([
        jnp.where(has_ut, u_exp, neg1),
        jnp.where(has_ut, u_sid, neg1),
        jnp.where(has_ut, u_ver, neg1),
    ], axis=-1)

    ch = state.children
    ch_pending = (ch[:, :, S.CH_OCC] > 0) & (
        ch[:, :, S.CH_STARTED_ID] == EMPTY_EVENT_ID
    ) & running[:, None]
    child_transfer = jnp.where(ch_pending, ch[:, :, S.CH_INITIATED_ID], neg1)

    rc = state.cancels
    cancel_transfer = jnp.where(
        (rc[:, :, S.RC_OCC] > 0) & running[:, None],
        rc[:, :, S.RC_INITIATED_ID], neg1,
    )
    sg = state.signals
    signal_transfer = jnp.where(
        (sg[:, :, S.SG_OCC] > 0) & running[:, None],
        sg[:, :, S.SG_INITIATED_ID], neg1,
    )

    first_decision_pending = (
        running
        & (ex[:, S.X_DEC_SCHEDULE_ID] == EMPTY_EVENT_ID)
        & (ex[:, S.X_LAST_PROCESSED_EVENT] < 1)
    )
    return RefreshedTasks(
        close_transfer=close_transfer,
        workflow_timeout_ts=workflow_timeout_ts,
        decision_transfer=decision_transfer,
        decision_timer=decision_timer,
        activity_transfer=activity_transfer,
        activity_timer=activity_timer,
        user_timer=user_timer,
        child_transfer=child_transfer,
        cancel_transfer=cancel_transfer,
        signal_transfer=signal_transfer,
        first_decision_pending=first_decision_pending,
        start_ts=ex[:, S.X_START_TS],
    )


refresh_tasks_device_jit = jax.jit(refresh_tasks_device)


def refreshed_to_numpy(refreshed: RefreshedTasks) -> RefreshedTasks:
    """One device→host transfer for the whole batch; do this once before
    hydrating workflows in a loop."""
    return jax.tree_util.tree_map(np.asarray, refreshed)


def hydrate_tasks(
    refreshed: RefreshedTasks, b: int, packed: PackedHistories, domain_id: str = ""
) -> Tuple[List[T.TransferTask], List[T.TimerTask]]:
    """Expand workflow ``b``'s compact arrays into task records, in the same
    deterministic order as core.task_refresher.refresh_tasks."""
    r = refreshed
    if not isinstance(r.close_transfer, np.ndarray):
        r = refreshed_to_numpy(r)
    epoch_s = packed.epoch_s

    def vis_ns(rel: int) -> int:
        # inverse of the packer's epoch rebasing (pack.py rel_ts)
        return (rel + epoch_s - 1) * SECOND
    side = packed.side[b]
    transfer: List[T.TransferTask] = []
    timer: List[T.TimerTask] = []

    if r.close_transfer[b]:
        transfer.append(T.close_execution_transfer_task())
        return transfer, timer

    # a pending first-decision backoff extends the timeout window and
    # re-arms the backoff timer, exactly like the host twin
    # (core/task_refresher.py)
    deadline = side.first_decision_backoff_deadline
    backoff_extra = 0
    if deadline and r.start_ts is not None:
        start_ns = vis_ns(int(np.asarray(r.start_ts)[b]))
        backoff_extra = max(0, deadline - start_ns)
    timer.append(T.TimerTask(
        task_type=TimerTaskType.WorkflowTimeout,
        visibility_timestamp=vis_ns(int(r.workflow_timeout_ts[b]))
        + backoff_extra,
    ))
    if (
        deadline
        and r.first_decision_pending is not None
        and bool(np.asarray(r.first_decision_pending)[b])
    ):
        timer.append(T.TimerTask(
            task_type=TimerTaskType.WorkflowBackoffTimer,
            visibility_timestamp=deadline,
        ))
    if r.decision_transfer[b] != -1:
        transfer.append(T.decision_transfer_task(
            domain_id, side.task_list, int(r.decision_transfer[b])
        ))
        if r.decision_timer[b][0] != -1:
            vis, sid, attempt = (int(x) for x in r.decision_timer[b])
            timer.append(T.TimerTask(
                task_type=TimerTaskType.DecisionTimeout,
                visibility_timestamp=vis_ns(vis),
                timeout_type=int(TimeoutType.StartToClose),
                event_id=sid,
                schedule_attempt=attempt,
            ))
    sids = sorted(int(x) for x in r.activity_transfer[b] if x != -1)
    slot_by_sid = {}
    for slot, x in enumerate(r.activity_transfer[b]):
        if x != -1:
            slot_by_sid[int(x)] = slot
    for sid in sids:
        transfer.append(T.activity_transfer_task(
            domain_id, side.activity_task_lists.get(slot_by_sid[sid], ""), sid
        ))
    if r.activity_timer[b][0] != -1:
        vis, tt, sid, attempt, ver = (int(x) for x in r.activity_timer[b])
        timer.append(T.TimerTask(
            task_type=TimerTaskType.ActivityTimeout,
            visibility_timestamp=vis_ns(vis),
            timeout_type=tt,
            event_id=sid,
            schedule_attempt=attempt,
            version=ver,
        ))
    if r.user_timer[b][0] != -1:
        vis, sid, ver = (int(x) for x in r.user_timer[b])
        timer.append(T.TimerTask(
            task_type=TimerTaskType.UserTimer,
            visibility_timestamp=vis_ns(vis),
            event_id=sid,
            version=ver,
        ))
    def _by_initiated(row):
        """(initiated_id, slot) pairs in initiated order — one linear
        pass instead of a next()-rescan per emitted task."""
        return sorted(
            (int(x), s) for s, x in enumerate(row) if x != -1
        )

    for init, slot in _by_initiated(r.child_transfer[b]):
        transfer.append(T.start_child_transfer_task(
            side.child_domains.get(slot, ""),
            side.child_workflow_ids.get(slot, ""), init,
        ))
    for init, slot in _by_initiated(r.cancel_transfer[b]):
        tgt = side.cancel_targets.get(slot) or ("", "", "", False)
        transfer.append(T.cancel_external_transfer_task(
            tgt[0] or domain_id, tgt[1], tgt[2], tgt[3], init,
        ))
    for init, slot in _by_initiated(r.signal_transfer[b]):
        tgt = side.signal_targets.get(slot) or ("", "", "", False)
        transfer.append(T.signal_external_transfer_task(
            tgt[0] or domain_id, tgt[1], tgt[2], tgt[3], init,
        ))
    return transfer, timer
