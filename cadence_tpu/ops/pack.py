"""Pack workflow histories into dense event tensors for device replay.

The packer is the host half of the replay-kernel contract
(cadence_tpu/ops/replay.py). Like a tokenizer, it precomputes everything
that is string- or hash-keyed so the device never chases pointers:

  * **slot assignment**: every pending-map entry (activity / timer / child /
    external cancel / external signal) gets a fixed slot index for its
    lifetime; events that touch an entry carry the slot in ``EV_SLOT``.
    Slot allocation is deterministic (lowest free slot) so replays are
    reproducible. This mirrors the reference's map keys
    (pendingActivityInfoIDs by schedule ID, pendingTimerInfoIDs by timer
    ID, … mutableStateBuilder.go:68-133) without on-device hashing.
  * **batch boundaries**: ``EV_BATCH_FIRST`` carries the first event ID of
    each transaction batch (the reference applies history batch-at-a-time,
    nDCStateRebuilder.go:103-137; batch structure drives
    scheduled_event_batch_id / completion_event_batch_id / transient
    decision schedule IDs).
  * **validation**: malformed histories (orphan completions, double fires,
    slot overflow) are rejected here with the same strictness as the host
    oracle, so the kernel can assume well-formed input.

Histories whose pending sets exceed `Capacities` raise
``PackOverflowError`` — callers route those to the host replay path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from cadence_tpu.core.enums import EventType, TimeoutType
from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.utils.hashing import hash31

from . import schema as S

SECONDS = 1_000_000_000  # ns per second
_INT32_MAX = 2**31 - 1

# the shared compiled-shape policy (ops/grid.py) — re-exported because
# every packer caller historically imported the grid from here
from .grid import round_scan_len  # noqa: E402,F401


class PackError(Exception):
    """History cannot be packed (malformed event stream)."""


class PackOverflowError(PackError):
    """History exceeds slot-table capacities — route to host replay."""


@dataclasses.dataclass
class PackResume:
    """Packer continuation state at a history cut point.

    Everything ``pack_workflow`` tracks host-side while walking a
    history — slot assignments, the live decision, version bookkeeping —
    captured so packing can continue from an event suffix exactly as if
    the whole history had been packed in one call. Stored alongside the
    device state row by the checkpoint subsystem
    (cadence_tpu/checkpoint/); attached to every
    :class:`WorkflowSideTable` as ``side.resume`` after packing.
    """

    next_event_id: int = 0
    last_version: Optional[int] = None
    version_changes: int = 0
    pending_dec: Optional[int] = None
    # the epoch the matching state row's timestamps are relative to
    epoch_s: int = 0
    activity_slots: Dict[int, int] = dataclasses.field(default_factory=dict)
    acts_by_name: Dict[str, int] = dataclasses.field(default_factory=dict)
    timer_slots: Dict[str, int] = dataclasses.field(default_factory=dict)
    child_slots: Dict[int, int] = dataclasses.field(default_factory=dict)
    cancel_slots: Dict[int, int] = dataclasses.field(default_factory=dict)
    signal_slots: Dict[int, int] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe form: int-keyed maps become [key, slot] pair lists
        (JSON object keys are strings; round-tripping through str keys
        would silently break slot seeding)."""
        d = {
            "next_event_id": self.next_event_id,
            "last_version": self.last_version,
            "version_changes": self.version_changes,
            "pending_dec": self.pending_dec,
            "epoch_s": self.epoch_s,
        }
        for f in ("activity_slots", "acts_by_name", "timer_slots",
                  "child_slots", "cancel_slots", "signal_slots"):
            d[f] = [[k, v] for k, v in getattr(self, f).items()]
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PackResume":
        out = cls(
            next_event_id=int(d["next_event_id"]),
            last_version=(
                None if d.get("last_version") is None
                else int(d["last_version"])
            ),
            version_changes=int(d.get("version_changes", 0)),
            pending_dec=(
                None if d.get("pending_dec") is None
                else int(d["pending_dec"])
            ),
            epoch_s=int(d.get("epoch_s", 0)),
        )
        for f in ("activity_slots", "timer_slots", "child_slots",
                  "cancel_slots", "signal_slots", "acts_by_name"):
            setattr(out, f, {k: int(v) for k, v in d.get(f, [])})
        return out


@dataclasses.dataclass
class WorkflowSideTable:
    """Host-side strings for one workflow, keyed by slot — merged back into
    snapshots by ops/unpack.py. Strings never influence transitions."""

    workflow_id: str = ""
    run_id: str = ""
    request_id: str = ""
    task_list: str = ""
    workflow_type: str = ""
    cron_schedule: str = ""
    parent_domain: str = ""
    parent_workflow_id: str = ""
    parent_run_id: str = ""
    memo: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    search_attributes: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    continued_execution_run_id: str = ""
    # auto reset points (first completed decision per worker binary) —
    # derived here at pack time so device rebuilds agree with the host
    # oracle's replicate path (mutable_state MAX_RESET_POINTS cap)
    auto_reset_points: List[Dict] = dataclasses.field(default_factory=list)
    # first-decision backoff deadline (ns) for cron/retry continued runs
    first_decision_backoff_deadline: int = 0
    # slot → (domain, workflow_id, run_id, child_only) for pending
    # external cancels/signals: the task refresher needs full targets
    cancel_targets: Dict[int, tuple] = dataclasses.field(default_factory=dict)
    signal_targets: Dict[int, tuple] = dataclasses.field(default_factory=dict)
    # slot → strings
    activity_ids: Dict[int, str] = dataclasses.field(default_factory=dict)
    activity_task_lists: Dict[int, str] = dataclasses.field(default_factory=dict)
    timer_ids: Dict[int, str] = dataclasses.field(default_factory=dict)
    child_domains: Dict[int, str] = dataclasses.field(default_factory=dict)
    child_workflow_ids: Dict[int, str] = dataclasses.field(default_factory=dict)
    child_run_ids: Dict[int, str] = dataclasses.field(default_factory=dict)
    child_types: Dict[int, str] = dataclasses.field(default_factory=dict)
    # packer continuation state at the end of this history — what a
    # checkpoint needs to resume packing from here (set by pack_workflow)
    resume: Optional["PackResume"] = None

    _SLOT_DICT_FIELDS = (
        "cancel_targets", "signal_targets", "activity_ids",
        "activity_task_lists", "timer_ids", "child_domains",
        "child_workflow_ids", "child_run_ids", "child_types",
    )

    def duplicate(self) -> "WorkflowSideTable":
        """Independent copy — resuming a pack must not mutate the stored
        checkpoint's side table. Generic over the dataclass fields so a
        future field cannot be silently dropped from resumed packs."""
        out = WorkflowSideTable()
        for f in dataclasses.fields(self):
            if f.name == "resume":
                continue  # the copy is about to be re-packed
            v = getattr(self, f.name)
            if isinstance(v, dict):
                v = dict(v)
            elif isinstance(v, list):
                v = [dict(p) if isinstance(p, dict) else p for p in v]
            setattr(out, f.name, v)
        return out

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe form (slot-keyed maps as pair lists, target tuples
        as lists) — the checkpoint record's side-table encoding."""
        d = {
            f.name: getattr(self, f.name)
            for f in dataclasses.fields(self)
            if f.name not in self._SLOT_DICT_FIELDS
            and f.name not in ("resume", "memo", "search_attributes",
                               "auto_reset_points")
        }
        d["memo"] = dict(self.memo)
        d["search_attributes"] = dict(self.search_attributes)
        d["auto_reset_points"] = [dict(p) for p in self.auto_reset_points]
        for f in self._SLOT_DICT_FIELDS:
            d[f] = [[k, list(v) if isinstance(v, tuple) else v]
                    for k, v in getattr(self, f).items()]
        d["resume"] = self.resume.to_dict() if self.resume else None
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "WorkflowSideTable":
        out = cls(
            workflow_id=d.get("workflow_id", ""),
            run_id=d.get("run_id", ""),
            request_id=d.get("request_id", ""),
            task_list=d.get("task_list", ""),
            workflow_type=d.get("workflow_type", ""),
            cron_schedule=d.get("cron_schedule", ""),
            parent_domain=d.get("parent_domain", ""),
            parent_workflow_id=d.get("parent_workflow_id", ""),
            parent_run_id=d.get("parent_run_id", ""),
            memo=dict(d.get("memo") or {}),
            search_attributes=dict(d.get("search_attributes") or {}),
            continued_execution_run_id=d.get(
                "continued_execution_run_id", ""),
            auto_reset_points=[dict(p) for p in
                               d.get("auto_reset_points") or []],
            first_decision_backoff_deadline=int(
                d.get("first_decision_backoff_deadline", 0)),
        )
        for f in ("cancel_targets", "signal_targets"):
            setattr(out, f, {
                int(k): (v[0], v[1], v[2], bool(v[3]))
                for k, v in d.get(f, [])
            })
        for f in ("activity_ids", "activity_task_lists", "timer_ids",
                  "child_domains", "child_workflow_ids", "child_run_ids",
                  "child_types"):
            setattr(out, f, {int(k): v for k, v in d.get(f, [])})
        if d.get("resume") is not None:
            out.resume = PackResume.from_dict(d["resume"])
        return out


@dataclasses.dataclass
class ResumeState:
    """Everything needed to pack + replay a history from a cut point:
    the packer continuation (``pack``), the side table accumulated over
    the prefix (``side``), and the device state row at the cut
    (``state_row``, schema.state_row form, timestamps relative to
    ``pack.epoch_s``). Built from checkpoint records by
    cadence_tpu/checkpoint/manager.py."""

    pack: PackResume
    side: WorkflowSideTable
    state_row: Dict[str, Any]


@dataclasses.dataclass
class PackedHistories:
    """Batched event tensors + host side tables.

    All on-device timestamps are seconds relative to ``epoch_s`` with a +1
    offset (0 stays the "unset" sentinel): abs_s = rel + epoch_s - 1. The
    rebasing keeps every `ts + timeout` sum far from int32 overflow.
    """

    events: np.ndarray        # [B, T, EV_N] int32
    lengths: np.ndarray       # [B] int32 — valid event count per row
    side: List[WorkflowSideTable]
    caps: S.Capacities
    epoch_s: int = 0
    # concatenated valid rows ([sum(lengths), EV_N]) kept for the native
    # sidecar's fused pad+layout path; None when constructed externally
    rows_concat: Optional[np.ndarray] = None
    # [B] StateTensors of initial carries (checkpoint resume): row i
    # seeds history i's replay instead of empty_state; None = all empty
    initial: Optional[Any] = None

    @property
    def batch(self) -> int:
        return self.events.shape[0]

    def time_major(self) -> np.ndarray:
        """[T, B, EV_N] — the layout lax.scan consumes. Uses the C++
        sidecar's fused scatter when the packed rows are available."""
        if self.rows_concat is not None:
            from cadence_tpu.native import scatter_time_major

            return scatter_time_major(
                self.rows_concat, self.lengths, self.caps.max_events
            )
        return np.ascontiguousarray(np.transpose(self.events, (1, 0, 2)))

    def teb(self) -> np.ndarray:
        """[T, EV_N, B] field-major — the Pallas replay kernel's native
        operand layout (ops/replay_pallas.py). Produced by the C++
        sidecar's fused scatter so the replay path never pays a
        device-side transpose of the event tensor."""
        if self.rows_concat is not None:
            from cadence_tpu.native import scatter_teb

            return scatter_teb(
                self.rows_concat, self.lengths, self.caps.max_events
            )
        return np.ascontiguousarray(np.transpose(self.events, (1, 2, 0)))

    def presence(self, bt: int) -> Optional[np.ndarray]:
        """[B/bt, T, 4] per-(batch-tile, step) presence bitmasks for the
        Pallas kernel (ops/replay_pallas.py). None when the batch is not
        a multiple of ``bt`` (the kernel then computes them on device)."""
        if self.rows_concat is None or len(self.lengths) % bt:
            return None
        from cadence_tpu.native import presence_masks

        return presence_masks(
            self.rows_concat, self.lengths, self.caps.max_events, bt
        )


# Bounds guaranteeing every on-device `rel_ts + timeout` sum fits int32:
# relative timestamps span < 2^28 s (~8.5 years of history) and individual
# timeout fields < 2^30 s (~34 years).
MAX_REL_TS = 2**28
MAX_TIMEOUT_S = 2**30


class _SlotTable:
    """Deterministic lowest-free-slot allocator keyed by an id.

    ``seed`` (a key → slot map from :class:`PackResume`) restores the
    allocator to a mid-history state so a resumed pack assigns the same
    slots a full pack would have."""

    def __init__(self, capacity: int, kind: str,
                 seed: Optional[Dict[Any, int]] = None) -> None:
        self.capacity = capacity
        self.kind = kind
        self.by_key: Dict[Any, int] = {}
        self.free: List[int] = list(range(capacity))  # kept sorted
        if seed:
            slots = list(seed.values())
            if len(set(slots)) != len(slots):
                raise PackError(f"resume {kind} slots collide: {seed}")
            for slot in slots:
                if not 0 <= slot < capacity:
                    raise PackOverflowError(
                        f"resume {kind} slot {slot} exceeds capacity "
                        f"{capacity}"
                    )
            self.by_key = dict(seed)
            used = set(slots)
            self.free = [s for s in range(capacity) if s not in used]

    def alloc(self, key: Any) -> int:
        if not self.free:
            raise PackOverflowError(
                f"pending {self.kind} capacity {self.capacity} exceeded"
            )
        slot = self.free.pop(0)
        self.by_key[key] = slot
        return slot

    def get(self, key: Any) -> Optional[int]:
        return self.by_key.get(key)

    def release(self, key: Any) -> int:
        if key not in self.by_key:
            raise PackError(f"unknown {self.kind} key {key!r}")
        slot = self.by_key.pop(key)
        # insert keeping order (capacities are small)
        i = 0
        while i < len(self.free) and self.free[i] < slot:
            i += 1
        self.free.insert(i, slot)
        return slot


def _timeout(a: Dict[str, Any], key: str) -> int:
    v = a.get(key, 0) or 0
    if not (0 <= v < MAX_TIMEOUT_S):
        raise PackError(f"timeout {key}={v} out of range")
    return int(v)


def pack_workflow(
    batches: Sequence[Sequence[HistoryEvent]],
    caps: S.Capacities,
    workflow_id: str = "",
    run_id: str = "",
    request_id: str = "",
    epoch_s: Optional[int] = None,
    domain_resolver=None,
    resume: Optional[ResumeState] = None,
) -> Tuple[np.ndarray, WorkflowSideTable]:
    """Pack one workflow's history (a sequence of transaction batches) into
    an [n_events, EV_N] int32 array + its side table.

    ``epoch_s``: shared batch epoch (defaults to this workflow's first
    event); all timestamps become rel = abs_s - epoch_s + 1.

    ``domain_resolver``: name -> domain id, applied to child/cancel/
    signal TARGET domains captured into the side table — the host
    oracle (StateBuilder) stores RESOLVED ids, and the transfer-task
    consumers look targets up by id; storing raw names here would make
    device rebuilds emit tasks whose cross-domain target can't be
    found.

    ``resume``: continue packing from a checkpoint — ``batches`` is then
    the event SUFFIX (first event id must equal the resume point's
    next_event_id); slot tables, the side table, and version/decision
    bookkeeping seed from the snapshot so slot assignment and
    validation behave exactly as a full pack. The returned side's
    ``resume`` field always carries the END state, so checkpoints
    compose across successive resumes."""

    if resume is not None:
        side = resume.side.duplicate()
        side.workflow_id = workflow_id or side.workflow_id
        side.run_id = run_id or side.run_id
        if request_id:
            side.request_id = request_id
    else:
        side = WorkflowSideTable(
            workflow_id=workflow_id, run_id=run_id, request_id=request_id
        )
    side.resume = None
    resolve_domain = domain_resolver or (lambda name: name)
    if epoch_s is None:
        first = next((b[0] for b in batches if b), None)
        if first is not None:
            epoch_s = first.timestamp // SECONDS
        elif resume is not None:
            epoch_s = resume.pack.epoch_s
        else:
            epoch_s = 0

    def rel_ts(ns: int) -> int:
        s = ns // SECONDS - epoch_s + 1
        if not (1 <= s < MAX_REL_TS):
            # a representability limit, not malformed input: the host
            # oracle replays such histories fine, so route them there
            raise PackOverflowError(
                f"timestamp {ns} out of packable window (epoch {epoch_s})"
            )
        return int(s)
    rp = resume.pack if resume is not None else PackResume()
    acts = _SlotTable(caps.max_activities, "activity",
                      seed=rp.activity_slots)
    acts_by_name: Dict[str, int] = dict(rp.acts_by_name)
    timers = _SlotTable(caps.max_timers, "timer", seed=rp.timer_slots)
    children = _SlotTable(caps.max_children, "child", seed=rp.child_slots)
    cancels = _SlotTable(caps.max_request_cancels, "request-cancel",
                         seed=rp.cancel_slots)
    signals = _SlotTable(caps.max_signals_ext, "external-signal",
                         seed=rp.signal_slots)

    rows: List[List[int]] = []
    n_events = sum(len(b) for b in batches)
    if n_events > caps.max_events:
        raise PackOverflowError(
            f"history length {n_events} exceeds max_events {caps.max_events}"
        )

    version_changes = rp.version_changes
    last_version: Optional[int] = rp.last_version
    next_event_id: Optional[int] = (
        rp.next_event_id if resume is not None else None
    )
    # decision schedule id currently pending
    pending_dec: Optional[int] = rp.pending_dec

    for batch in batches:
        if not batch:
            raise PackError("empty event batch")
        batch_first = batch[0].event_id
        for i, ev in enumerate(batch):
            et = ev.event_type
            a = ev.attributes
            slot = -1
            attrs = [0] * 8

            if next_event_id is not None and ev.event_id != next_event_id:
                raise PackError(
                    f"event id {ev.event_id} breaks contiguity "
                    f"(expected {next_event_id})"
                )
            next_event_id = ev.event_id + 1

            if last_version is None or ev.version != last_version:
                if last_version is not None and ev.version < last_version:
                    # same strictness as VersionHistory.add_or_update_item
                    raise PackError(
                        f"event version {ev.version} < last version {last_version}"
                    )
                version_changes += 1
                last_version = ev.version
            if version_changes > caps.max_version_items:
                raise PackOverflowError(
                    f"version-history items exceed {caps.max_version_items}"
                )

            if et == EventType.WorkflowExecutionStarted:
                side.task_list = a.get("task_list", "")
                side.workflow_type = a.get("workflow_type", "")
                side.cron_schedule = a.get("cron_schedule", "")
                backoff_s = a.get(
                    "first_decision_task_backoff_seconds", 0) or 0
                side.first_decision_backoff_deadline = (
                    ev.timestamp + backoff_s * SECONDS if backoff_s else 0
                )
                side.parent_domain = a.get("parent_workflow_domain") or ""
                side.parent_workflow_id = a.get("parent_workflow_id") or ""
                side.parent_run_id = a.get("parent_run_id") or ""
                side.continued_execution_run_id = a.get("continued_execution_run_id", "")
                side.memo = dict(a.get("memo") or {})
                side.search_attributes = dict(a.get("search_attributes") or {})
                rp = a.get("retry_policy")
                attrs[0] = _timeout(a, "execution_start_to_close_timeout_seconds")
                attrs[1] = _timeout(a, "task_start_to_close_timeout_seconds")
                attrs[2] = a.get("attempt", 0)
                attrs[3] = 1 if rp is not None else 0
                exp = a.get("expiration_timestamp", 0)
                attrs[4] = rel_ts(exp) if exp else 0
                attrs[5] = _timeout(a, "first_decision_task_backoff_seconds")
                attrs[6] = a.get("initiator", 0)
                attrs[7] = a.get("parent_initiated_event_id", EMPTY_EVENT_ID)

            elif et == EventType.DecisionTaskScheduled:
                attrs[0] = _timeout(a, "start_to_close_timeout_seconds")
                attrs[1] = a.get("attempt", 0)
                pending_dec = ev.event_id

            elif et == EventType.DecisionTaskStarted:
                sched = a.get("scheduled_event_id", EMPTY_EVENT_ID)
                # same strictness as replicate_decision_task_started_event
                if pending_dec is None or sched != pending_dec:
                    raise PackError(
                        f"decision started references schedule {sched}, "
                        f"pending is {pending_dec}"
                    )
                attrs[0] = sched

            elif et == EventType.DecisionTaskCompleted:
                attrs[0] = a.get("started_event_id", EMPTY_EVENT_ID)
                pending_dec = None
                MutableState.record_reset_point(
                    side.auto_reset_points,
                    a.get("binary_checksum", "") or "",
                    side.run_id, ev.event_id, ev.timestamp,
                )

            elif et == EventType.DecisionTaskTimedOut:
                attrs[0] = a.get("timeout_type", 0)
                # sticky timeouts drop the decision; others leave a
                # transient decision pending (schedule id = batch first)
                if attrs[0] == int(TimeoutType.ScheduleToStart):
                    pending_dec = None
                else:
                    pending_dec = batch_first

            elif et == EventType.DecisionTaskFailed:
                pending_dec = batch_first  # transient decision

            elif et == EventType.ActivityTaskScheduled:
                activity_id = a.get("activity_id", "")
                slot = acts.alloc(ev.event_id)
                acts_by_name[activity_id] = slot
                side.activity_ids[slot] = activity_id
                side.activity_task_lists[slot] = a.get("task_list", "")
                rp = a.get("retry_policy")
                attrs[0] = hash31(activity_id)
                attrs[1] = _timeout(a, "schedule_to_start_timeout_seconds")
                attrs[2] = _timeout(a, "schedule_to_close_timeout_seconds")
                attrs[3] = _timeout(a, "start_to_close_timeout_seconds")
                attrs[4] = _timeout(a, "heartbeat_timeout_seconds")
                attrs[5] = 1 if rp is not None else 0
                attrs[6] = _timeout(rp or {}, "expiration_interval_seconds")

            elif et == EventType.ActivityTaskStarted:
                sched = a.get("scheduled_event_id", EMPTY_EVENT_ID)
                slot = acts.get(sched)
                if slot is None:
                    raise PackError(f"activity started for unknown schedule {sched}")
                attrs[0] = sched
                attrs[1] = a.get("attempt", 0)

            elif et in (
                EventType.ActivityTaskCompleted,
                EventType.ActivityTaskFailed,
                EventType.ActivityTaskTimedOut,
                EventType.ActivityTaskCanceled,
            ):
                sched = a.get("scheduled_event_id", EMPTY_EVENT_ID)
                slot = acts.release(sched)
                name = side.activity_ids.get(slot, "")
                if acts_by_name.get(name) == slot:
                    acts_by_name.pop(name, None)
                attrs[0] = sched
                if et == EventType.ActivityTaskTimedOut:
                    attrs[1] = a.get("timeout_type", 0)

            elif et == EventType.ActivityTaskCancelRequested:
                activity_id = a.get("activity_id", "")
                slot = acts_by_name.get(activity_id)
                if slot is None:
                    raise PackError(
                        f"cancel requested for unknown activity {activity_id!r}"
                    )
                attrs[0] = hash31(activity_id)

            elif et == EventType.RequestCancelActivityTaskFailed:
                pass

            elif et == EventType.TimerStarted:
                timer_id = a.get("timer_id", "")
                if timers.get(timer_id) is not None:
                    raise PackError(f"duplicate timer id {timer_id!r}")
                slot = timers.alloc(timer_id)
                side.timer_ids[slot] = timer_id
                attrs[0] = hash31(timer_id)
                attrs[1] = _timeout(a, "start_to_fire_timeout_seconds")

            elif et in (EventType.TimerFired, EventType.TimerCanceled):
                timer_id = a.get("timer_id", "")
                slot = timers.release(timer_id)
                attrs[0] = a.get("started_event_id", EMPTY_EVENT_ID)
                attrs[1] = hash31(timer_id)

            elif et == EventType.CancelTimerFailed:
                pass

            elif et == EventType.StartChildWorkflowExecutionInitiated:
                slot = children.alloc(ev.event_id)
                # slot reuse: a prior occupant's started run id must not
                # leak into this (not-yet-started) child's rehydration
                side.child_run_ids.pop(slot, None)
                side.child_domains[slot] = resolve_domain(
                    a.get("domain", "")
                )
                side.child_workflow_ids[slot] = a.get("workflow_id", "")
                side.child_types[slot] = a.get("workflow_type", "")
                attrs[0] = hash31(a.get("workflow_id", ""))
                attrs[1] = a.get("parent_close_policy", 0)

            elif et == EventType.ChildWorkflowExecutionStarted:
                init = a.get("initiated_event_id", EMPTY_EVENT_ID)
                slot = children.get(init)
                if slot is None:
                    raise PackError(f"child started for unknown initiated {init}")
                child_run_id = a.get("run_id", "")
                side.child_run_ids[slot] = child_run_id
                attrs[0] = init
                attrs[1] = hash31(child_run_id) if child_run_id else 0

            elif et in (
                EventType.StartChildWorkflowExecutionFailed,
                EventType.ChildWorkflowExecutionCompleted,
                EventType.ChildWorkflowExecutionFailed,
                EventType.ChildWorkflowExecutionCanceled,
                EventType.ChildWorkflowExecutionTimedOut,
                EventType.ChildWorkflowExecutionTerminated,
            ):
                init = a.get("initiated_event_id", EMPTY_EVENT_ID)
                slot = children.release(init)
                attrs[0] = init

            elif et == EventType.RequestCancelExternalWorkflowExecutionInitiated:
                slot = cancels.alloc(ev.event_id)
                side.cancel_targets[slot] = (
                    resolve_domain(a.get("domain", "")),
                    a.get("workflow_id", ""),
                    a.get("run_id", ""),
                    bool(a.get("child_workflow_only", False)),
                )

            elif et in (
                EventType.RequestCancelExternalWorkflowExecutionFailed,
                EventType.ExternalWorkflowExecutionCancelRequested,
            ):
                init = a.get("initiated_event_id", EMPTY_EVENT_ID)
                slot = cancels.release(init)
                attrs[0] = init

            elif et == EventType.SignalExternalWorkflowExecutionInitiated:
                slot = signals.alloc(ev.event_id)
                side.signal_targets[slot] = (
                    resolve_domain(a.get("domain", "")),
                    a.get("workflow_id", ""),
                    a.get("run_id", ""),
                    bool(a.get("child_workflow_only", False)),
                )

            elif et in (
                EventType.SignalExternalWorkflowExecutionFailed,
                EventType.ExternalWorkflowExecutionSignaled,
            ):
                init = a.get("initiated_event_id", EMPTY_EVENT_ID)
                slot = signals.release(init)
                attrs[0] = init

            elif et == EventType.UpsertWorkflowSearchAttributes:
                side.search_attributes.update(a.get("search_attributes", {}))

            elif et in (
                EventType.MarkerRecorded,
                EventType.WorkflowExecutionSignaled,
                EventType.WorkflowExecutionCancelRequested,
                EventType.WorkflowExecutionCompleted,
                EventType.WorkflowExecutionFailed,
                EventType.WorkflowExecutionTimedOut,
                EventType.WorkflowExecutionCanceled,
                EventType.WorkflowExecutionTerminated,
                EventType.WorkflowExecutionContinuedAsNew,
            ):
                pass

            else:
                raise PackError(f"unknown event type {et}")

            rows.append([
                int(et),
                ev.event_id,
                ev.version,
                ev.task_id,
                rel_ts(ev.timestamp),
                batch_first,
                1 if i == len(batch) - 1 else 0,
                slot,
                *attrs,
            ])

    arr = np.asarray(rows, dtype=np.int64).reshape(-1, S.EV_N)
    if arr.size and (arr.max() > _INT32_MAX or arr.min() < -(2**31)):
        raise PackError("event field does not fit int32")
    side.resume = PackResume(
        next_event_id=(next_event_id if next_event_id is not None
                       else rp.next_event_id),
        last_version=last_version,
        version_changes=version_changes,
        pending_dec=pending_dec,
        epoch_s=epoch_s,
        activity_slots=dict(acts.by_key),
        acts_by_name=dict(acts_by_name),
        timer_slots=dict(timers.by_key),
        child_slots=dict(children.by_key),
        cancel_slots=dict(cancels.by_key),
        signal_slots=dict(signals.by_key),
    )
    return arr.astype(np.int32), side


def _resume_epoch(first_ts: List[int],
                  resume: List[Optional[ResumeState]]) -> int:
    """Shared batch epoch covering both suffix events and resumed state
    rows: the minimum over first-event epochs and resume epochs, so
    every rebased row timestamp stays >= 1 (rows only shift forward)."""
    cands = [ts // SECONDS for ts in first_ts]
    cands += [r.pack.epoch_s for r in resume if r is not None]
    return min(cands) if cands else 0


def _build_initial(
    resume: List[Optional[ResumeState]], caps: S.Capacities,
    epoch_s: int, n_rows: int,
) -> Optional[S.StateTensors]:
    """[n_rows] StateTensors with resumed histories' (rebased) snapshot
    rows; None when nothing resumes."""
    if not any(r is not None for r in resume):
        return None
    initial = S.empty_state(n_rows, caps)
    for idx, r in enumerate(resume):
        if r is None:
            continue
        delta = r.pack.epoch_s - epoch_s
        row = S.rebase_state_row(r.state_row, delta)
        for field, cols in S.ROW_TS_COLS.items():
            arr = row[field]
            for c in cols:
                if (arr[..., c] >= MAX_REL_TS).any():
                    raise PackOverflowError(
                        "resumed state row timestamp out of packable "
                        f"window after rebase (delta {delta}s)"
                    )
        try:
            S.set_state_row(initial, idx, row)
        except ValueError as e:  # shape mismatch = caps mismatch
            raise PackOverflowError(
                f"resume state row does not fit capacities {caps}: {e}"
            )
    return initial


def pack_histories(
    histories: Sequence[Tuple[str, str, Sequence[Sequence[HistoryEvent]]]],
    caps: Optional[S.Capacities] = None,
    pad_batch_to: Optional[int] = None,
    domain_resolver=None,
    resume: Optional[Sequence[Optional[ResumeState]]] = None,
) -> PackedHistories:
    """Pack many workflows into one padded [B, T, EV_N] tensor.

    ``histories``: sequence of (workflow_id, run_id, batches).
    ``pad_batch_to``: round the batch dim up (e.g. to a multiple of the
    device-mesh size for even sharding).
    ``resume``: optional per-history checkpoint resume states — a
    resumed history's batches are its event SUFFIX and its row of the
    result's ``initial`` StateTensors carries the snapshot state.
    """
    caps = caps or S.Capacities()
    b = len(histories)
    bp = max(pad_batch_to or b, b)
    resume = list(resume) if resume is not None else [None] * b
    if len(resume) != b:
        raise ValueError("resume list must align with histories")
    lengths = np.zeros((bp,), dtype=np.int32)
    side: List[WorkflowSideTable] = []
    first_ts = [
        batches[0][0].timestamp
        for _, _, batches in histories
        if batches and batches[0]
    ]
    epoch_s = _resume_epoch(first_ts, resume)
    per_wf: List[np.ndarray] = []
    for idx, (wf_id, run_id, batches) in enumerate(histories):
        arr, st = pack_workflow(
            batches, caps, workflow_id=wf_id, run_id=run_id,
            epoch_s=epoch_s, domain_resolver=domain_resolver,
            resume=resume[idx],
        )
        lengths[idx] = arr.shape[0]
        side.append(st)
        per_wf.append(arr)
    for _ in range(bp - b):
        side.append(WorkflowSideTable())
    initial = _build_initial(resume, caps, epoch_s, bp)
    rows_concat = (
        np.concatenate(per_wf, axis=0)
        if per_wf
        else np.zeros((0, S.EV_N), dtype=np.int32)
    )
    # one fused pad+layout pass (C++ sidecar when available) instead of
    # a per-workflow fill loop
    from cadence_tpu.native import scatter_batch_major

    events = scatter_batch_major(rows_concat, lengths, caps.max_events)
    # rows_concat is the replay source of truth (time_major reads it);
    # freeze the derived tensor so divergence-by-mutation is an error,
    # not a silent mismatch
    events.flags.writeable = False
    rows_concat.flags.writeable = False
    return PackedHistories(
        events=events, lengths=lengths, side=side, caps=caps,
        epoch_s=epoch_s, rows_concat=rows_concat, initial=initial,
    )


@dataclasses.dataclass
class PackedLanes:
    """Ragged lane-packed batch: multiple whole histories back-to-back in
    each scan lane (sequence packing for the replay kernel).

    Where :class:`PackedHistories` pads every history to the deepest one
    in the batch, this layout packs segments (whole histories) end to end
    so the effective scan length per history is its own depth, not
    ``max(depth)``. Each segment's last (possibly padded) row carries a
    segment-end flag and a precomputed output snapshot row; the kernel
    scatters the lane's state there and resets the lane to
    ``empty_state`` — bit-identically to replaying the segment alone
    (tests/test_replay_differential.py::TestLanePacking).
    """

    events: np.ndarray       # [L, T, EV_N] int32 (-1 type = padding)
    seg_end: np.ndarray      # [L, T] bool — last row of each segment
    out_row: np.ndarray      # [L, T] int32 — snapshot row at seg-end rows
    lengths: np.ndarray      # [n_histories] int32 — real events per history
    side: List[WorkflowSideTable]  # indexed by output row (input order)
    caps: S.Capacities
    epoch_s: int = 0
    # per-lane segment table: (out_row, start, end_excl) with end_excl
    # including seg_align padding — how ops/unpack.py splits snapshots
    lane_segments: List[List[Tuple[int, int, int]]] = dataclasses.field(
        default_factory=list
    )
    seg_align: int = 1
    # [n_histories] StateTensors of initial segment carries (checkpoint
    # resume): row i seeds history i's segment instead of empty_state;
    # None = every segment starts empty
    initial: Optional[Any] = None

    @property
    def n_histories(self) -> int:
        return len(self.lengths)

    @property
    def lanes(self) -> int:
        return self.events.shape[0]

    @property
    def scan_len(self) -> int:
        return self.events.shape[1]

    @property
    def total_events(self) -> int:
        return int(self.lengths.sum())

    @property
    def padding_frac(self) -> float:
        """Padded steps ÷ real events — the waste the packer removes."""
        real = self.total_events
        if not real:
            return 0.0
        return (self.lanes * self.scan_len - real) / real

    @property
    def lanes_per_history(self) -> float:
        n = self.n_histories
        return self.lanes / n if n else 0.0

    @property
    def present_types(self) -> Tuple[int, ...]:
        """Sorted event types occurring in this batch — feed through
        ops.replay.type_signature to statically specialize the scan."""
        et = np.unique(self.events[:, :, S.EV_TYPE])
        return tuple(int(t) for t in et if t >= 0)

    def time_major(self):
        """(events [T, L, EV_N], seg_end [T, L], out_row [T, L]) — the
        layout replay_scan_packed consumes."""
        ev = np.ascontiguousarray(np.transpose(self.events, (1, 0, 2)))
        return ev, self.seg_end.T.copy(), self.out_row.T.copy()

    def teb(self) -> np.ndarray:
        """[T, EV_N, L] field-major for the Pallas packed path."""
        return np.ascontiguousarray(np.transpose(self.events, (1, 2, 0)))

    def reset_rows(self) -> np.ndarray:
        """[L, T] int32: at each segment-end step, the ``initial`` row
        the lane resets to — the NEXT segment's initial state. The
        sentinel ``n_histories`` indexes the kernels' appended pristine
        empty row (the default for non-resumed segments and lane ends)."""
        rr = np.full(
            (self.lanes, self.scan_len), self.n_histories, np.int32
        )
        for ln, segs in enumerate(self.lane_segments):
            for k in range(len(segs) - 1):
                rr[ln, segs[k][2] - 1] = segs[k + 1][0]
        return rr

    def lane_state0(self, initial=None) -> "S.StateTensors":
        """[lanes] initial lane carries: each lane starts from its FIRST
        segment's initial row (``initial``, default ``self.initial``),
        or empty_state."""
        initial = initial if initial is not None else self.initial
        state0 = S.empty_state(self.lanes, self.caps)
        if initial is None:
            return state0
        for ln, segs in enumerate(self.lane_segments):
            if segs:
                S.set_state_row(
                    state0, ln, S.state_row(initial, segs[0][0])
                )
        return state0


def pack_lanes(
    histories: Sequence[Tuple[str, str, Sequence[Sequence[HistoryEvent]]]],
    caps: Optional[S.Capacities] = None,
    target_lane_len: Optional[int] = None,
    seg_align: int = 1,
    pad_lanes_to: Optional[int] = None,
    round_lengths: bool = True,
    domain_resolver=None,
    resume: Optional[Sequence[Optional[ResumeState]]] = None,
) -> PackedLanes:
    """Greedy first-fit lane packing of many workflow histories.

    ``target_lane_len``: lane capacity in events; histories are packed
    back-to-back up to it (a history longer than the target still gets a
    lane — the final scan length is the longest lane, grid-rounded).
    Defaults to the longest single history, i.e. one history per lane,
    matching :func:`pack_histories` density.

    ``seg_align``: segment starts/ends are padded to this multiple — the
    Pallas packed kernel flushes snapshots at time-block boundaries, so
    its callers pack with ``seg_align == tb``. Padding rows are no-ops
    (EV_TYPE −1), so the aligned snapshot equals the unaligned one.

    Output rows follow the input order: ``out_row`` i and ``side[i]``
    belong to ``histories[i]`` whatever lane its segment landed in.

    ``resume``: optional per-history checkpoint resume states (see
    :func:`pack_histories`) — a resumed history's batches are its event
    SUFFIX; its row of ``PackedLanes.initial`` seeds the segment carry.
    A zero-event suffix (checkpoint at the branch tip) still occupies
    one ``seg_align`` block of padding rows so its segment-end flush
    emits the (initial) state into the output row.
    """
    caps = caps or S.Capacities()
    if seg_align < 1:
        raise ValueError(f"seg_align must be >= 1, got {seg_align}")
    n = len(histories)
    resume = list(resume) if resume is not None else [None] * n
    if len(resume) != n:
        raise ValueError("resume list must align with histories")
    first_ts = [
        batches[0][0].timestamp
        for _, _, batches in histories
        if batches and batches[0]
    ]
    epoch_s = _resume_epoch(first_ts, resume)
    per_wf: List[np.ndarray] = []
    side: List[WorkflowSideTable] = []
    lengths = np.zeros((n,), dtype=np.int32)
    seg_lens: List[int] = []
    for idx, (wf_id, run_id, batches) in enumerate(histories):
        arr, st = pack_workflow(
            batches, caps, workflow_id=wf_id, run_id=run_id,
            epoch_s=epoch_s, domain_resolver=domain_resolver,
            resume=resume[idx],
        )
        per_wf.append(arr)
        side.append(st)
        lengths[idx] = arr.shape[0]
        seg_lens.append(-(-max(arr.shape[0], 1) // seg_align) * seg_align)

    max_seg = max(seg_lens, default=seg_align)
    cap_t = max(target_lane_len or 0, max_seg)

    # greedy first-fit in ascending-length order (original index breaks
    # ties) — lanes too small for the current segment can never fit a
    # later one, so they drop out of the open set and the fit stays
    # O(n + lanes) even for storm-sized batches
    order = sorted(range(n), key=lambda i: (seg_lens[i], i))
    lane_fill: List[int] = []          # events used per lane
    assign: List[List[int]] = []       # history indices per lane
    open_lanes: List[int] = []
    for i in order:
        seg = seg_lens[i]
        placed = None
        still_open: List[int] = []
        for ln in open_lanes:
            if placed is None and lane_fill[ln] + seg <= cap_t:
                placed = ln
            if lane_fill[ln] + seg <= cap_t or ln == placed:
                still_open.append(ln)
        open_lanes = still_open
        if placed is None:
            placed = len(lane_fill)
            lane_fill.append(0)
            assign.append([])
            open_lanes.append(placed)
        lane_fill[placed] += seg
        assign[placed].append(i)

    n_lanes = max(len(lane_fill), 1)
    t = max(lane_fill, default=seg_align)
    t = round_scan_len(t) if round_lengths else t
    # the Pallas packed path needs scan length divisible by the block
    # (= seg_align); grid points like 12/24/48 may not be
    t = -(-t // seg_align) * seg_align
    lanes = round_scan_len(max(pad_lanes_to or 0, n_lanes)) \
        if round_lengths else max(pad_lanes_to or 0, n_lanes)

    events = np.full((lanes, t, S.EV_N), 0, dtype=np.int32)
    events[:, :, S.EV_TYPE] = -1
    seg_end = np.zeros((lanes, t), dtype=bool)
    out_row = np.zeros((lanes, t), dtype=np.int32)
    lane_segments: List[List[Tuple[int, int, int]]] = [
        [] for _ in range(lanes)
    ]
    for ln, members in enumerate(assign):
        cursor = 0
        for i in members:
            arr = per_wf[i]
            events[ln, cursor : cursor + arr.shape[0]] = arr
            end = cursor + seg_lens[i]
            seg_end[ln, end - 1] = True
            out_row[ln, end - 1] = i
            lane_segments[ln].append((i, cursor, end))
            cursor = end

    events.flags.writeable = False
    # initial's batch dim is a jit specialization key like every other
    # shape here: grid-round it so resumed storm chunks of arbitrary
    # size don't each compile a fresh executable (padding rows are
    # empty_state — the reset sentinel indexes one identically)
    n_init = round_scan_len(n) if round_lengths else n
    initial = _build_initial(resume, caps, epoch_s, n_init)
    return PackedLanes(
        events=events, seg_end=seg_end, out_row=out_row, lengths=lengths,
        side=side, caps=caps, epoch_s=epoch_s,
        lane_segments=lane_segments, seg_align=seg_align, initial=initial,
    )


