"""Dense tensor encodings + the batched TPU replay kernel (the north star)."""
