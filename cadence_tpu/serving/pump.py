"""Background tick pump: bounded staleness for write-heavy lanes.

PR 14's ticks ride reads and appends — a write-heavy, read-light
workflow could stage persist-feed debt forever without a reader to
compose it, so its resident row's staleness was unbounded (the ROADMAP
follow-on this closes). The pump is one daemon thread driving
``ResidentEngine.tick()`` at a configured cadence
(``serving.tickIntervalMs``), so every dirty lane composes within
~one interval regardless of read traffic; the proof is the
``serving_staleness_ms`` histogram the engine records per composed lane
(first-dirty → composed), which TestOverloadChaos holds under the
configured bound.

Discipline:

* **drain-on-stop**: ``stop()`` joins the thread and runs ONE final
  tick so Δs staged between the last cycle and the stop are composed
  before HistoryService.drain flushes the lanes;
* **fault-tolerant**: the tick calls through the engine into the
  (possibly ``wrap_bundle``-fault-injected) history manager — an
  injected or real error must not kill the pump. A failed cycle logs,
  counts ``serving_tick_pump_errors``, and backs off (doubling, capped
  at 8× the cadence) so a down store is not hammered at full cadence;
* **no locks held while sleeping**: the pump owns no lock at all; the
  engine's own ``_tick_lock`` serializes it against inline tick
  callers (reads composing dirty lanes) exactly like any other caller.
"""

from __future__ import annotations

import threading

from cadence_tpu.utils.backoff import BackoffLadder
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP, Scope


class TickPump:
    """Drives ``engine.tick()`` every ``interval_s`` until stopped."""

    def __init__(
        self,
        engine,
        interval_s: float,
        metrics: Scope = None,
        name: str = "serving-tick-pump",
    ) -> None:
        if interval_s <= 0:
            raise ValueError("tick pump: interval_s must be > 0")
        self.engine = engine
        self.interval_s = float(interval_s)
        self._metrics = (
            metrics if metrics is not None else NOOP
        ).tagged(layer="serving")
        self._log = get_logger("cadence_tpu.serving.pump")
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name=name, daemon=True
        )
        self._started = False
        self.cycles = 0
        self.errors = 0

    def start(self) -> "TickPump":
        self._started = True
        self._thread.start()
        return self

    def _run(self) -> None:
        ladder = BackoffLadder(self.interval_s, self.interval_s * 8.0)
        delay = self.interval_s
        while not self._stop.wait(delay):
            try:
                self.engine.tick()
                self.cycles += 1
                ladder.success()
                delay = self.interval_s
            except Exception as e:
                # a sick store must not kill the staleness bound for
                # good — log, count, back off (capped), keep pumping
                self.errors += 1
                self._metrics.inc("serving_tick_pump_errors")
                self._log.warn(f"tick pump cycle failed ({e}); backoff")
                delay = ladder.failure()

    def stop(self, timeout_s: float = 5.0) -> None:
        """Drain-on-stop: join the pump, then one final tick composes
        whatever was staged after the last cycle."""
        if not self._started:
            return
        self._stop.set()
        self._thread.join(timeout_s)
        try:
            self.engine.tick()
            self.cycles += 1
        except Exception as e:
            self.errors += 1
            self._metrics.inc("serving_tick_pump_errors")
            self._log.warn(f"tick pump drain tick failed ({e})")

    @property
    def running(self) -> bool:
        return self._thread.is_alive()
