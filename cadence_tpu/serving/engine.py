"""Persistent megabatch serving engine: device-resident hot state with
O(Δ) replay-on-append.

At millions-of-users scale the dominant operation is "N new events
arrived on a live workflow", not "rebuild 1k events from zero" — yet
every rebuild path replays from a checkpoint or from scratch per
request. This engine keeps hot workflows' state rows RESIDENT in a
fixed-shape [S]-lane state tensor and converts each append into an
O(Δ) suffix composition:

* ``admit()`` seats a workflow into a free lane by rehydrating its
  ``ReplayCheckpoint`` (suffix-only resume through the packer's
  ResumeState seam) or cold-replaying the prefix through the existing
  double-buffered dispatcher (``ops.dispatch.replay_stream``);
* ``append()`` stages just the Δ suffix against the workflow's lane;
* ``tick()`` runs ONE fused device step composing every pending suffix
  against its lane via the associative affine update algebra
  (``ops/assoc.py`` / ``schema.UPDATE_ALGEBRA``) — lanes whose Δ
  carries a type the classifier cannot prove affine fall back to the
  sequential packed scan in the same tick (a second, sequential-kernel
  batch), exactly the hybrid discipline of ``replay_assoc``;
* ``read()`` answers decision/query requests straight from the
  resident row — no replay, no history read;
* eviction (LRU-idle + on-close) flushes a lane's row back through
  ``CheckpointManager.flush`` and refills the slot from the admission
  queue — the finished-chain/slot-refill discipline of vectorized-MCMC
  continuous batching.

Correctness invariants (tests/test_serving.py):

* **differential**: resident state after K appends is byte-identical
  to a cold ``rebuild_many``/``replay_packed`` of the full history —
  for affine-only Δs, hybrid non-affine Δs, recycle-then-readmit, and
  checkpoint-resume seeding;
* **generation stamp**: every lane slot carries a generation bumped on
  recycle; a stale in-flight append (ticket from a previous tenancy)
  can never land on a recycled slot;
* **compiled-shape discipline**: every tick/seat shape comes off the
  shared ``ops.grid`` policy, so the serving tick and the storm
  rebuild path cannot drift on executable selection.

Concurrency discipline (the sanitizer gates): the single engine lock is
constructed via ``utils/locks.make_lock``, the hot shared containers
are declared via ``make_guarded`` + ``testing/race_witness.
GUARDED_FIELDS``, and NOTHING blocking runs under the lock — packing,
device steps, checkpoint flushes, and metric emissions all happen
outside it (lane state is snapshotted/committed under the lock in
plain-python critical sections).
"""

from __future__ import annotations

import dataclasses
import time as _time
from typing import Dict, List, Optional, Sequence, Tuple

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.ops import schema as S
from cadence_tpu.ops.grid import round_scan_len
from cadence_tpu.ops.pack import ResumeState, pack_lanes
from cadence_tpu.serving.admission import (
    AdmissionPolicy,
    FairAdmissionQueue,
)
from cadence_tpu.utils import locks
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP, Scope

Batches = Sequence[Sequence[HistoryEvent]]


@dataclasses.dataclass(frozen=True)
class LaneTicket:
    """A seat handle: (slot, generation) at seat time. The generation
    is the stale-append guard — a ticket outlives its tenancy only as a
    rejected append, never as a write onto a recycled slot."""

    workflow_id: str
    run_id: str
    lane: int
    generation: int


@dataclasses.dataclass
class ResidentRead:
    """One resident-row read: the canonical snapshot plus everything
    needed to rehydrate a full MutableState lazily."""

    snapshot: Dict
    side: object
    epoch_s: int
    domain_id: str
    resident: bool
    state_row: Dict
    branch_token: bytes = b""

    def mutable_state(self):
        from cadence_tpu.ops.unpack import state_row_to_mutable_state

        one = S.empty_state(1, _caps_of_row(self.state_row))
        S.set_state_row(one, 0, self.state_row)
        return state_row_to_mutable_state(
            one, 0, self.side, domain_id=self.domain_id,
            epoch_s=self.epoch_s,
        )


def _caps_of_row(row: Dict) -> S.Capacities:
    return S.Capacities(
        max_events=1,  # not represented in a state row
        max_activities=row["activities"].shape[0],
        max_timers=row["timers"].shape[0],
        max_children=row["children"].shape[0],
        max_request_cancels=row["cancels"].shape[0],
        max_signals_ext=row["signals"].shape[0],
        max_version_items=row["vh_items"].shape[0],
    )


@dataclasses.dataclass
class _Lane:
    """One seated workflow's lane bookkeeping (the resident state row
    itself lives in the engine's [S] StateTensors at this slot)."""

    domain_id: str
    workflow_id: str
    run_id: str
    branch_token: bytes
    side: object                 # WorkflowSideTable; .resume at the tip
    epoch_s: int
    generation: int
    last_used: int               # tick number
    seated: bool = False         # False while the seat replay is in flight
    closed: bool = False
    pending: List[List[HistoryEvent]] = dataclasses.field(
        default_factory=list
    )
    pending_events: int = 0
    # staged tip: the next event id NOT yet staged into this lane —
    # committed row tip + every pending Δ. The append-idempotence
    # watermark: a duplicate/overlapping batch is dropped here
    next_staged: int = 0
    # persist feed high-water mark (``on_persisted``): history has
    # advanced to this next_event_id; the next tick fetches the
    # [next_staged, behind_through) suffix — O(Δ) — and stages it
    behind_through: int = 0
    # wall time the lane FIRST went dirty (staged Δ or persist debt)
    # since its last compose — the ``serving_staleness_ms`` input the
    # tick pump's bounded-staleness contract is asserted against
    dirty_since: float = 0.0

    @property
    def key(self) -> Tuple[str, str]:
        return (self.workflow_id, self.run_id)


@dataclasses.dataclass
class _Admission:
    domain_id: str
    workflow_id: str
    run_id: str
    branch_token: bytes
    batches: List
    resume: Optional[ResumeState]

    @property
    def key(self) -> Tuple[str, str]:
        return (self.workflow_id, self.run_id)


class ResidentEngine:
    """Fixed-S-lane resident serving megabatch (module docstring)."""

    def __init__(
        self,
        lanes: int = 64,
        caps: Optional[S.Capacities] = None,
        checkpoints=None,
        history=None,
        metrics: Optional[Scope] = None,
        idle_ticks: int = 256,
        affine_types: Optional[frozenset] = None,
        admission: Optional[AdmissionPolicy] = None,
        tick_interval_s: float = 0.0,
    ) -> None:
        if lanes < 1:
            raise ValueError("serving: lanes must be >= 1")
        if idle_ticks < 1:
            raise ValueError("serving: idle_ticks must be >= 1")
        self.caps = caps or S.Capacities()
        self.lanes = int(lanes)
        # checkpoint.CheckpointManager: eviction flush target + the
        # resume source for admits; None = cold admits, flush-less
        # evictions (the history store stays the source of truth)
        self.checkpoints = checkpoints
        # persistence HistoryManager for admit_from_store / read-through
        self.history = history
        self.idle_ticks = int(idle_ticks)
        # test seam mirroring replay_assoc(affine_types=...): may only
        # SHRINK the proven-affine set (forces lanes onto the
        # sequential fallback), never grow it
        self._affine_types = affine_types
        self._metrics = (
            metrics if metrics is not None else NOOP
        ).tagged(layer="serving")
        self._log = get_logger("cadence_tpu.serving")
        # -- guarded state (everything below is touched ONLY under
        # _lock; blocking work never runs while it is held) -----------
        self._lock = locks.make_lock("ResidentEngine._lock")
        # tick serialization: the snapshot → compose → commit cycle of
        # one tick must be atomic w.r.t. other ticks, or two concurrent
        # ticks could compose disjoint pending Δs from the SAME base
        # row snapshot and the later commit would silently discard the
        # earlier Δ. Strict order: _tick_lock is taken first, _lock
        # only inside it (no path holds _lock while acquiring this)
        self._tick_lock = locks.make_lock("ResidentEngine._tick_lock")
        self._slots = locks.make_guarded(
            [None] * self.lanes, "ResidentEngine._slots", self._lock
        )
        self._by_key = locks.make_guarded(
            {}, "ResidentEngine._by_key", self._lock
        )
        # fair admission (serving/admission.py): weighted + deadline-
        # aged + per-domain-quota'd refill, replacing the PR 14 FIFO
        # list; the queue's parked table is guarded by THIS engine lock
        self._admit_queue = FairAdmissionQueue(admission, self._lock)
        # the tick pump's cadence (serving/pump.py; 0 = no pump): the
        # engine just carries the configured value for whoever owns the
        # pump thread (HistoryService.start)
        self.tick_interval_s = float(tick_interval_s)
        self._slot_gen = [0] * self.lanes
        self._tick_no = 0
        # the resident store: one [S]-row StateTensors, rows scattered
        # in place under the lock (device-resident on TPU deployments;
        # host numpy on the CPU fallback — same O(Δ) discipline)
        self._state = S.empty_state(self.lanes, self.caps)

    # ------------------------------------------------------------------
    # admission
    # ------------------------------------------------------------------

    def admit(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        branch_token: bytes = b"",
        batches: Optional[Batches] = None,
        checkpoint=None,
    ) -> Optional[LaneTicket]:
        """Seat one workflow; returns its ticket, or None when every
        lane is occupied (the admission queued for the next recycle).

        ``batches`` is the FULL history prefix (cold admit). With a
        ``checkpoint`` (ReplayCheckpoint) the engine seats from the
        snapshot and ``batches`` — when given — is filtered down to the
        suffix past it; with a CheckpointManager attached, admits
        consult the store the same way ``rebuild_many`` does."""
        out = self.admit_many([
            dict(domain_id=domain_id, workflow_id=workflow_id,
                 run_id=run_id, branch_token=branch_token,
                 batches=batches, checkpoint=checkpoint)
        ])
        return out.get((workflow_id, run_id))

    def admit_from_store(
        self, domain_id: str, workflow_id: str, run_id: str,
        branch_token: bytes,
    ) -> Optional[LaneTicket]:
        """Production admission: full history from the attached history
        manager (checkpoint consult inside ``admit`` trims it to the
        suffix when a snapshot resumes)."""
        if self.history is None:
            raise RuntimeError("admit_from_store needs a history manager")
        return self.admit(
            domain_id, workflow_id, run_id, branch_token=branch_token,
            batches=self._read_batches(branch_token),
        )

    def admit_many(self, requests: Sequence[Dict], _requeued=None) -> Dict:
        """Bulk admission; returns {(workflow_id, run_id): ticket|None}.

        Free lanes are reserved under the lock, then every seat replay
        runs as ONE batch through the existing dispatcher
        (``replay_stream`` — pack overlap, depth bucketing, grid
        shapes), and the rows commit back under the lock. ``_requeued``
        (internal, the refill path): key → the original parked entry,
        so an admission that fails to seat re-parks at its ORIGINAL
        age — re-queueing must never reset the starvation clock."""
        admissions = [self._prepare_admission(r) for r in requests]
        out: Dict = {}
        seat: List[Tuple[int, int, _Admission]] = []
        queued = resumed = cold = 0
        with self._lock:
            for adm in admissions:
                slot = self._by_key.get(adm.key)
                if slot is not None:
                    lane = self._slots[slot]
                    lane.last_used = self._tick_no
                    out[adm.key] = LaneTicket(
                        adm.workflow_id, adm.run_id, slot,
                        lane.generation,
                    )
                    continue
                free = self._free_slot()
                if free is None:
                    self._admit_queue.park(
                        adm,
                        requeued_from=(_requeued or {}).get(adm.key),
                    )
                    queued += 1
                    out[adm.key] = None
                    continue
                gen = self._slot_gen[free]
                lane = _Lane(
                    domain_id=adm.domain_id,
                    workflow_id=adm.workflow_id, run_id=adm.run_id,
                    branch_token=adm.branch_token, side=None,
                    epoch_s=0, generation=gen,
                    last_used=self._tick_no, seated=False,
                )
                self._slots[free] = lane
                self._by_key[adm.key] = free
                seat.append((free, gen, adm))
                if adm.resume is not None:
                    resumed += 1
                else:
                    cold += 1
        if seat:
            seated = self._seat(seat)
            out.update(seated)
        scope = self._metrics
        if queued:
            scope.inc("serving_admit_queued", queued)
        if resumed:
            scope.inc("serving_admit_resume", resumed)
        if cold:
            scope.inc("serving_admit_cold", cold)
        return out

    def _prepare_admission(self, r: Dict) -> _Admission:
        """Resolve one admit request's seeding (checkpoint consult +
        suffix trim) — store I/O, so it runs before the lock."""
        batches = list(r.get("batches") or [])
        ckpt = r.get("checkpoint")
        branch_token = r.get("branch_token") or b""
        if ckpt is None and self.checkpoints is not None and branch_token:
            try:
                from cadence_tpu.checkpoint.manager import HIT

                cand, status = self.checkpoints.lookup(
                    branch_token, caps=self.caps
                )
                if status == HIT:
                    ckpt = cand
            except Exception:
                ckpt = None
        resume = None
        if ckpt is not None:
            suffix = [
                b for b in batches if b and b[0].event_id > ckpt.event_id
            ]
            straddles = any(
                b and b[0].event_id <= ckpt.event_id < b[-1].event_id
                for b in batches
            )
            if not straddles:
                try:
                    resume = ckpt.resume_state()
                    batches = suffix
                except Exception:
                    resume = None  # corrupt snapshot: cold admit
        return _Admission(
            domain_id=r.get("domain_id", ""),
            workflow_id=r["workflow_id"], run_id=r["run_id"],
            branch_token=branch_token, batches=batches, resume=resume,
        )

    def _seat(self, seat: List[Tuple[int, int, _Admission]]) -> Dict:
        """Replay the reserved admissions (outside the lock) and commit
        the rows; per-admission fallback isolates one bad history."""
        from cadence_tpu.ops.dispatch import replay_stream

        histories = [
            (adm.workflow_id, adm.run_id, adm.batches)
            for _, _, adm in seat
        ]
        resumes = [adm.resume for _, _, adm in seat]
        out: Dict = {}
        failures = 0
        try:
            results = replay_stream(
                histories, caps=self.caps, lane_pack=True,
                resume=resumes,
            )
            rows: List[Optional[Tuple]] = []
            for packed, final in results:
                rows.extend(
                    (packed, final, j)
                    for j in range(packed.n_histories)
                )
        except Exception:
            # group poisoned (one malformed history fails the strict
            # stream): seat individually, drop only the bad ones
            rows = []
            for hist, rs in zip(histories, resumes):
                try:
                    packed = pack_lanes(
                        [hist], caps=self.caps, resume=[rs]
                    )
                    final = self._replay(packed, scan_mode="auto")
                    rows.append((packed, final, 0))
                except Exception:
                    rows.append(None)
        admitted = 0
        with self._lock:
            for (slot, gen, adm), row in zip(seat, rows):
                if row is None:
                    failures += 1
                    # release ONLY our own reservation: the slot may
                    # have been recycled + re-seated while the replay
                    # ran (drain/evict bump the generation)
                    if self._slot_gen[slot] == gen:
                        self._release_slot(slot, adm.key)
                    out[adm.key] = None
                    continue
                packed, final, j = row
                if self._slot_gen[slot] != gen:
                    failures += 1  # recycled mid-seat (drain/shutdown)
                    out[adm.key] = None
                    continue
                lane = self._slots[slot]
                self._commit_row(slot, lane, packed, final, j)
                lane.seated = True
                admitted += 1
                out[adm.key] = LaneTicket(
                    adm.workflow_id, adm.run_id, slot, gen
                )
        if admitted:
            self._metrics.inc("serving_admits", admitted)
        if failures:
            self._metrics.inc("serving_admit_failures", failures)
        return out

    def _free_slot(self) -> Optional[int]:
        for i in range(self.lanes):
            if self._slots[i] is None:
                return i
        return None

    def _release_slot(self, slot: int, key) -> None:
        self._slot_gen[slot] += 1
        self._slots[slot] = None
        if self._by_key.get(key) == slot:
            del self._by_key[key]

    def _commit_row(self, slot, lane, packed, final, j) -> None:
        """Install one replay-result row into its lane (under _lock)."""
        row = S.state_row(final, j)
        S.set_state_row(self._state, slot, row)
        lane.side = packed.side[j]
        lane.epoch_s = packed.epoch_s
        lane.closed = bool(row["exec_info"][S.X_CLOSE_STATUS] != 0)
        lane.last_used = self._tick_no
        lane.next_staged = max(
            lane.next_staged, int(row["exec_info"][S.X_NEXT_EVENT_ID])
        )

    # ------------------------------------------------------------------
    # append + the fused tick
    # ------------------------------------------------------------------

    def append(self, ticket, batches: Batches) -> bool:
        """Stage a Δ suffix against a seated lane.

        ``ticket``: a LaneTicket (generation-checked — the stale-append
        guard) or a (workflow_id, run_id) key. Returns False (and
        counts ``serving_stale_appends``) when the ticket's tenancy is
        gone; the caller re-admits and retries. At-least-once feeds
        (the persist catch-up and an explicit append may overlap, with
        arbitrary re-chunking): events at or below the staged tip are
        trimmed, a batch that STRADDLES the tip keeps its unseen tail.
        A batch past the tip (a GAP — events between the tip and the
        batch never arrived here) is never composed over: lanes with a
        history feed record the debt and the next tick's catch-up
        fetches the whole span; bare lanes refuse the append (False,
        ``serving_gapped_appends``) so divergent state can never be
        served as resident truth — the caller evicts/re-admits."""
        batches = [list(b) for b in batches if b]
        stale = gapped = False
        n_events = 0
        with self._lock:
            lane = self._resolve_lane(ticket)
            if lane is None:
                stale = True
            else:
                for b in batches:
                    if b[0].event_id < lane.next_staged:
                        b = [
                            e for e in b
                            if e.event_id >= lane.next_staged
                        ]
                        if not b:
                            continue  # duplicate delivery, whole
                    if b[0].event_id > lane.next_staged:
                        if self.history is not None and lane.branch_token:
                            lane.behind_through = max(
                                lane.behind_through,
                                b[-1].event_id + 1,
                            )
                            if not lane.dirty_since:
                                lane.dirty_since = _time.monotonic()
                            continue
                        gapped = True
                        break
                    lane.pending.append(b)
                    lane.pending_events += len(b)
                    n_events += len(b)
                    lane.next_staged = b[-1].event_id + 1
                    if not lane.dirty_since:
                        lane.dirty_since = _time.monotonic()
        if stale:
            self._metrics.inc("serving_stale_appends")
            return False
        if gapped:
            self._metrics.inc("serving_gapped_appends")
            return False
        self._metrics.inc("serving_appends")
        if n_events:
            self._metrics.inc("serving_append_events", n_events)
        return True

    def on_persisted(
        self, domain_id: str, workflow_id: str, run_id: str,
        next_event_id: int, running: bool = True,
    ) -> None:
        """The persist-path feed (HistoryEngine fires this after every
        durable write): O(1) — records that the workflow's history
        advanced to ``next_event_id``. The NEXT tick fetches just the
        [staged_tip, next_event_id) suffix from the history manager and
        composes it — the O(Δ) append, without any I/O on the persist
        caller's thread. Unseated workflows are a dict miss (admission
        stays read-driven)."""
        with self._lock:
            slot = self._by_key.get((workflow_id, run_id))
            if slot is None:
                return
            lane = self._slots[slot]
            if lane is None:
                return
            # reserved-but-unseated lanes record the debt too: events
            # persisted during the seating window would otherwise be
            # dropped and the fresh lane would serve a stale tip until
            # the workflow's NEXT durable write (possibly never); the
            # post-seat catch-up heals the recorded span instead
            lane.behind_through = max(lane.behind_through, next_event_id)
            if not lane.dirty_since:
                lane.dirty_since = _time.monotonic()
            if not running:
                # close hint: once the debt composes (the close events
                # are in it), the committed row confirms and the
                # on-close eviction recycles the lane
                lane.closed = True

    def _catch_up(self) -> None:
        """Fetch + stage the persist-feed suffixes of behind lanes
        (tick phase 0). History reads run OUTSIDE the lock; a failed
        read leaves the lane behind — retried next tick."""
        if self.history is None:
            return
        fetch: List[Tuple[int, int, Tuple, bytes, int, int]] = []
        with self._lock:
            for slot in range(self.lanes):
                lane = self._slots[slot]
                if (lane is None or not lane.seated
                        or not lane.branch_token
                        or lane.behind_through <= lane.next_staged):
                    continue
                fetch.append((
                    slot, lane.generation, lane.key, lane.branch_token,
                    lane.next_staged, lane.behind_through,
                ))
        for slot, gen, key, token, lo, hi in fetch:
            try:
                batches = self._read_batches(
                    token, min_event_id=lo, max_event_id=hi
                )
                first = next((b for b in batches if b), None)
                if first is None or first[0].event_id > lo:
                    # the node containing ``lo`` starts below it (the
                    # store pages by node id, and an explicit append's
                    # re-chunking can leave the tip mid-node): refetch
                    # from the start; the staging trim below drops the
                    # already-staged prefix
                    batches = self._read_batches(
                        token, max_event_id=hi
                    )
            except Exception:
                continue  # still behind; next tick retries
            released = 0
            with self._lock:
                if self._slot_gen[slot] != gen:
                    continue  # recycled mid-fetch: never lands
                lane = self._slots[slot]
                if lane is None or lane.key != key:
                    continue
                for b in batches:
                    if not b:
                        continue
                    if b[0].event_id < lane.next_staged:
                        b = [
                            e for e in b
                            if e.event_id >= lane.next_staged
                        ]
                        if not b:
                            continue
                    if b[0].event_id > lane.next_staged:
                        # even the start-of-branch refetch cannot
                        # provide [next_staged, b[0]) — the span is
                        # gone from the store (pruned/torn history).
                        # The lane can never heal: composing over the
                        # hole would serve divergent state as resident
                        # truth, so free it — readmit-from-store
                        # recovers whatever the store still has
                        self._release_slot(slot, lane.key)
                        released = 1
                        break
                    lane.pending.append(list(b))
                    lane.pending_events += len(b)
                    lane.next_staged = b[-1].event_id + 1
                if not released and (
                    lane.behind_through <= lane.next_staged
                ):
                    lane.behind_through = 0
            if released:
                self._metrics.inc("serving_compose_failures")

    def _resolve_lane(self, ticket) -> Optional[_Lane]:
        """Under _lock: the live lane a ticket/key addresses, or None.
        Tickets check slot + generation — the recycled-slot guard."""
        if isinstance(ticket, LaneTicket):
            if not 0 <= ticket.lane < self.lanes:
                return None
            if self._slot_gen[ticket.lane] != ticket.generation:
                return None
            lane = self._slots[ticket.lane]
            return lane if lane is not None and lane.seated else None
        slot = self._by_key.get(tuple(ticket))
        if slot is None:
            return None
        lane = self._slots[slot]
        return lane if lane is not None and lane.seated else None

    def tick(self) -> Dict:
        """One serving tick: ONE fused device step composes every
        pending Δ against its lane (affine Δs through the assoc
        algebra, non-affine Δs through the sequential packed scan),
        then eviction/recycle and admission refill. Returns tick
        stats. Ticks SERIALIZE (``_tick_lock``): concurrent callers
        (every dirty read composes-first) queue behind the running
        tick instead of racing its base-row snapshots; Δs staged while
        a tick composes stay pending and ride the next one."""
        with self._tick_lock:
            return self._tick_locked()

    def _tick_locked(self) -> Dict:
        t0 = _time.perf_counter()
        self._catch_up()
        work: List[Tuple[int, int, _Lane, List, ResumeState]] = []
        with self._lock:
            self._tick_no += 1
            tick_no = self._tick_no
            for slot in range(self.lanes):
                lane = self._slots[slot]
                if lane is None or not lane.seated or not lane.pending:
                    continue
                rs = ResumeState(
                    pack=lane.side.resume, side=lane.side,
                    state_row=S.state_row(self._state, slot),
                )
                work.append(
                    (slot, lane.generation, lane, lane.pending, rs)
                )
                lane.pending = []
                lane.pending_events = 0
                lane.last_used = tick_no
        composed, replayed, failures, stale = self._compose(work)
        evicted, recycled, flush_failed = self._evict_and_refill(tick_no)
        dt = _time.perf_counter() - t0
        scope = self._metrics
        scope.inc("serving_ticks")
        scope.record("serving_tick_seconds", dt)
        if composed:
            # batches counted per grid-rounded width, like the
            # dispatcher's batch_width (bounded tag cardinality)
            scope.tagged(width=str(round_scan_len(composed))).inc(
                "serving_append_width"
            )
        if replayed:
            scope.inc("serving_events_replayed", replayed)
        if stale:
            scope.inc("serving_stale_appends", stale)
        if failures:
            scope.inc("serving_compose_failures", failures)
        if evicted:
            scope.inc("serving_evictions", evicted)
        if recycled:
            scope.inc("serving_recycles", recycled)
        if flush_failed:
            scope.inc("serving_flush_failures", flush_failed)
        scope.gauge("serving_lane_occupancy", self.occupancy())
        return {
            "tick": tick_no, "composed": composed,
            "events_replayed": replayed, "evicted": evicted,
            "recycled": recycled, "tick_seconds": dt,
        }

    def _delta_types(self, batches) -> frozenset:
        return frozenset(
            int(e.event_type) for b in batches for e in b
        )

    def _replay(self, packed, scan_mode: str):
        from cadence_tpu.ops.replay import replay_packed_lanes

        return replay_packed_lanes(packed, scan_mode=scan_mode)

    def _compose(self, work) -> Tuple[int, int, int, int]:
        """The fused step: split pending lanes into the affine group
        (assoc algebra) and the sequential-fallback group, pack + run
        each as one device batch, commit rows under the lock."""
        from cadence_tpu.ops.assoc import classify_types

        if not work:
            return 0, 0, 0, 0
        groups: Dict[str, List] = {"auto": [], "scan": []}
        for item in work:
            _, non = classify_types(
                self._delta_types(item[3]), self._affine_types
            )
            groups["scan" if non else "auto"].append(item)
        composed = replayed = failures = stale = 0
        staleness_ms: List[float] = []
        for mode, items in groups.items():
            if not items:
                continue
            histories = [
                (lane.workflow_id, lane.run_id, batches)
                for _, _, lane, batches, _ in items
            ]
            resumes = [rs for *_, rs in items]
            results: List[Optional[Tuple]] = []
            try:
                packed = pack_lanes(
                    histories, caps=self.caps, resume=resumes
                )
                final = self._replay(packed, scan_mode=mode)
                results = [(packed, final, j) for j in range(len(items))]
            except Exception:
                # one malformed Δ must not poison the whole tick:
                # degrade to per-lane composition, fail only the bad one
                for hist, rs in zip(histories, resumes):
                    try:
                        pk = pack_lanes(
                            [hist], caps=self.caps, resume=[rs]
                        )
                        results.append(
                            (pk, self._replay(pk, scan_mode=mode), 0)
                        )
                    except Exception:
                        results.append(None)
            with self._lock:
                for (slot, gen, lane, batches, _), row in zip(
                    items, results
                ):
                    if row is None:
                        # the Δ is unreplayable: free the lane; the
                        # history store remains the source of truth and
                        # a readmit-from-store recovers the workflow.
                        # Generation-checked like the commit branch — a
                        # slot recycled + re-seated mid-step must not
                        # be clobbered (its tenant's _by_key entry
                        # would dangle onto the next occupant)
                        failures += 1
                        if (self._slot_gen[slot] == gen
                                and self._slots[slot] is lane):
                            self._release_slot(slot, lane.key)
                        continue
                    if (self._slot_gen[slot] != gen
                            or self._slots[slot] is not lane):
                        stale += 1  # recycled mid-step: never lands
                        continue
                    packed, final, j = row
                    self._commit_row(slot, lane, packed, final, j)
                    composed += 1
                    replayed += sum(len(b) for b in batches)
                    if lane.dirty_since:
                        # staleness: first-dirty → composed. Reset to
                        # "now" (not 0) when Δs staged mid-compose —
                        # their clock started while this step ran
                        now = _time.monotonic()
                        staleness_ms.append(
                            (now - lane.dirty_since) * 1e3
                        )
                        lane.dirty_since = now if (
                            lane.pending
                            or lane.behind_through > lane.next_staged
                        ) else 0.0
        for ms in staleness_ms:
            self._metrics.record("serving_staleness_ms", ms)
        return composed, replayed, failures, stale

    # ------------------------------------------------------------------
    # eviction / recycle
    # ------------------------------------------------------------------

    def _evict_and_refill(self, tick_no: int) -> Tuple[int, int, int]:
        """LRU-idle + on-close eviction, then admission-queue refill.
        Slots are freed (generation bumped) UNDER the lock; the flush
        itself — store I/O — runs after release."""
        flush: List[Tuple[_Lane, Dict]] = []
        with self._lock:
            for slot in range(self.lanes):
                lane = self._slots[slot]
                if (lane is None or not lane.seated or lane.pending
                        or lane.behind_through > lane.next_staged):
                    continue  # dirty lanes compose before they evict
                idle = tick_no - lane.last_used
                if not lane.closed and idle < self.idle_ticks:
                    continue
                flush.append((lane, S.state_row(self._state, slot)))
                self._release_slot(slot, lane.key)
        flush_failed = 0
        for lane, row in flush:
            if not self._flush_row(lane, row):
                flush_failed += 1
        recycled = 0
        # refill whenever a free slot exists — slots freed by seat/
        # compose failures or an explicit evict() (not just this tick's
        # evictions) must not starve parked admissions. The refill
        # order is the fair scheduler's (weighted + deadline-aged +
        # per-domain quotas): only as many admissions as there are free
        # slots are taken, and a take that fails to seat re-parks at
        # its original age
        with self._lock:
            n_free = sum(1 for s in self._slots if s is None)
            backlog = (
                self._admit_queue.take(n_free) if n_free else []
            )
            ages_ms = [
                self._admit_queue.parked_age_s(e) * 1e3 for e in backlog
            ]
        if backlog:
            # store reads + the bulk admission run OUTSIDE the lock
            reqs = []
            for entry in backlog:
                a = entry.adm
                batches = a.batches
                if self.history is not None and a.branch_token:
                    try:
                        # queue-time batches go stale while the
                        # admission waits (on_persisted is a dict
                        # miss for unseated workflows) — re-read
                        # the tip so a refilled lane never serves
                        # a stale row as resident truth
                        batches = self._read_batches(a.branch_token)
                    except Exception:
                        pass  # queue-time prefix: still consistent
                reqs.append(dict(
                    domain_id=a.domain_id,
                    workflow_id=a.workflow_id, run_id=a.run_id,
                    branch_token=a.branch_token, batches=batches,
                ))
            readmitted = self.admit_many(
                reqs,
                _requeued={e.adm.key: e for e in backlog},
            )
            recycled = sum(
                1 for t in readmitted.values() if t is not None
            )
            # a taken admission whose SEAT REPLAY failed was dropped by
            # admit_many (only the no-free-slot branch re-parks): put
            # it back at its original age so a transient fault storm
            # cannot eat a parked admission's starvation guarantee —
            # bounded attempts so a genuinely poisoned history drops
            # after 3 tries (readmit-from-read stays its recovery path)
            with self._lock:
                for entry in backlog:
                    if (readmitted.get(entry.adm.key) is None
                            and entry.attempts < 3
                            and not self._admit_queue.has_key(
                                entry.adm.key)):
                        self._admit_queue.park(
                            entry.adm, requeued_from=entry
                        )
            # the parked-age distribution at seat time: the starvation
            # observable TestOverloadChaos bounds (aging guarantees a
            # seat within K recycles for any weight assignment)
            for ms, entry in zip(ages_ms, backlog):
                if readmitted.get(entry.adm.key) is not None:
                    self._metrics.record(
                        "serving_admit_starvation_age_ms", ms
                    )
        return len(flush), recycled, flush_failed

    def _flush_row(self, lane: _Lane, row: Dict) -> bool:
        """Flush one evicted lane's row back through the checkpoint
        plane (policy-free write). True when durable — False counts as
        a flush failure but is never fatal: the history store is still
        the source of truth and a readmit cold-replays."""
        if self.checkpoints is None or not lane.branch_token:
            return True
        one = S.empty_state(1, self.caps)
        S.set_state_row(one, 0, row)
        return self.checkpoints.flush(
            lane.branch_token, one, 0, lane.side, epoch_s=lane.epoch_s,
            caps=self.caps, domain_id=lane.domain_id,
            workflow_id=lane.workflow_id, run_id=lane.run_id,
        )

    # ------------------------------------------------------------------
    # reads
    # ------------------------------------------------------------------

    def read(
        self,
        workflow_id: str,
        run_id: str,
        domain_id: str = "",
        branch_token: Optional[bytes] = None,
    ) -> Optional[ResidentRead]:
        """Answer a decision/query read.

        Resident lanes answer straight from the row — no replay, no
        history read. A lane with staged Δs composes first (one tick)
        so reads always reflect acknowledged appends. A miss falls
        through to a cold single-history rebuild when the engine has a
        history manager and the caller names the branch (and counts as
        ``serving_cold_misses``); otherwise None."""
        t0 = _time.perf_counter()
        scope = self._metrics
        out = self.resident_row(workflow_id, run_id, domain_id=domain_id)
        if out is not None:
            scope.inc("serving_resident_hits")
            scope.record(
                "serving_read_seconds", _time.perf_counter() - t0
            )
            return out
        scope.inc("serving_cold_misses")
        return self._cold_read(
            workflow_id, run_id, domain_id, branch_token, t0
        )

    def _cold_read(
        self, workflow_id: str, run_id: str, domain_id: str,
        branch_token: Optional[bytes], t0: float,
    ) -> Optional[ResidentRead]:
        """One-shot cold replay of the full history — the miss path
        shared by ``read`` and ``read_through`` (no lane is touched).
        A history the serving caps cannot pack (capacity overflow /
        malformed stream) returns None — counted
        ``serving_cold_read_failures``, never an exception out of the
        read verb; the rebuild verbs stay the recovery path."""
        from cadence_tpu.ops.unpack import state_row_to_snapshot

        if self.history is None or not branch_token:
            self._metrics.record(
                "serving_read_seconds", _time.perf_counter() - t0
            )
            return None
        try:
            batches = self._read_batches(branch_token)
            packed = pack_lanes(
                [(workflow_id, run_id, batches)], caps=self.caps
            )
            final = self._replay(packed, scan_mode="auto")
        except Exception as e:
            self._log.warn(f"serving cold read failed ({e}); miss")
            self._metrics.inc("serving_cold_read_failures")
            self._metrics.record(
                "serving_read_seconds", _time.perf_counter() - t0
            )
            return None
        row = S.state_row(final, 0)
        one = S.empty_state(1, self.caps)
        S.set_state_row(one, 0, row)
        out = ResidentRead(
            snapshot=state_row_to_snapshot(one, 0, packed.epoch_s),
            side=packed.side[0], epoch_s=packed.epoch_s,
            domain_id=domain_id, resident=False, state_row=row,
            branch_token=branch_token or b"",
        )
        self._metrics.record(
            "serving_read_seconds", _time.perf_counter() - t0
        )
        return out

    def resident_row(
        self, workflow_id: str, run_id: str, domain_id: str = "",
    ) -> Optional[ResidentRead]:
        """The resident view of one seated lane, or None — NO cold
        fallback and no hit/miss accounting (``read`` adds both; the
        rebuilder's serving consult counts its own hits). A dirty lane
        (staged Δs or a persist-feed debt) composes first so the row
        always reflects acknowledged appends."""
        from cadence_tpu.ops.unpack import state_row_to_snapshot

        key = (workflow_id, run_id)
        got = None
        for _ in range(4):
            dirty = False
            with self._lock:
                slot = self._by_key.get(key)
                if slot is not None:
                    lane = self._slots[slot]
                    if lane is not None and lane.seated:
                        if (lane.pending
                                or lane.behind_through > lane.next_staged):
                            dirty = True
                        else:
                            lane.last_used = self._tick_no
                            got = (
                                S.state_row(self._state, slot),
                                lane.side, lane.epoch_s,
                                lane.domain_id, lane.branch_token,
                            )
            if got is not None or not dirty:
                break
            self.tick()
        if got is None:
            return None
        row, side, epoch_s, dom, token = got
        one = S.empty_state(1, self.caps)
        S.set_state_row(one, 0, row)
        return ResidentRead(
            snapshot=state_row_to_snapshot(one, 0, epoch_s),
            side=side, epoch_s=epoch_s,
            domain_id=domain_id or dom, resident=True,
            state_row=row, branch_token=token,
        )

    def read_through(
        self, domain_id: str, workflow_id: str, run_id: str,
        branch_token: bytes,
    ) -> Optional[ResidentRead]:
        """The serving-plane read verb: resident hit, else ADMIT the
        workflow (full-history seat through the dispatcher, suffix-only
        when a checkpoint resumes) and answer from the fresh lane —
        the next read is resident. Falls back to a one-shot cold replay
        when every lane is occupied (the admission queued)."""
        t0 = _time.perf_counter()
        got = self.resident_row(workflow_id, run_id, domain_id=domain_id)
        scope = self._metrics
        if got is not None:
            scope.inc("serving_resident_hits")
            scope.record(
                "serving_read_seconds", _time.perf_counter() - t0
            )
            return got
        scope.inc("serving_cold_misses")
        try:
            batches = self._read_batches(branch_token)
        except Exception:
            batches = None  # unreadable branch: the cold path misses
        ticket = None
        if batches is not None:
            ticket = self.admit(
                domain_id, workflow_id, run_id,
                branch_token=branch_token, batches=batches,
            )
        if ticket is not None:
            got = self.resident_row(
                workflow_id, run_id, domain_id=domain_id
            )
        if got is not None:
            scope.record(
                "serving_read_seconds", _time.perf_counter() - t0
            )
            return got
        return self._cold_read(
            workflow_id, run_id, domain_id, branch_token, t0
        )

    def _read_batches(
        self, branch_token: bytes, min_event_id: int = 1,
        max_event_id: int = 1 << 60,
    ) -> List:
        from cadence_tpu.runtime.persistence.records import BranchToken

        branch = BranchToken.from_json(
            branch_token.decode()
            if isinstance(branch_token, bytes) else str(branch_token)
        )
        out: List = []
        token = 0
        while True:
            batches, token = self.history.read_history_branch(
                branch, max(1, min_event_id), max_event_id,
                page_size=256, next_token=token,
            )
            out.extend(batches)
            if not token:
                return out

    # ------------------------------------------------------------------
    # lifecycle / introspection
    # ------------------------------------------------------------------

    def evict(self, workflow_id: str, run_id: str) -> bool:
        """Explicit eviction (operator/test entry): compose pending,
        flush, free the lane."""
        key = (workflow_id, run_id)
        with self._lock:
            slot = self._by_key.get(key)
            lane = self._slots[slot] if slot is not None else None
            has_pending = lane is not None and bool(
                lane.pending
                or lane.behind_through > lane.next_staged
            )
        if slot is None:
            return False
        if has_pending:
            self.tick()
        flush = None
        with self._lock:
            slot = self._by_key.get(key)
            if slot is None:
                return False
            lane = self._slots[slot]
            flush = (lane, S.state_row(self._state, slot))
            self._release_slot(slot, key)
        ok = self._flush_row(*flush)
        self._metrics.inc("serving_evictions")
        if not ok:
            self._metrics.inc("serving_flush_failures")
        return True

    def drain(self) -> Dict:
        """Shutdown: compose everything pending, flush + free every
        lane. Returns {"flushed", "flush_failed", "queued_dropped"};
        clean means flush_failed == 0 and the engine is empty after."""
        # compose until quiescent (appends racing the drain get one
        # more tick; a live producer should be stopped first)
        for _ in range(8):
            with self._lock:
                dirty = any(
                    l is not None
                    and (l.pending or l.behind_through > l.next_staged)
                    for l in self._slots
                )
            if not dirty:
                break
            self.tick()
        flush: List[Tuple[_Lane, Dict]] = []
        with self._lock:
            for slot in range(self.lanes):
                lane = self._slots[slot]
                if lane is None:
                    continue
                flush.append((lane, S.state_row(self._state, slot)))
                self._release_slot(slot, lane.key)
            queued = self._admit_queue.drain()
        failed = 0
        for lane, row in flush:
            if not self._flush_row(lane, row):
                failed += 1
        if flush:
            self._metrics.inc("serving_evictions", len(flush))
        if failed:
            self._metrics.inc("serving_flush_failures", failed)
        return {
            "flushed": len(flush), "flush_failed": failed,
            "queued_dropped": queued,
        }

    def retune_admission(
        self, quota_rps: float, quota_burst=None
    ) -> None:
        """Live retune of the admission queue's per-domain quota (the
        capacity autopilot's serving-plane actuator)."""
        with self._lock:
            self._admit_queue.set_quota_rps(quota_rps, burst=quota_burst)

    def admission_quota_rps(self) -> float:
        with self._lock:
            return self._admit_queue.policy.quota_rps

    def occupancy(self) -> float:
        with self._lock:
            seated = sum(
                1 for l in self._slots if l is not None and l.seated
            )
        return seated / self.lanes

    def describe(self) -> Dict:
        with self._lock:
            seated = [
                {
                    "lane": i, "workflow_id": l.workflow_id,
                    "run_id": l.run_id, "generation": l.generation,
                    "pending_events": l.pending_events,
                    "closed": l.closed, "last_used": l.last_used,
                }
                for i, l in enumerate(self._slots)
                if l is not None
            ]
            queued = len(self._admit_queue)
            tick = self._tick_no
        return {
            "lanes": self.lanes, "seated": len(seated),
            "queued": queued, "tick": tick, "lanes_detail": seated,
        }
