"""Continuous-batching serving engine: device-resident hot state with
O(Δ) replay-on-append.

``ResidentEngine`` (engine.py) owns a fixed-shape resident state tensor
of S lanes and applies per-append suffix compositions in one fused
device step per tick — LLM-style continuous batching for workflow
replay. ``harness.py`` is the open-loop SLO load harness (Poisson /
bursty arrival processes at sustained QPS through token buckets).
"""

from .engine import (
    LaneTicket,
    ResidentEngine,
    ResidentRead,
)
from .harness import ArrivalProcess, OpenLoopHarness, ServeWorkload

__all__ = [
    "ArrivalProcess",
    "LaneTicket",
    "OpenLoopHarness",
    "ResidentEngine",
    "ResidentRead",
    "ServeWorkload",
]
