"""Continuous-batching serving engine: device-resident hot state with
O(Δ) replay-on-append.

``ResidentEngine`` (engine.py) owns a fixed-shape resident state tensor
of S lanes and applies per-append suffix compositions in one fused
device step per tick — LLM-style continuous batching for workflow
replay. ``admission.py`` is the fair admission scheduler (weighted +
deadline-aged + per-domain-quota'd refill of freed lanes). ``pump.py``
is the background tick pump bounding resident-row staleness for
write-heavy lanes. ``harness.py`` is the open-loop SLO load harness
(Poisson / bursty arrival processes at sustained QPS through token
buckets, with retry-budgeted re-offers of shed arrivals).
"""

from .admission import AdmissionPolicy, FairAdmissionQueue
from .engine import (
    LaneTicket,
    ResidentEngine,
    ResidentRead,
)
from .harness import ArrivalProcess, OpenLoopHarness, ServeWorkload
from .pump import TickPump

__all__ = [
    "AdmissionPolicy",
    "ArrivalProcess",
    "FairAdmissionQueue",
    "LaneTicket",
    "OpenLoopHarness",
    "ResidentEngine",
    "ResidentRead",
    "ServeWorkload",
    "TickPump",
]
