"""Fair admission for the resident serving engine: weighted,
starvation-free refill with per-domain quotas and deadline aging.

PR 14's admission queue was a FIFO list drained whole at every recycle:
one chatty domain arriving first could occupy every freed lane for as
long as its backlog lasted, and a parked admission behind it aged
without bound. This module replaces it with the lane-refill fairness
discipline of vectorized-MCMC continuous batching ("Efficiently
Vectorized MCMC on Modern Accelerators", PAPERS.md): freed slots are a
scarce fixed-shape resource, and the refill order decides whether every
chain (here: every domain) keeps making progress.

Policy, per freed slot:

* every domain with parked admissions bids its HEAD entry (per-domain
  order stays FIFO — reordering inside a domain would starve its own
  oldest work);
* a bid's priority is ``weight(domain) + aging_boost × age`` where age
  counts refill rounds parked — so a parked admission's priority grows
  WITHOUT BOUND and must eventually exceed any fixed weight: seating
  within K recycles is guaranteed for ANY weight assignment (K ≤
  starvation_recycles + (max_weight − min_weight) / aging_boost +
  #domains for a single-slot refill — the property test's bound);
* a per-domain token-bucket quota gates how fast one domain may consume
  freed slots; a quota-rejected domain is SKIPPED, not waited on, so a
  quota-exhausted domain can never block a quota-available one;
* aging overrides quota: a bid parked ≥ ``starvation_recycles`` rounds
  seats regardless of its domain's bucket (bounded unfairness beats
  unbounded starvation — the same reasoning as deadline-aged I/O
  schedulers).

Concurrency: the queue does NOT own a lock. The owning ResidentEngine
passes its engine lock in as the guard, every verb documents "caller
holds the engine lock", and the parked table is declared through
``utils/locks.make_guarded`` (+ ``race_witness.GUARDED_FIELDS``) so the
sanitizer proves the discipline at runtime instead of trusting it.
"""

from __future__ import annotations

import dataclasses
import time as _time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from cadence_tpu.utils import locks
from cadence_tpu.utils.quotas import TokenBucket


@dataclasses.dataclass
class AdmissionPolicy:
    """The ``serving:`` section's fairness knobs.

    ``domain_weights`` maps domain → base priority weight (missing
    domains use ``default_weight``); ``quota_rps``/``quota_burst`` size
    each domain's refill token bucket (0 = unmetered); ``aging_boost``
    is priority gained per refill round parked; ``starvation_recycles``
    is the age at which a bid bypasses its domain quota entirely."""

    domain_weights: Dict[str, float] = dataclasses.field(
        default_factory=dict
    )
    default_weight: float = 1.0
    quota_rps: float = 0.0
    quota_burst: int = 0
    aging_boost: float = 1.0
    starvation_recycles: int = 8

    def validate(self) -> None:
        if self.default_weight <= 0:
            raise ValueError("admission: default_weight must be > 0")
        for dom, w in self.domain_weights.items():
            if w <= 0:
                raise ValueError(
                    f"admission: weight for domain '{dom}' must be > 0"
                )
        if self.quota_rps < 0 or self.quota_burst < 0:
            raise ValueError("admission: negative quota")
        if self.aging_boost <= 0:
            # zero aging would reintroduce unbounded starvation for
            # low-weight domains — the exact failure this replaces
            raise ValueError("admission: aging_boost must be > 0")
        if self.starvation_recycles < 1:
            raise ValueError(
                "admission: starvation_recycles must be >= 1"
            )

    def weight(self, domain_id: str) -> float:
        return self.domain_weights.get(domain_id, self.default_weight)


class _Parked:
    """One parked admission + its aging bookkeeping. ``attempts``
    counts failed seat attempts (a taken entry whose replay failed and
    came back) so a poisoned history cannot re-park forever."""

    __slots__ = ("adm", "enq_round", "enq_t", "attempts")

    def __init__(self, adm, enq_round: int, enq_t: float,
                 attempts: int = 0) -> None:
        self.adm = adm
        self.enq_round = enq_round
        self.enq_t = enq_t
        self.attempts = attempts


class FairAdmissionQueue:
    """Per-domain parked admissions + the weighted/aged/quota'd refill.

    Every verb below MUST be called with the guard lock (the engine
    lock) held — this class never blocks and never acquires."""

    def __init__(
        self,
        policy: Optional[AdmissionPolicy],
        guard,
        clock=_time.monotonic,
    ) -> None:
        self.policy = policy or AdmissionPolicy()
        self.policy.validate()
        self._clock = clock
        # domain → FIFO list of _Parked (head bids at refill)
        self._parked: Dict[str, List[_Parked]] = locks.make_guarded(
            {}, "FairAdmissionQueue._parked", guard
        )
        # per-domain refill quota buckets: LRU-bounded like the
        # MultiStage limiter's domain table (churn of short-lived
        # domains cannot grow it). Buckets SURVIVE the backlog
        # emptying — dropping one there would refund a full burst to
        # any domain whose queue oscillates to empty between recycles,
        # letting it consume freed slots far above quotaRps
        self._quota: "OrderedDict[str, TokenBucket]" = OrderedDict()
        self._max_quota_domains = 1024
        self._round = 0
        self._count = 0

    # -- parking -------------------------------------------------------

    def park(self, adm, requeued_from: Optional[_Parked] = None) -> None:
        """Caller holds the guard. ``requeued_from``: the original
        parked entry when a taken admission failed to seat and comes
        back — its age is preserved (and its attempt count bumped) so
        re-queueing can never reset the starvation clock."""
        entry = _Parked(
            adm,
            requeued_from.enq_round if requeued_from is not None
            else self._round,
            requeued_from.enq_t if requeued_from is not None
            else self._clock(),
            attempts=(
                requeued_from.attempts + 1
                if requeued_from is not None else 0
            ),
        )
        self._parked.setdefault(adm.domain_id, []).append(entry)
        self._count += 1

    def has_key(self, key) -> bool:
        """Caller holds the guard: is an admission with this
        (workflow_id, run_id) key currently parked?"""
        return any(
            e.adm.key == key
            for entries in self._parked.values()
            for e in entries
        )

    # -- the refill ----------------------------------------------------

    def take(self, n: int) -> List[_Parked]:
        """Caller holds the guard. Pop up to ``n`` parked admissions in
        fairness order; advances the aging round once per call (a call
        == one recycle round)."""
        self._round += 1
        pol = self.policy
        out: List[_Parked] = []
        while len(out) < n and self._count:
            bids: List[Tuple[float, int, str]] = []
            for dom, entries in self._parked.items():
                if not entries:
                    continue
                head = entries[0]
                age = self._round - head.enq_round
                bids.append((
                    pol.weight(dom) + pol.aging_boost * age, age, dom,
                ))
            if not bids:
                break
            # highest priority first; FIFO (older round) breaks ties
            bids.sort(key=lambda b: (-b[0], -b[1], b[2]))
            seated_one = False
            for _, age, dom in bids:
                if len(out) >= n:
                    break
                if (pol.quota_rps > 0
                        and age < pol.starvation_recycles
                        and not self._quota_bucket(dom).allow()):
                    continue  # skipped, never waited on
                entries = self._parked[dom]
                out.append(entries.pop(0))
                self._count -= 1
                seated_one = True
                if not entries:
                    del self._parked[dom]
            if not seated_one:
                break  # every remaining bid is quota-parked this round
        return out

    def set_quota_rps(
        self, rps: float, burst: Optional[int] = None
    ) -> None:
        """Caller holds the guard. Live retune of the per-domain refill
        quota (the autopilot's serving actuator): updates the policy so
        future buckets mint at the new rate, and ``set_rate``s every
        existing bucket so retuning takes effect this recycle, not at
        the next domain-table miss."""
        if rps < 0:
            raise ValueError("admission: negative quota")
        self.policy.quota_rps = float(rps)
        if burst is not None:
            self.policy.quota_burst = int(burst)
        for bucket in self._quota.values():
            bucket.set_rate(rps, burst=burst)

    def _quota_bucket(self, dom: str) -> TokenBucket:
        bucket = self._quota.get(dom)
        if bucket is None:
            pol = self.policy
            bucket = self._quota[dom] = TokenBucket(
                pol.quota_rps,
                burst=pol.quota_burst or None,
                clock=self._clock,
            )
            while len(self._quota) > self._max_quota_domains:
                self._quota.popitem(last=False)
        else:
            self._quota.move_to_end(dom)
        return bucket

    # -- introspection / drain -----------------------------------------

    def __len__(self) -> int:
        return self._count

    def parked_age_s(self, entry: _Parked) -> float:
        return max(0.0, self._clock() - entry.enq_t)

    def oldest_age_rounds(self) -> int:
        """Caller holds the guard: the oldest bid's age in refill
        rounds (the starvation gauge's input)."""
        oldest = 0
        for entries in self._parked.values():
            if entries:
                oldest = max(oldest, self._round - entries[0].enq_round)
        return oldest

    def drain(self) -> int:
        """Caller holds the guard: drop everything (shutdown)."""
        n = self._count
        self._parked.clear()
        self._quota.clear()
        self._count = 0
        return n
