"""Open-loop SLO load harness for the resident serving engine.

Closed-loop benches (issue N requests, wait, repeat) hide queueing:
when the server slows down, the load generator slows down with it and
the reported latency stays flat. This harness is OPEN-LOOP — arrival
times are drawn from an arrival process (Poisson or bursty) at a
sustained target QPS BEFORE the run starts, and every request's
latency is measured from its SCHEDULED arrival to completion, so
falling behind shows up as queueing delay in the p99, exactly as it
would for real users.

Admission rides the existing token buckets (``utils/quotas``): a
request the bucket rejects counts as shed load, not latency. The
overload control plane (ISSUE 15) adds the domain-aware shape: a
``MultiStageRateLimiter`` admits per (domain, global) budget, and a
rejected arrival may RE-OFFER itself after the limiter's retry-after
hint — but only while the ``RetryBudget`` (success-refilled) has
tokens, so the harness reproduces exactly the client discipline that
keeps total offered load bounded instead of amplifying the overload.
Latency for a retried arrival still counts from its ORIGINAL scheduled
time — retries are honest queueing delay, not a fresh clock.

Per-arrival shape (the serving hot path): ``append(Δ)`` → engine tick
(all due arrivals in one fused step — continuous batching) →
``read()``; the decision latency histogram lands in the PR 9
exponential-bucket registry (``Registry.timer_stats``), which is where
the reported p50/p99 come from (tagged ``domain=`` so per-domain p99
is one ``timer_stats(tags=...)`` away).
"""

from __future__ import annotations

import dataclasses
import heapq
import random
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cadence_tpu.utils.metrics import NOOP, Scope
from cadence_tpu.utils.quotas import RetryBudget, TokenBucket


@dataclasses.dataclass(frozen=True)
class ArrivalProcess:
    """Deterministic (seeded) open-loop arrival schedule.

    ``kind``: ``poisson`` (exponential inter-arrivals at ``qps``) or
    ``bursty`` (Poisson base with ``burst_factor``× rate inside
    periodic burst windows covering ``burst_frac`` of the run — the
    thundering-herd shape an SLO has to survive)."""

    qps: float
    kind: str = "poisson"
    seed: int = 0
    burst_factor: float = 4.0
    burst_frac: float = 0.2
    burst_period_s: float = 1.0

    def validate(self) -> None:
        if self.qps <= 0:
            raise ValueError("arrival process: qps must be > 0")
        if self.kind not in ("poisson", "bursty"):
            raise ValueError(
                f"arrival process: unknown kind '{self.kind}'"
            )
        if self.kind == "bursty":
            if not 0.0 < self.burst_frac < 1.0:
                raise ValueError(
                    "arrival process: burst_frac must be in (0, 1)"
                )
            if self.burst_factor <= 1.0:
                raise ValueError(
                    "arrival process: burst_factor must be > 1"
                )

    def schedule(self, n: int) -> List[float]:
        """The first ``n`` arrival offsets (seconds from start)."""
        self.validate()
        rng = random.Random(self.seed)
        out: List[float] = []
        t = 0.0
        while len(out) < n:
            if self.kind == "poisson":
                rate = self.qps
            else:
                # burst windows: [0, burst_frac) of every period runs
                # at burst_factor × the off-window rate; the average
                # over a period is the target qps
                f, k = self.burst_frac, self.burst_factor
                base = self.qps / (f * k + (1.0 - f))
                in_burst = (t % self.burst_period_s) < (
                    f * self.burst_period_s
                )
                rate = base * (k if in_burst else 1.0)
            t += rng.expovariate(rate)
            out.append(t)
        return out


@dataclasses.dataclass
class ServeWorkload:
    """One workflow's serve trajectory: the admit prefix plus the Δ
    suffixes the open-loop arrivals will append, in order."""

    domain_id: str
    workflow_id: str
    run_id: str
    branch_token: bytes
    prefix: List            # batches replayed at admit
    deltas: List[List]      # per-arrival Δ (each a list of batches)

    @property
    def total_events(self) -> int:
        return sum(len(b) for b in self.prefix) + sum(
            len(b) for d in self.deltas for b in d
        )


class OpenLoopHarness:
    """Drive a ResidentEngine with an open-loop arrival schedule.

    ``run()`` admits every workload (the warm phase — bulk, through
    the dispatcher), then walks the arrival schedule: all arrivals due
    by "now" append their Δs, ONE engine tick composes them (the
    continuous batch), and each request's read completes it. Latency
    is recorded scheduled-arrival → read-complete into
    ``metrics.timer("serve_decision")``.

    Overload controls (all optional, all off by default):

    * ``admission_bucket`` — the PR 14 single token bucket;
    * ``limiter`` — a ``MultiStageRateLimiter``: per-domain + global
      admission, shed responses carry its retry-after hint;
    * ``retry_budget`` — a ``RetryBudget``: a rejected arrival
      re-offers itself at now + retry-after while the budget holds;
      exhausted, it sheds permanently (``retry_budget_exhausted``).
    """

    def __init__(
        self,
        engine,
        workloads: Sequence[ServeWorkload],
        process: ArrivalProcess,
        metrics: Optional[Scope] = None,
        admission_bucket: Optional[TokenBucket] = None,
        limiter=None,
        retry_budget: Optional[RetryBudget] = None,
        clock: Callable[[], float] = _time.monotonic,
        sleep: Callable[[float], None] = _time.sleep,
        max_wait_s: float = 0.25,
    ) -> None:
        self.engine = engine
        self.workloads = list(workloads)
        self.process = process
        self.metrics = (
            metrics if metrics is not None else NOOP
        ).tagged(layer="serving_harness")
        self.bucket = admission_bucket
        self.limiter = limiter
        self.retry_budget = retry_budget
        self._clock = clock
        self._sleep = sleep
        self._max_wait_s = max_wait_s

    def admit_all(self) -> Dict:
        """Warm phase: seat every workload in one bulk admission."""
        tickets = self.engine.admit_many([
            dict(domain_id=w.domain_id, workflow_id=w.workflow_id,
                 run_id=w.run_id, branch_token=w.branch_token,
                 batches=w.prefix)
            for w in self.workloads
        ])
        return tickets

    @staticmethod
    def _through(w: ServeWorkload, k: int) -> List:
        """The full event stream up to and including Δ ``k`` — the
        re-seat batches after a shed/stale gap."""
        return list(w.prefix) + [
            b for d in w.deltas[: k + 1] for b in d
        ]

    # -- admission controls --------------------------------------------

    def _admitted(self, domain_id: str) -> bool:
        if self.limiter is not None and not self.limiter.allow(domain_id):
            return False
        if self.bucket is not None and not self.bucket.allow():
            return False
        return True

    def _retry_after_s(self, domain_id: str) -> float:
        hint = 0.0
        if self.limiter is not None:
            hint = self.limiter.retry_after_s(domain_id)
        elif self.bucket is not None:
            get = getattr(self.bucket, "retry_after_s", None)
            if get is not None:
                hint = get()
        # floor at one mean inter-arrival so a zero hint cannot busy-
        # spin the re-offer against a still-saturated bucket
        return max(hint, 1.0 / self.process.qps)

    def run(self) -> Dict:
        """The open-loop drive; returns the run's SLO stats."""
        tickets = self.admit_all()
        # one arrival per available Δ, round-robin over workloads
        order: List[Tuple[ServeWorkload, List, int]] = []
        max_deltas = max(
            (len(w.deltas) for w in self.workloads), default=0
        )
        for k in range(max_deltas):
            for w in self.workloads:
                if k < len(w.deltas):
                    order.append((w, w.deltas[k], k))
        schedule = self.process.schedule(len(order))
        # the live arrival queue: (due time, seq, arrival index).
        # Retries re-push the same index at now + retry-after; latency
        # ALWAYS measures from schedule[i], the original arrival
        heap: List[Tuple[float, int, int]] = [
            (schedule[i], i, i) for i in range(len(order))
        ]
        seq = len(order)
        t_start = self._clock()
        shed = completed = retries = 0
        offered = len(order)
        domains: Dict[str, Dict[str, int]] = {}

        def dom_stats(d: str) -> Dict[str, int]:
            s = domains.get(d)
            if s is None:
                s = domains[d] = {
                    "completed": 0, "shed": 0, "retries": 0,
                }
            return s

        def reject(i: int, w: ServeWorkload, now: float) -> None:
            """One rejection — limiter shed, failed seat, or a lane
            lost between append and read: re-offer at now + the
            retry-after hint while the budget holds, else shed
            permanently. Python's closure-over-nonlocal keeps the
            three call sites honest about the same accounting."""
            nonlocal shed, retries, offered, seq
            self.metrics.inc("serve_shed")
            budget = self.retry_budget
            if budget is not None and budget.can_retry():
                retries += 1
                offered += 1
                dom_stats(w.domain_id)["retries"] += 1
                seq += 1
                heapq.heappush(heap, (
                    now + self._retry_after_s(w.domain_id), seq, i,
                ))
            else:
                if budget is not None:
                    self.metrics.inc("retry_budget_exhausted")
                shed += 1
                dom_stats(w.domain_id)["shed"] += 1

        while heap:
            now = self._clock() - t_start
            if heap[0][0] > now:
                self._sleep(
                    min(heap[0][0] - now, self._max_wait_s)
                )
                continue
            # continuous batch: every arrival due by now appends first,
            # then ONE tick composes all of them
            due: List[Tuple[int, ServeWorkload]] = []
            processed = 0
            while heap and heap[0][0] <= now:
                processed += 1
                _, _, i = heapq.heappop(heap)
                w, delta, k = order[i]
                if not self._admitted(w.domain_id):
                    # shed-then-retry: back off by the limiter's hint,
                    # re-offer at the same arrival index
                    reject(i, w, now)
                    continue
                key = (w.workflow_id, w.run_id)
                t = tickets.get(key)
                if t is None:
                    # queued admission: retry the seat at THIS
                    # arrival's position (earlier arrivals may have
                    # been shed while unseated — seating the bare
                    # prefix would leave a permanent gap)
                    t = self.engine.admit(
                        w.domain_id, w.workflow_id, w.run_id,
                        branch_token=w.branch_token,
                        batches=self._through(w, k),
                    )
                    tickets[key] = t
                    ok = t is not None
                elif not self.engine.append(t, delta):
                    # stale ticket (recycled lane) or the gap a shed
                    # arrival left behind: re-seat at this position —
                    # the O(depth) re-admit is honest latency, never a
                    # frozen lane or divergent resident state
                    self.engine.evict(w.workflow_id, w.run_id)
                    t = self.engine.admit(
                        w.domain_id, w.workflow_id, w.run_id,
                        branch_token=w.branch_token,
                        batches=self._through(w, k),
                    )
                    tickets[key] = t
                    ok = t is not None
                else:
                    ok = True
                if not ok:
                    # every lane occupied: the admission parked in the
                    # engine's fair queue — the arrival re-offers and
                    # meets its seated lane at a later refill
                    reject(i, w, now)
                    continue
                due.append((i, w))
            if not due:
                if processed:
                    # rejected-only round: still drive one tick so
                    # eviction + fair-queue refill progress — an
                    # all-parked cohort would otherwise livelock
                    # (no completion → no tick → no refill → every
                    # re-offer parks again, forever)
                    self.engine.tick()
                continue
            self.engine.tick()
            for j, w in due:
                got = self.engine.read(w.workflow_id, w.run_id)
                t_read = self._clock() - t_start
                if got is None:
                    # the LRU recycled this lane between the arrival's
                    # append and its read (aggressive idle horizons
                    # under overload churn — the re-seat ticks of
                    # OTHER arrivals in the same batch age it out):
                    # the arrival re-offers like any shed, its Δ
                    # duplicate-trims on the healed lane
                    reject(j, w, self._clock() - t_start)
                    continue
                # open-loop latency: scheduled arrival → read done
                # (queueing delay from falling behind — and retry
                # backoff — is IN the number)
                self.metrics.tagged(domain=w.domain_id).record(
                    "serve_decision", t_read - schedule[j]
                )
                completed += 1
                dom_stats(w.domain_id)["completed"] += 1
                if self.retry_budget is not None:
                    self.retry_budget.record_success()
        wall = self._clock() - t_start
        return {
            "requests": len(order),
            "completed": completed,
            "shed": shed,
            "retries": retries,
            # total offered load = arrivals + retries: the retry-budget
            # boundedness observable (offered / requests stays near 1 +
            # budget even under sustained rejection)
            "offered": offered,
            "wall_s": wall,
            "qps_sustained": completed / wall if wall > 0 else 0.0,
            "qps_target": self.process.qps,
            "domains": domains,
        }
