"""Open-loop SLO load harness for the resident serving engine.

Closed-loop benches (issue N requests, wait, repeat) hide queueing:
when the server slows down, the load generator slows down with it and
the reported latency stays flat. This harness is OPEN-LOOP — arrival
times are drawn from an arrival process (Poisson or bursty) at a
sustained target QPS BEFORE the run starts, and every request's
latency is measured from its SCHEDULED arrival to completion, so
falling behind shows up as queueing delay in the p99, exactly as it
would for real users.

Admission rides the existing token buckets (``utils/quotas``): a
request the bucket rejects counts as shed load, not latency.

Per-arrival shape (the serving hot path): ``append(Δ)`` → engine tick
(all due arrivals in one fused step — continuous batching) →
``read()``; the decision latency histogram lands in the PR 9
exponential-bucket registry (``Registry.timer_stats``), which is where
the reported p50/p99 come from.
"""

from __future__ import annotations

import dataclasses
import random
import time as _time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from cadence_tpu.utils.metrics import NOOP, Scope
from cadence_tpu.utils.quotas import TokenBucket


@dataclasses.dataclass(frozen=True)
class ArrivalProcess:
    """Deterministic (seeded) open-loop arrival schedule.

    ``kind``: ``poisson`` (exponential inter-arrivals at ``qps``) or
    ``bursty`` (Poisson base with ``burst_factor``× rate inside
    periodic burst windows covering ``burst_frac`` of the run — the
    thundering-herd shape an SLO has to survive)."""

    qps: float
    kind: str = "poisson"
    seed: int = 0
    burst_factor: float = 4.0
    burst_frac: float = 0.2
    burst_period_s: float = 1.0

    def validate(self) -> None:
        if self.qps <= 0:
            raise ValueError("arrival process: qps must be > 0")
        if self.kind not in ("poisson", "bursty"):
            raise ValueError(
                f"arrival process: unknown kind '{self.kind}'"
            )
        if self.kind == "bursty":
            if not 0.0 < self.burst_frac < 1.0:
                raise ValueError(
                    "arrival process: burst_frac must be in (0, 1)"
                )
            if self.burst_factor <= 1.0:
                raise ValueError(
                    "arrival process: burst_factor must be > 1"
                )

    def schedule(self, n: int) -> List[float]:
        """The first ``n`` arrival offsets (seconds from start)."""
        self.validate()
        rng = random.Random(self.seed)
        out: List[float] = []
        t = 0.0
        while len(out) < n:
            if self.kind == "poisson":
                rate = self.qps
            else:
                # burst windows: [0, burst_frac) of every period runs
                # at burst_factor × the off-window rate; the average
                # over a period is the target qps
                f, k = self.burst_frac, self.burst_factor
                base = self.qps / (f * k + (1.0 - f))
                in_burst = (t % self.burst_period_s) < (
                    f * self.burst_period_s
                )
                rate = base * (k if in_burst else 1.0)
            t += rng.expovariate(rate)
            out.append(t)
        return out


@dataclasses.dataclass
class ServeWorkload:
    """One workflow's serve trajectory: the admit prefix plus the Δ
    suffixes the open-loop arrivals will append, in order."""

    domain_id: str
    workflow_id: str
    run_id: str
    branch_token: bytes
    prefix: List            # batches replayed at admit
    deltas: List[List]      # per-arrival Δ (each a list of batches)

    @property
    def total_events(self) -> int:
        return sum(len(b) for b in self.prefix) + sum(
            len(b) for d in self.deltas for b in d
        )


class OpenLoopHarness:
    """Drive a ResidentEngine with an open-loop arrival schedule.

    ``run()`` admits every workload (the warm phase — bulk, through
    the dispatcher), then walks the arrival schedule: all arrivals due
    by "now" append their Δs, ONE engine tick composes them (the
    continuous batch), and each request's read completes it. Latency
    is recorded scheduled-arrival → read-complete into
    ``metrics.timer("serve_decision")``.
    """

    def __init__(
        self,
        engine,
        workloads: Sequence[ServeWorkload],
        process: ArrivalProcess,
        metrics: Optional[Scope] = None,
        admission_bucket: Optional[TokenBucket] = None,
        clock: Callable[[], float] = _time.monotonic,
        sleep: Callable[[float], None] = _time.sleep,
        max_wait_s: float = 0.25,
    ) -> None:
        self.engine = engine
        self.workloads = list(workloads)
        self.process = process
        self.metrics = (
            metrics if metrics is not None else NOOP
        ).tagged(layer="serving_harness")
        self.bucket = admission_bucket
        self._clock = clock
        self._sleep = sleep
        self._max_wait_s = max_wait_s

    def admit_all(self) -> Dict:
        """Warm phase: seat every workload in one bulk admission."""
        tickets = self.engine.admit_many([
            dict(domain_id=w.domain_id, workflow_id=w.workflow_id,
                 run_id=w.run_id, branch_token=w.branch_token,
                 batches=w.prefix)
            for w in self.workloads
        ])
        return tickets

    @staticmethod
    def _through(w: ServeWorkload, k: int) -> List:
        """The full event stream up to and including Δ ``k`` — the
        re-seat batches after a shed/stale gap."""
        return list(w.prefix) + [
            b for d in w.deltas[: k + 1] for b in d
        ]

    def run(self) -> Dict:
        """The open-loop drive; returns the run's SLO stats."""
        tickets = self.admit_all()
        # one arrival per available Δ, round-robin over workloads
        order: List[Tuple[ServeWorkload, List, int]] = []
        max_deltas = max(
            (len(w.deltas) for w in self.workloads), default=0
        )
        for k in range(max_deltas):
            for w in self.workloads:
                if k < len(w.deltas):
                    order.append((w, w.deltas[k], k))
        schedule = self.process.schedule(len(order))
        t_start = self._clock()
        shed = completed = 0
        latencies_recorded = 0
        i = 0
        while i < len(order):
            now = self._clock() - t_start
            if schedule[i] > now:
                self._sleep(
                    min(schedule[i] - now, self._max_wait_s)
                )
                continue
            # continuous batch: every arrival due by now appends first,
            # then ONE tick composes all of them
            due: List[Tuple[int, ServeWorkload]] = []
            while i < len(order) and schedule[i] <= now:
                w, delta, k = order[i]
                if self.bucket is not None and not self.bucket.allow():
                    shed += 1
                    self.metrics.inc("serve_shed")
                    i += 1
                    continue
                key = (w.workflow_id, w.run_id)
                t = tickets.get(key)
                if t is None:
                    # queued admission: retry the seat at THIS
                    # arrival's position (earlier arrivals may have
                    # been shed while unseated — seating the bare
                    # prefix would leave a permanent gap)
                    t = self.engine.admit(
                        w.domain_id, w.workflow_id, w.run_id,
                        branch_token=w.branch_token,
                        batches=self._through(w, k),
                    )
                    tickets[key] = t
                    ok = t is not None
                elif not self.engine.append(t, delta):
                    # stale ticket (recycled lane) or the gap a shed
                    # arrival left behind: re-seat at this position —
                    # the O(depth) re-admit is honest latency, never a
                    # frozen lane or divergent resident state
                    self.engine.evict(w.workflow_id, w.run_id)
                    t = self.engine.admit(
                        w.domain_id, w.workflow_id, w.run_id,
                        branch_token=w.branch_token,
                        batches=self._through(w, k),
                    )
                    tickets[key] = t
                    ok = t is not None
                else:
                    ok = True
                if not ok:
                    shed += 1
                    self.metrics.inc("serve_shed")
                    i += 1
                    continue
                due.append((i, w))
                i += 1
            if not due:
                continue
            self.engine.tick()
            for j, w in due:
                got = self.engine.read(w.workflow_id, w.run_id)
                t_read = self._clock() - t_start
                assert got is not None, (
                    f"resident read lost {w.workflow_id}"
                )
                # open-loop latency: scheduled arrival → read done
                # (queueing delay from falling behind is IN the number)
                self.metrics.record(
                    "serve_decision", t_read - schedule[j]
                )
                latencies_recorded += 1
                completed += 1
        wall = self._clock() - t_start
        return {
            "requests": len(order),
            "completed": completed,
            "shed": shed,
            "wall_s": wall,
            "qps_sustained": completed / wall if wall > 0 else 0.0,
            "qps_target": self.process.qps,
        }
