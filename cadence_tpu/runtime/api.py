"""Public API request/response types + service errors.

The wire-model subset of the reference's shared.thrift the runtime
speaks (StartWorkflowExecutionRequest etc., workflowHandler.go request
validation). Decisions carry their attributes as plain dicts keyed
exactly like the corresponding event attributes — the same convention
the event model uses."""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from cadence_tpu.core.enums import DecisionType, IDReusePolicy
from cadence_tpu.core.events import HistoryEvent, RetryPolicy


# -- errors ---------------------------------------------------------------


class ServiceError(Exception):
    pass


class BadRequestError(ServiceError):
    pass


class EntityNotExistsServiceError(ServiceError):
    pass


class WorkflowExecutionAlreadyStartedServiceError(ServiceError):
    def __init__(self, msg: str, start_request_id: str = "", run_id: str = ""):
        super().__init__(msg)
        self.start_request_id = start_request_id
        self.run_id = run_id


class DomainNotActiveError(ServiceError):
    def __init__(self, msg: str, active_cluster: str = ""):
        super().__init__(msg)
        self.active_cluster = active_cluster


class CancellationAlreadyRequestedError(ServiceError):
    pass


class QueryFailedError(ServiceError):
    pass


class InternalServiceError(ServiceError):
    pass


class ServiceBusyError(ServiceError):
    """Rate limit / overload shed. RETRYABLE: carries a
    ``retry_after_s`` hint (derived from the rejecting bucket's refill
    horizon or the admission queue depth) so clients back off for the
    right interval instead of hammering a saturated stage."""

    def __init__(self, msg: str = "", retry_after_s: float = 0.0) -> None:
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


# -- requests -------------------------------------------------------------


@dataclasses.dataclass
class StartWorkflowRequest:
    domain: str
    workflow_id: str
    workflow_type: str
    task_list: str
    execution_start_to_close_timeout_seconds: int
    task_start_to_close_timeout_seconds: int = 10
    input: bytes = b""
    identity: str = ""
    request_id: str = ""
    workflow_id_reuse_policy: IDReusePolicy = IDReusePolicy.AllowDuplicateFailedOnly
    retry_policy: Optional[RetryPolicy] = None
    cron_schedule: str = ""
    memo: Optional[Dict[str, bytes]] = None
    search_attributes: Optional[Dict[str, bytes]] = None
    # parent execution (set when started as a child workflow by the
    # transfer queue; reference: historyEngine StartWorkflowExecution
    # with ParentExecutionInfo)
    parent_domain: str = ""
    parent_workflow_id: str = ""
    parent_run_id: str = ""
    parent_initiated_id: int = 0

    def validate(self) -> None:
        if not self.domain:
            raise BadRequestError("domain is not set")
        if not self.workflow_id:
            raise BadRequestError("workflowId is not set")
        if not self.workflow_type:
            raise BadRequestError("workflowType is not set")
        if not self.task_list:
            raise BadRequestError("taskList is not set")
        if self.execution_start_to_close_timeout_seconds <= 0:
            raise BadRequestError(
                "executionStartToCloseTimeoutSeconds must be positive"
            )
        if self.task_start_to_close_timeout_seconds <= 0:
            raise BadRequestError(
                "taskStartToCloseTimeoutSeconds must be positive"
            )
        if self.retry_policy is not None:
            from cadence_tpu.utils.backoff import validate_retry_policy

            try:
                validate_retry_policy(self.retry_policy)
            except (ValueError, TypeError) as e:
                raise BadRequestError(str(e))


@dataclasses.dataclass
class SignalRequest:
    domain: str
    workflow_id: str
    run_id: str = ""
    signal_name: str = ""
    input: bytes = b""
    identity: str = ""
    request_id: str = ""

    def validate(self) -> None:
        if not self.domain:
            raise BadRequestError("domain is not set")
        if not self.workflow_id:
            raise BadRequestError("workflowId is not set")
        if not self.signal_name:
            raise BadRequestError("signalName is not set")


@dataclasses.dataclass
class SignalWithStartRequest:
    start: StartWorkflowRequest
    signal_name: str = ""
    signal_input: bytes = b""

    def validate(self) -> None:
        self.start.validate()
        if not self.signal_name:
            raise BadRequestError("signalName is not set")


@dataclasses.dataclass
class Decision:
    decision_type: DecisionType
    attributes: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class RespondDecisionTaskCompletedRequest:
    task_token: Dict[str, Any]
    decisions: List[Decision] = dataclasses.field(default_factory=list)
    identity: str = ""
    binary_checksum: str = ""
    execution_context: bytes = b""
    sticky_task_list: str = ""
    sticky_schedule_to_start_timeout_seconds: int = 0
    return_new_decision_task: bool = False


@dataclasses.dataclass
class PollForDecisionTaskResponse:
    task_token: Dict[str, Any]
    workflow_id: str
    run_id: str
    workflow_type: str
    previous_started_event_id: int
    started_event_id: int
    attempt: int
    history: List[HistoryEvent]
    backlog_count_hint: int = 0
    scheduled_timestamp: int = 0
    started_timestamp: int = 0
    # direct (sync) query task: {"query_id", "query_type", "query_args"}
    query: Optional[Dict[str, Any]] = None
    # consistent queries piggybacked on a real decision task
    queries: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class PollForActivityTaskResponse:
    task_token: Dict[str, Any]
    workflow_id: str
    run_id: str
    activity_id: str
    activity_type: str
    input: bytes
    scheduled_timestamp: int
    started_timestamp: int
    schedule_to_close_timeout_seconds: int
    start_to_close_timeout_seconds: int
    heartbeat_timeout_seconds: int
    attempt: int
    heartbeat_details: bytes = b""


@dataclasses.dataclass
class DescribeWorkflowResponse:
    workflow_id: str
    run_id: str
    workflow_type: str
    start_time: int
    close_time: int
    close_status: int
    is_running: bool
    history_length: int
    pending_activities: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    pending_children: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    search_attributes: Dict[str, bytes] = dataclasses.field(default_factory=dict)
    memo: Dict[str, bytes] = dataclasses.field(default_factory=dict)


def make_task_token(
    domain_id: str, workflow_id: str, run_id: str, schedule_id: int,
    started_id: int = 0, activity_id: str = "",
) -> Dict[str, Any]:
    return {
        "domain_id": domain_id,
        "workflow_id": workflow_id,
        "run_id": run_id,
        "schedule_id": schedule_id,
        "started_id": started_id,
        "activity_id": activity_id,
    }
