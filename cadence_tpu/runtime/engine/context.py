"""Workflow execution context: load / persist orchestration.

Reference: service/history/workflowExecutionContext.go — the component
that knows how a closed ActiveTransaction becomes durable: append the
event batch to the history branch, stamp queue-task IDs from the shard
sequencer, then write the mutable-state snapshot conditioned on the
load-time next_event_id (and the shard's range_id), creating the
continue-as-new run atomically when present."""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from cadence_tpu.core.active_transaction import TransactionResult
from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.core.tasks import ReplicationTask
from cadence_tpu.utils.locks import make_rlock

from ..persistence.records import (
    BranchToken,
    CreateWorkflowMode,
    WorkflowSnapshot,
)
from ..shard import ShardContext


class WorkflowExecutionContext:
    def __init__(
        self,
        shard: ShardContext,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        on_persist=None,
        events_cache=None,
    ) -> None:
        self.shard = shard
        self.domain_id = domain_id
        self.workflow_id = workflow_id
        self.run_id = run_id
        self.lock = make_rlock("WorkflowExecutionContext.lock")
        self._ms: Optional[MutableState] = None
        self._condition = 0
        # invoked after every durable write (historyEventNotifier feed)
        self._on_persist = on_persist or (lambda ms: None)
        # shard-level event LRU (engine/events_cache.py); None in bare
        # test harnesses — get_event then always pages history
        self.events_cache = events_cache

    def _drain_cached_events(self, ms: MutableState, run_id: str = "") -> None:
        """Move transition-written events (activity scheduled, child
        initiated, ...) into the shard events cache, keeping the
        mutable state bounded (ref eventsCache.go putEvent)."""
        if self.events_cache is not None:
            for e in ms.cached_events:
                self.events_cache.put(
                    self.domain_id, self.workflow_id,
                    run_id or self.run_id, e,
                )
        ms.cached_events.clear()

    def get_event(
        self, ms: MutableState, event_id: int, first_event_id: int = 1
    ):
        """Event lookup: staged → shard cache → history branch
        (ref eventsCache.go getEvent's history fallback)."""
        for e in ms.cached_events:
            if e.event_id == event_id:
                return e
        if self.events_cache is not None:
            hit = self.events_cache.get(
                self.domain_id, self.workflow_id, self.run_id, event_id
            )
            if hit is not None:
                return hit
        history, _ = self.read_history(ms, first_event_id=first_event_id)
        for e in history:
            if e.event_id == event_id:
                # cache only the requested event — inserting the whole
                # page would let one deep-history lookup evict the
                # shard cache's hot entries
                if self.events_cache is not None:
                    self.events_cache.put(
                        self.domain_id, self.workflow_id, self.run_id, e
                    )
                return e
        return None

    # -- load ---------------------------------------------------------

    def load(self) -> MutableState:
        if self._ms is None:
            resp = self.shard.persistence.execution.get_workflow_execution(
                self.shard.shard_id, self.domain_id, self.workflow_id,
                self.run_id,
            )
            self._ms = MutableState.from_snapshot(resp.snapshot)
            self._condition = resp.next_event_id
        return self._ms

    def clear(self) -> None:
        """Drop cached state (after a condition failure — reload next)."""
        self._ms = None

    @property
    def condition(self) -> int:
        return self._condition

    # -- history ------------------------------------------------------

    def branch_token(self, ms: MutableState) -> BranchToken:
        raw = ms.execution_info.branch_token
        return BranchToken.from_json(raw.decode())

    def _append_events(
        self, branch: BranchToken, events: List[HistoryEvent]
    ) -> int:
        if not events:
            return 0
        return self.shard.persistence.history.append_history_nodes(
            branch, events, transaction_id=self.shard.next_task_id()
        )

    # -- persist ------------------------------------------------------

    def _stamp_identity(self, run_id: str, *task_lists) -> None:
        """Stamp workflow identity onto queue tasks (the reference's task
        rows carry domainID/workflowID/runID; the StateBuilder emits them
        identity-free so replay stays pure)."""
        for tasks in task_lists:
            for t in tasks:
                if not t.domain_id:
                    t.domain_id = self.domain_id
                if not t.workflow_id:
                    t.workflow_id = self.workflow_id
                if not t.run_id:
                    t.run_id = run_id

    def _replication_tasks(
        self, ms: MutableState, events: List[HistoryEvent],
        new_run_branch: bytes = b"",
    ) -> List[ReplicationTask]:
        """Active-side replication task for one persisted event batch.

        Reference: mutableStateBuilder closeTransactionHandleWorkflow-
        ReplicationTask — global domains (version histories present) emit
        one HistoryReplicationTask per transaction batch so the
        replicator queue can ship it to remote clusters."""
        if ms.version_histories is None or not events:
            return []
        return [
            ReplicationTask(
                first_event_id=events[0].event_id,
                next_event_id=events[-1].event_id + 1,
                version=events[0].version,
                branch_token=ms.execution_info.branch_token,
                new_run_branch_token=new_run_branch,
            )
        ]

    def _snapshot_of(
        self, ms: MutableState, result_tasks: TransactionResult,
        new_run: bool = False,
        replication_tasks: Optional[List[ReplicationTask]] = None,
    ) -> WorkflowSnapshot:
        ei = ms.execution_info
        return WorkflowSnapshot(
            domain_id=self.domain_id,
            workflow_id=self.workflow_id,
            run_id=ei.run_id,
            snapshot=ms.snapshot(),
            next_event_id=ms.next_event_id,
            last_write_version=ms.current_version,
            transfer_tasks=(
                result_tasks.new_run_transfer_tasks
                if new_run
                else result_tasks.transfer_tasks
            ),
            timer_tasks=(
                result_tasks.new_run_timer_tasks
                if new_run
                else result_tasks.timer_tasks
            ),
            replication_tasks=replication_tasks or [],
        )

    def create_workflow(
        self,
        ms: MutableState,
        result: TransactionResult,
        mode: int = CreateWorkflowMode.BRAND_NEW,
        prev_run_id: str = "",
    ) -> None:
        """First persistence of a new run: new branch, events, record."""
        history = self.shard.persistence.history
        branch = history.new_history_branch(tree_id=self.run_id)
        ms.execution_info.branch_token = branch.to_json().encode()
        if ms.version_histories is not None:
            ms.version_histories.get_current_version_history().branch_token = (
                ms.execution_info.branch_token
            )
        size = self._append_events(branch, result.events)
        ms.execution_info.history_size = size
        repl = self._replication_tasks(ms, result.events)
        self.shard.assign_task_ids(
            result.transfer_tasks, result.timer_tasks, repl
        )
        self._stamp_identity(
            self.run_id, result.transfer_tasks, result.timer_tasks, repl
        )
        self.shard.persistence.execution.create_workflow_execution(
            self.shard.shard_id,
            self.shard.range_id,
            mode,
            self._snapshot_of(ms, result, replication_tasks=repl),
            prev_run_id=prev_run_id,
        )
        self._ms = ms
        self._condition = ms.next_event_id
        self._drain_cached_events(ms)
        self._on_persist(ms)

    def update_workflow(
        self, ms: MutableState, result: TransactionResult
    ) -> None:
        """Persist a mutation of a loaded workflow (+ CAN run if staged)."""
        size = 0
        if result.events:
            size = self._append_events(self.branch_token(ms), result.events)
        ms.execution_info.history_size += size

        new_snapshot = None
        new_run_id = ""
        new_run_branch = b""
        if result.new_run_ms is not None:
            new_ms = result.new_run_ms
            new_run_id = result.events[-1].attributes.get(
                "new_execution_run_id", ""
            )
            new_ms.execution_info.run_id = new_run_id
            branch = self.shard.persistence.history.new_history_branch(
                tree_id=new_run_id
            )
            new_ms.execution_info.branch_token = branch.to_json().encode()
            if new_ms.version_histories is not None:
                new_ms.version_histories.get_current_version_history(
                ).branch_token = new_ms.execution_info.branch_token
            new_run_branch = new_ms.execution_info.branch_token
            new_size = self._append_events(branch, result.new_run_events)
            new_ms.execution_info.history_size = new_size
            self.shard.assign_task_ids(
                result.new_run_transfer_tasks, result.new_run_timer_tasks
            )
            self._stamp_identity(
                new_run_id,
                result.new_run_transfer_tasks,
                result.new_run_timer_tasks,
            )
            new_snapshot = self._snapshot_of(new_ms, result, new_run=True)

        repl = self._replication_tasks(ms, result.events, new_run_branch)
        self.shard.assign_task_ids(
            result.transfer_tasks, result.timer_tasks, repl
        )
        self._stamp_identity(
            self.run_id, result.transfer_tasks, result.timer_tasks, repl
        )
        self.shard.persistence.execution.update_workflow_execution(
            self.shard.shard_id,
            self.shard.range_id,
            self._condition,
            self._snapshot_of(ms, result, replication_tasks=repl),
            new_snapshot=new_snapshot,
        )
        self._condition = ms.next_event_id
        self._drain_cached_events(ms)
        if result.new_run_ms is not None:
            self._drain_cached_events(result.new_run_ms, run_id=new_run_id)
        self._on_persist(ms)

    # -- reads --------------------------------------------------------

    def read_history(
        self,
        ms: MutableState,
        first_event_id: int = 1,
        next_event_id: int = 0,
        page_size: int = 0,
        next_token: int = 0,
    ) -> Tuple[List[HistoryEvent], int]:
        branch = self.branch_token(ms)
        batches, token = self.shard.persistence.history.read_history_branch(
            branch,
            first_event_id,
            next_event_id or ms.next_event_id,
            page_size=page_size,
            next_token=next_token,
        )
        return [e for batch in batches for e in batch], token
