"""Auto-restart on close: workflow retry policy and cron schedule.

Reference: service/history/workflowExecutionContext.go, where a close
converts into a continue-as-new instead — ``retryWorkflow`` when a
failed/timed-out run's retry policy grants another attempt (backoff per
service/history/retry.go getBackoffInterval), else ``cronWorkflow``
when the run has a cron schedule (attempt resets, backoff is the cron
delay, service/history/mutableStateBuilder.go GetCronBackoffDuration).
Completion consults only cron; fail/timeout consult retry first.

The new run starts with a WorkflowBackoffTimer instead of an immediate
first decision (state_builder.py handles initiator==CronSchedule /
RetryPolicy when generating the new-run tasks), so the restart fires
after the computed delay.
"""

from __future__ import annotations

import uuid

from cadence_tpu.core.events import HistoryEvent, RetryPolicy
from cadence_tpu.core.enums import ContinueAsNewInitiator
from cadence_tpu.utils.backoff import (
    NO_INTERVAL,
    RetryPolicy as BackoffPolicy,
    next_backoff_interval_seconds,
)
from cadence_tpu.utils.cron import next_cron_delay_seconds


def try_continue_after_close(
    txn,
    ms,
    started_event_fn,
    close: str,
    now: int,
    error_reason: str = "",
    decision_completed_id: int = 0,
) -> bool:
    """If this close should restart the workflow, stage the
    continue-as-new on ``txn`` and return True.

    close: "complete" | "fail" | "timeout". ``now`` is ns.
    ``started_event_fn`` lazily fetches the run's started event (may be
    a persistence read) — it is only called once a restart is decided,
    so the common no-cron/no-retry close never pays for it. The caller
    must NOT also add its close event when this returns True.
    """
    ei = ms.execution_info
    initiator = None
    backoff = 0
    attempt = 0

    if close in ("fail", "timeout") and ei.has_retry_policy:
        policy = BackoffPolicy(
            initial_interval_seconds=ei.initial_interval,
            backoff_coefficient=ei.backoff_coefficient or 2.0,
            maximum_interval_seconds=ei.maximum_interval,
            maximum_attempts=ei.maximum_attempts,
            expiration_seconds=ei.expiration_seconds,
            non_retriable_errors=tuple(ei.non_retriable_errors),
        )
        delay = next_backoff_interval_seconds(
            policy, ei.attempt, ei.expiration_time, now,
            error_reason=error_reason,
        )
        if delay != NO_INTERVAL:
            initiator = ContinueAsNewInitiator.RetryPolicy
            backoff = delay
            attempt = ei.attempt + 1

    if initiator is None and ei.cron_schedule:
        # anchor '@every' at this run's execution time (start + first-
        # decision backoff) the way mutableStateBuilder.GetCronBackoffDuration
        # does (/root/reference/service/history/mutableStateBuilder.go:1048-1064)
        anchor = (ei.first_decision_backoff_deadline
                  or ei.start_timestamp) / 1e9
        delay = next_cron_delay_seconds(ei.cron_schedule, now / 1e9, anchor)
        if delay > 0:
            initiator = ContinueAsNewInitiator.CronSchedule
            backoff = delay
            attempt = 0

    if initiator is None:
        return False

    started_event: HistoryEvent | None = (
        started_event_fn() if started_event_fn else None
    )
    started_attrs = started_event.attributes if started_event else {}
    retry_policy = None
    if ei.has_retry_policy:
        retry_policy = RetryPolicy(
            initial_interval_seconds=ei.initial_interval,
            backoff_coefficient=ei.backoff_coefficient,
            maximum_interval_seconds=ei.maximum_interval,
            maximum_attempts=ei.maximum_attempts,
            expiration_interval_seconds=ei.expiration_seconds,
            non_retriable_error_reasons=list(ei.non_retriable_errors),
        )
    # retries keep the run's absolute expiration; a cron fire is a fresh
    # run whose retry budget (if any) restarts from its own start
    if initiator == ContinueAsNewInitiator.RetryPolicy:
        expiration_ts = ei.expiration_time
    elif ei.has_retry_policy and ei.expiration_seconds:
        expiration_ts = now + (backoff + ei.expiration_seconds) * 1_000_000_000
    else:
        expiration_ts = 0
    txn.add_continued_as_new(
        decision_completed_id, now, str(uuid.uuid4()),
        workflow_type=ei.workflow_type_name,
        task_list=ei.task_list,
        execution_start_to_close_timeout_seconds=ei.workflow_timeout,
        task_start_to_close_timeout_seconds=ei.decision_timeout_value,
        input=started_attrs.get("input", b"") or b"",
        backoff_start_interval_seconds=backoff,
        initiator=int(initiator),
        retry_policy=retry_policy,
        attempt=attempt,
        expiration_timestamp=expiration_ts,
        cron_schedule=ei.cron_schedule,
        identity=started_attrs.get("identity", ""),
        memo=started_attrs.get("memo"),
        search_attributes=started_attrs.get("search_attributes"),
    )
    return True
