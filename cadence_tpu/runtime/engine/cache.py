"""Per-workflow execution contexts with pinned locks.

Reference: service/history/historyCache.go — an LRU of
workflowExecutionContext; callers pin an entry, take its lock, mutate,
release. Eviction only removes unpinned, unlocked entries."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, Tuple

from .context import WorkflowExecutionContext


class HistoryCache:
    def __init__(self, make_context: Callable[..., WorkflowExecutionContext],
                 max_size: int = 1024) -> None:
        self._make = make_context
        self._max = max_size
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Tuple[str, str, str], WorkflowExecutionContext]" = (
            OrderedDict()
        )

    def get_or_create(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> WorkflowExecutionContext:
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            ctx = self._entries.get(key)
            if ctx is None:
                ctx = self._make(domain_id, workflow_id, run_id)
                self._entries[key] = ctx
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                old_key, old_ctx = next(iter(self._entries.items()))
                if old_ctx.lock.acquire(blocking=False):
                    old_ctx.lock.release()
                    del self._entries[old_key]
                else:
                    break  # oldest is busy; skip eviction this round
            return ctx

    def evict(self, domain_id: str, workflow_id: str, run_id: str) -> None:
        with self._lock:
            self._entries.pop((domain_id, workflow_id, run_id), None)
