"""Per-workflow execution contexts with canonical identity.

Reference: service/history/historyCache.go — an LRU of
workflowExecutionContext; callers pin an entry, take its lock, mutate,
release. Eviction only removes unpinned, unlocked entries.

Here pinning is implemented by IDENTITY rather than refcount: the LRU
bounds how many contexts stay strongly cached, while a
WeakValueDictionary guarantees that as long as ANY caller still holds a
context for a run, get_or_create returns that same object — eviction
can drop the strong reference but can never mint a second live context
(two contexts would mean two locks, and two writers could interleave
appends under the same next_event_id condition and corrupt history).
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Callable, Tuple

from cadence_tpu.utils.locks import make_lock

from .context import WorkflowExecutionContext


class HistoryCache:
    def __init__(self, make_context: Callable[..., WorkflowExecutionContext],
                 max_size: int = 1024) -> None:
        self._make = make_context
        self._max = max_size
        self._lock = make_lock("HistoryCache._lock")
        self._entries: "OrderedDict[Tuple[str, str, str], WorkflowExecutionContext]" = (
            OrderedDict()
        )
        # every LIVE context, strongly cached or not
        self._live: "weakref.WeakValueDictionary[Tuple[str, str, str], WorkflowExecutionContext]" = (
            weakref.WeakValueDictionary()
        )

    def get_or_create(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> WorkflowExecutionContext:
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            ctx = self._entries.get(key) or self._live.get(key)
            if ctx is None:
                ctx = self._make(domain_id, workflow_id, run_id)
                self._live[key] = ctx
            self._entries[key] = ctx
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                old_key, old_ctx = next(iter(self._entries.items()))
                if old_ctx.lock.acquire(blocking=False):
                    old_ctx.lock.release()
                    del self._entries[old_key]
                else:
                    break  # oldest is busy; skip eviction this round
            return ctx

    def evict(self, domain_id: str, workflow_id: str, run_id: str) -> None:
        """Forget the run's cached state (retention/zombification). The
        context object stays canonical for existing holders via the
        weak map, so a concurrent holder keeps a consistent lock; its
        next load() re-reads durable state because the caller clears
        the context's cached mutable state."""
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            ctx = self._entries.pop(key, None) or self._live.get(key)
        if ctx is not None:
            ctx.clear()
