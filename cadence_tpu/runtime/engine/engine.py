"""The history engine: all workflow mutations for one shard.

Reference: service/history/historyEngine.go (Start :408, Signal :1493,
SignalWithStart :1606, Terminate, RequestCancel, RecordDecisionTask
Started, RespondDecisionTaskCompleted via decisionHandler.go:258-340,
activity RPCs) — per-workflow lock + optimistic-concurrency retry
(Update_History_Loop, decisionHandler.go:291-311) around every mutation.
"""

from __future__ import annotations

import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

from cadence_tpu.core.active_transaction import (
    ActiveTransaction,
    TransactionResult,
    WorkflowStateError,
)
from cadence_tpu.core.enums import (
    CloseStatus,
    DecisionTaskFailedCause,
    EventType,
    IDReusePolicy,
    TimeoutType,
    WorkflowState,
)
from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.ids import (
    EMPTY_EVENT_ID,
    EMPTY_VERSION,
    FIRST_EVENT_ID,
    TRANSIENT_EVENT_ID,
)
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.core.version_history import VersionHistories
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP, Scope

from ..api import (
    BadRequestError,
    CancellationAlreadyRequestedError,
    Decision,
    DescribeWorkflowResponse,
    EntityNotExistsServiceError,
    InternalServiceError,
    ServiceBusyError,
    SignalRequest,
    SignalWithStartRequest,
    StartWorkflowRequest,
    WorkflowExecutionAlreadyStartedServiceError,
    make_task_token,
)
from ..domains import DomainCache
from ..persistence.errors import (
    ConditionFailedError,
    EntityNotExistsError,
    WorkflowAlreadyStartedError,
)
from ..persistence.records import CreateWorkflowMode
from ..shard import ShardContext
from .cache import HistoryCache
from .context import WorkflowExecutionContext
from .events_cache import EventsCache
from .decision_handler import DecisionFailure, DecisionTaskHandler
from .notifier import HistoryEventNotifier
from .query import QueryRegistry

_CONDITION_RETRY_COUNT = 5  # reference: workflowExecutionContext conditionalRetryCount


class HistoryEngine:
    def __init__(
        self,
        shard: ShardContext,
        domain_cache: DomainCache,
        metrics: Scope = NOOP,
        task_notifier: Optional[Callable[[], None]] = None,
        timer_notifier: Optional[Callable[[], None]] = None,
    ) -> None:
        self.shard = shard
        self.domains = domain_cache
        self.metrics = metrics.tagged(service="history", shard=str(shard.shard_id))
        self.log = get_logger("cadence_tpu.history", shard=shard.shard_id)
        self.event_notifier = HistoryEventNotifier()
        self.events_cache = EventsCache()
        self.cache = HistoryCache(
            lambda d, w, r: WorkflowExecutionContext(
                shard, d, w, r, on_persist=self._publish_progress,
                events_cache=self.events_cache,
            )
        )
        self.query_registry = QueryRegistry()
        self.matching_client = None  # wired by the service for queries
        # per-API requests/latency/errors (ref common/metrics/defs.go
        # history scopes)
        from cadence_tpu.utils.metrics_defs import (
            HISTORY_OPS,
            instrument_methods,
        )

        instrument_methods(self, self.metrics, HISTORY_OPS)
        # queue processors poke these after each persisted transaction
        self._task_notifier = task_notifier or (lambda: None)
        self._timer_notifier = timer_notifier or (lambda: None)
        # overload control (ISSUE 15): a MultiStageRateLimiter wired by
        # HistoryService — None (the default) costs one attribute read.
        # The frontend's limiter alone cannot protect this layer: queue
        # processors, replication appliers, and cross-shard calls all
        # reach the engine without passing a frontend
        self.rate_limiter = None

    # -- helpers ------------------------------------------------------

    def _shed_check(self, domain_key: str, op: str) -> None:
        """Coordinated shedding: consult the service-level limiter and
        shed with the RETRYABLE ``ServiceBusyError`` (retry-after hint
        = the rejecting bucket's refill horizon) — clients spend their
        retry budget instead of stacking work on a saturated shard."""
        lim = self.rate_limiter
        if lim is None:
            return
        if not lim.allow(domain_key):
            hint = getattr(lim, "retry_after_s", None)
            raise ServiceBusyError(
                f"history overloaded ({op}, domain {domain_key})",
                retry_after_s=hint(domain_key) if hint else 0.0,
            )

    def _domain_version(self, domain_record) -> int:
        return (
            domain_record.failover_version
            if domain_record.is_global
            else EMPTY_VERSION
        )

    def _publish_progress(self, ms: MutableState) -> None:
        ei = ms.execution_info
        # trace joining for the asynchronous hops: bind this workflow to
        # the caller's (sampled) trace so the queue tasks this persist
        # just scheduled — processed later on pump threads — land in
        # the SAME trace (utils/tracing.py; queues/base.task_span does
        # the lookup). No active trace → one thread-local read, no bind.
        from cadence_tpu.utils.tracing import TRACER

        TRACER.bind(("wf", ei.workflow_id))
        self.event_notifier.notify(
            ei.domain_id, ei.workflow_id, ei.run_id,
            ms.next_event_id, ms.is_workflow_execution_running(),
        )
        # continuous-batching serving feed (config `serving:`): O(1) —
        # marks a seated lane behind; the next serving tick composes
        # just the Δ suffix. Unseated workflows are one dict miss
        serving = getattr(self, "serving", None)
        if serving is not None:
            serving.on_persisted(
                ei.domain_id, ei.workflow_id, ei.run_id,
                ms.next_event_id,
                running=ms.is_workflow_execution_running(),
            )

    def _notify(self, result: TransactionResult) -> None:
        if result.transfer_tasks or result.new_run_transfer_tasks:
            self._task_notifier()
        if result.timer_tasks or result.new_run_timer_tasks:
            self._timer_notifier()

    def _current_run_id(self, domain_id: str, workflow_id: str) -> str:
        try:
            return self.shard.persistence.execution.get_current_execution(
                self.shard.shard_id, domain_id, workflow_id
            ).run_id
        except EntityNotExistsError:
            raise EntityNotExistsServiceError(
                f"workflow {workflow_id} not found"
            )

    def _update_workflow(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        action: Callable[[WorkflowExecutionContext, MutableState], Any],
    ) -> Any:
        """The Update_History_Loop: lock, load, act, persist; reload and
        retry on optimistic-concurrency failure."""
        if not run_id:
            run_id = self._current_run_id(domain_id, workflow_id)
        ctx = self.cache.get_or_create(domain_id, workflow_id, run_id)
        with ctx.lock:
            for _ in range(_CONDITION_RETRY_COUNT):
                try:
                    ms = ctx.load()
                except EntityNotExistsError:
                    raise EntityNotExistsServiceError(
                        f"workflow {workflow_id}/{run_id} not found"
                    )
                next_id_before = ms.next_event_id
                try:
                    out = action(ctx, ms)
                except ConditionFailedError:
                    ctx.clear()
                    continue
                except BaseException:
                    # the action may have mutated the cached ms before
                    # failing (staged events, then a persistence I/O
                    # error): drop the cache so the next load re-reads
                    # durable state instead of serving a completed-in-
                    # memory/unchanged-in-store split brain. Read-path
                    # errors (no events staged) keep the cache warm
                    if ms.next_event_id != next_id_before:
                        ctx.clear()
                    raise
                # size check only after a MUTATING transaction (the
                # reference enforces post-update; a read must never
                # terminate as a side effect)
                if ms.next_event_id > next_id_before:
                    self._enforce_history_limits(ctx, ms)
                return out
            raise InternalServiceError(
                f"workflow {workflow_id} update failed after "
                f"{_CONDITION_RETRY_COUNT} condition retries"
            )

    # reference: dynamicconfig HistorySizeLimitError (200MB) /
    # HistoryCountLimitError (200k events) — a runaway history is
    # force-terminated before it can take the shard down with it
    HISTORY_SIZE_LIMIT_BYTES = 200 * 1024 * 1024
    HISTORY_COUNT_LIMIT = 200_000

    def _enforce_history_limits(self, ctx, ms) -> None:
        """Force-terminate a run whose history outgrew the limits
        (reference workflowExecutionContext enforceSizeCheck)."""
        ei = ms.execution_info
        if not ms.is_workflow_execution_running():
            return
        if (
            ei.history_size <= self.HISTORY_SIZE_LIMIT_BYTES
            and ms.next_event_id <= self.HISTORY_COUNT_LIMIT
        ):
            return
        self.log.warn(
            f"terminating {ei.workflow_id}/{ei.run_id}: history "
            f"{ei.history_size}B / {ms.next_event_id - 1} events "
            "exceeds the limit"
        )
        try:
            txn = self._txn(ctx, ms, ms.current_version)
            txn.add_workflow_execution_terminated(
                self.shard.now(),
                reason="history size or count exceeds the limit",
                identity="history-service",
            )
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)
        except Exception:
            # the cached ms was mutated by the staged terminate — drop
            # it so the next load re-reads durable state instead of a
            # closed-in-memory/running-in-store split brain
            ctx.clear()
            self.log.exception("history-limit termination failed")

    def _txn(
        self, ctx: WorkflowExecutionContext, ms: MutableState,
        version: int, request_id: str = "",
    ) -> ActiveTransaction:
        return ActiveTransaction(
            ms, ctx.domain_id, ctx.workflow_id, ctx.run_id, version,
            request_id=request_id,
            domain_resolver=lambda name: (
                self.domains.resolve(name).info.id if name else ""
            ),
        )

    # -- StartWorkflowExecution ---------------------------------------

    def start_workflow_execution(
        self, request: StartWorkflowRequest, domain_id: str = "",
        signal_name: str = "", signal_input: bytes = b"",
    ) -> str:
        """Returns the new run_id (reference historyEngine.go:408)."""
        request.validate()
        self._shed_check(request.domain, "start_workflow_execution")
        domain = (
            self.domains.get_by_id(domain_id)
            if domain_id
            else self.domains.get_by_name(request.domain)
        )
        domain_id = domain.info.id
        run_id = str(uuid.uuid4())
        request_id = request.request_id or str(uuid.uuid4())
        version = self._domain_version(domain)
        now = self.shard.now()

        ms = MutableState(domain_id=domain_id, current_version=version)
        if domain.is_global:
            ms.version_histories = VersionHistories.new_empty()
        txn = ActiveTransaction(
            ms, domain_id, request.workflow_id, run_id, version,
            request_id=request_id,
            domain_resolver=lambda name: (
                self.domains.resolve(name).info.id if name else ""
            ),
        )
        txn.add_workflow_execution_started(
            now,
            workflow_type=request.workflow_type,
            task_list=request.task_list,
            execution_start_to_close_timeout_seconds=(
                request.execution_start_to_close_timeout_seconds
            ),
            task_start_to_close_timeout_seconds=(
                request.task_start_to_close_timeout_seconds
            ),
            input=request.input,
            identity=request.identity,
            retry_policy=request.retry_policy,
            # absolute retry budget: expiration_interval_seconds counts
            # from the first run's start (reference historyEngine
            # startWorkflow: ExpirationTime = now + ExpirationInterval)
            expiration_timestamp=(
                now + request.retry_policy.expiration_interval_seconds
                * 1_000_000_000
                if request.retry_policy
                and request.retry_policy.expiration_interval_seconds
                else 0
            ),
            cron_schedule=request.cron_schedule,
            memo=request.memo,
            search_attributes=request.search_attributes,
            parent_workflow_domain=request.parent_domain or None,
            parent_workflow_id=request.parent_workflow_id or None,
            parent_run_id=request.parent_run_id or None,
            parent_initiated_event_id=(
                request.parent_initiated_id
                if request.parent_workflow_id
                else None
            ),
        )
        if signal_name:
            txn.add_workflow_execution_signaled(
                signal_name, signal_input, request.identity, now
            )
        txn.add_decision_task_scheduled(now)
        result = txn.close()

        ctx = self.cache.get_or_create(domain_id, request.workflow_id, run_id)
        with ctx.lock:
            try:
                ctx.create_workflow(ms, result)
            except WorkflowAlreadyStartedError as e:
                return self._handle_start_collision(
                    request, domain_id, ms, result, ctx, e, request_id
                )
        self._notify(result)
        self.metrics.inc("workflow_started")
        return run_id

    def _handle_start_collision(
        self, request, domain_id, ms, result, ctx, err, request_id
    ) -> str:
        # request-id dedup: same start request -> same run (reference
        # historyEngine.go startWorkflow dedup on CreateRequestID)
        if err.start_request_id == request_id:
            return err.run_id
        policy = request.workflow_id_reuse_policy
        if err.state != int(WorkflowState.Completed):
            raise WorkflowExecutionAlreadyStartedServiceError(
                f"workflow {request.workflow_id} already running",
                err.start_request_id, err.run_id,
            )
        if policy == IDReusePolicy.RejectDuplicate:
            raise WorkflowExecutionAlreadyStartedServiceError(
                f"workflow {request.workflow_id} already finished "
                "(RejectDuplicate)",
                err.start_request_id, err.run_id,
            )
        if (
            policy == IDReusePolicy.AllowDuplicateFailedOnly
            and err.close_status
            in (int(CloseStatus.Completed), int(CloseStatus.ContinuedAsNew))
        ):
            raise WorkflowExecutionAlreadyStartedServiceError(
                f"workflow {request.workflow_id} completed successfully "
                "(AllowDuplicateFailedOnly)",
                err.start_request_id, err.run_id,
            )
        ctx.create_workflow(
            ms, result,
            mode=CreateWorkflowMode.WORKFLOW_ID_REUSE,
            prev_run_id=err.run_id,
        )
        self._notify(result)
        return ms.execution_info.run_id

    # -- signals ------------------------------------------------------

    def signal_workflow_execution(self, request: SignalRequest) -> None:
        request.validate()
        self._shed_check(request.domain, "signal_workflow_execution")
        domain = self.domains.get_by_name(request.domain)
        version = self._domain_version(domain)

        def action(ctx, ms):
            if request.request_id and request.request_id in ms.signal_requested_ids:
                return  # dedup
            txn = self._txn(ctx, ms, version)
            try:
                txn.add_workflow_execution_signaled(
                    request.signal_name, request.input, request.identity,
                    self.shard.now(),
                )
                if not ms.has_pending_decision() and not txn.has_buffered_events():
                    txn.add_decision_task_scheduled(self.shard.now())
            except WorkflowStateError as e:
                raise EntityNotExistsServiceError(str(e))
            if request.request_id:
                ms.signal_requested_ids.add(request.request_id)
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)

        self._update_workflow(
            domain.info.id, request.workflow_id, request.run_id, action
        )

    def signal_with_start_workflow_execution(
        self, request: SignalWithStartRequest
    ) -> str:
        request.validate()
        start = request.start
        domain = self.domains.get_by_name(start.domain)
        # running workflow -> plain signal (reference historyEngine.go:1606)
        try:
            cur = self.shard.persistence.execution.get_current_execution(
                self.shard.shard_id, domain.info.id, start.workflow_id
            )
            run_id = cur.run_id
            if cur.state != int(WorkflowState.Completed):
                # delegate through the RAW methods: the instance's are
                # metric-wrapped (instrument_methods), and going through
                # them would phantom-count every SignalWithStart as a
                # start/signal RPC too (the reference instruments at
                # the handler boundary only)
                from cadence_tpu.utils.metrics_defs import raw_method

                raw_method(self.signal_workflow_execution)(
                    SignalRequest(
                        domain=start.domain,
                        workflow_id=start.workflow_id,
                        run_id=run_id,
                        signal_name=request.signal_name,
                        input=request.signal_input,
                        identity=start.identity,
                    )
                )
                return run_id
        except (EntityNotExistsServiceError, EntityNotExistsError):
            pass
        from cadence_tpu.utils.metrics_defs import raw_method

        return raw_method(self.start_workflow_execution)(
            start,
            domain_id=domain.info.id,
            signal_name=request.signal_name,
            signal_input=request.signal_input,
        )

    # -- terminate / cancel -------------------------------------------

    def terminate_workflow_execution(
        self, domain_name: str, workflow_id: str, run_id: str = "",
        reason: str = "", details: bytes = b"", identity: str = "",
    ) -> None:
        domain = self.domains.get_by_name(domain_name)
        version = self._domain_version(domain)

        def action(ctx, ms):
            txn = self._txn(ctx, ms, version)
            try:
                txn.add_workflow_execution_terminated(
                    self.shard.now(), reason=reason, details=details,
                    identity=identity,
                )
            except WorkflowStateError as e:
                raise EntityNotExistsServiceError(str(e))
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)

        if not run_id:
            # queries buffer under the CONCRETE run id
            run_id = self._current_run_id(domain.info.id, workflow_id)
        self._update_workflow(domain.info.id, workflow_id, run_id, action)
        # a terminated run never runs another decision: buffered
        # consistent queries fail now rather than timing out
        self.query_registry.fail_all(
            domain.info.id, workflow_id, run_id,
            "workflow terminated before the query could run",
        )

    def request_cancel_workflow_execution(
        self, domain_name: str, workflow_id: str, run_id: str = "",
        cause: str = "", identity: str = "", request_id: str = "",
    ) -> None:
        domain = self.domains.get_by_name(domain_name)
        version = self._domain_version(domain)

        def action(ctx, ms):
            txn = self._txn(ctx, ms, version)
            try:
                txn.add_workflow_execution_cancel_requested(
                    cause, identity, self.shard.now(),
                    request_id=request_id,
                )
                if not ms.has_pending_decision():
                    txn.add_decision_task_scheduled(self.shard.now())
            except WorkflowStateError as e:
                if ms.execution_info.cancel_requested:
                    # same requester retrying is idempotent success
                    # (reference historyEngine RequestCancel dedup by
                    # requestID)
                    if (
                        request_id
                        and ms.execution_info.cancel_request_id
                        == request_id
                    ):
                        return
                    raise CancellationAlreadyRequestedError(str(e))
                raise EntityNotExistsServiceError(str(e))
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)

        self._update_workflow(domain.info.id, workflow_id, run_id, action)

    # -- decision task lifecycle --------------------------------------

    def record_decision_task_started(
        self, domain_id: str, workflow_id: str, run_id: str,
        schedule_id: int, request_id: str, identity: str = "",
    ) -> Dict[str, Any]:
        """Called by matching on dispatch; returns poll-response fields
        (reference decisionHandler.handleDecisionTaskStarted)."""

        def action(ctx, ms):
            ei = ms.execution_info
            if not ms.has_pending_decision() or ei.decision_schedule_id != schedule_id:
                # stale dispatch: decision already handled
                raise EntityNotExistsServiceError(
                    f"decision {schedule_id} not found "
                    f"(current {ei.decision_schedule_id})"
                )
            if ei.decision_started_id != EMPTY_EVENT_ID:
                if ei.decision_request_id == request_id:
                    pass  # duplicate dispatch of same poll: return same
                else:
                    raise EntityNotExistsServiceError(
                        f"decision {schedule_id} already started"
                    )
            version = ms.current_version
            txn = self._txn(ctx, ms, version)
            if ei.decision_started_id == EMPTY_EVENT_ID:
                try:
                    txn.add_decision_task_started(
                        schedule_id, request_id, identity, self.shard.now()
                    )
                except WorkflowStateError as e:
                    raise EntityNotExistsServiceError(str(e))
                result = txn.close()
                ctx.update_workflow(ms, result)
                self._notify(result)
            # sticky dispatch ships only the delta since the worker's
            # last decision — its cache holds the prefix (reference
            # historyEngine createPollForDecisionTaskResponse: sticky ⇒
            # partial history from previousStartedEventID + 1)
            first = 1
            if (
                ms.is_sticky_task_list_enabled()
                and ms.execution_info.last_processed_event > 0
            ):
                first = ms.execution_info.last_processed_event + 1
            history, _ = ctx.read_history(ms, first_event_id=first)
            return {
                "workflow_type": ms.execution_info.workflow_type_name,
                "previous_started_event_id": ms.execution_info.last_processed_event,
                "scheduled_event_id": ms.execution_info.decision_schedule_id,
                "started_event_id": ms.execution_info.decision_started_id,
                "attempt": ms.execution_info.decision_attempt,
                "history": history,
                "task_token": make_task_token(
                    domain_id, workflow_id, run_id,
                    ms.execution_info.decision_schedule_id,
                    ms.execution_info.decision_started_id,
                ),
            }

        resp = self._update_workflow(domain_id, workflow_id, run_id, action)
        # consistent queries ride the decision task (queryRegistry
        # buffered → started). Attached only AFTER the dispatch
        # persisted — a condition-retried action must not consume them.
        resp["queries"] = {
            q.id: {"query_type": q.query_type, "query_args": q.query_args}
            for q in self.query_registry.take_buffered(
                domain_id, workflow_id, run_id
            )
        }
        return resp

    def respond_decision_task_completed(
        self,
        task_token: Dict[str, Any],
        decisions: List[Decision],
        identity: str = "",
        binary_checksum: str = "",
        sticky_task_list: str = "",
        sticky_schedule_to_start_timeout_seconds: int = 0,
        query_results: Optional[Dict[str, Dict[str, Any]]] = None,
    ) -> None:
        domain_id = task_token["domain_id"]
        workflow_id = task_token["workflow_id"]
        run_id = task_token["run_id"]
        schedule_id = task_token["schedule_id"]

        def action(ctx, ms):
            ei = ms.execution_info
            if (
                ei.decision_schedule_id != schedule_id
                or ei.decision_started_id == EMPTY_EVENT_ID
            ):
                raise EntityNotExistsServiceError(
                    f"decision {schedule_id} not in flight"
                )
            started_id = ei.decision_started_id
            version = ms.current_version
            now = self.shard.now()
            # bad-binary gate (reference handleDecisionTaskCompleted →
            # checkBadBinary): a worker running a checksum the domain
            # marked bad must not make progress
            if binary_checksum and binary_checksum in (
                self.domains.get_by_id(domain_id).config.bad_binaries
            ):
                self._fail_decision_task(
                    ctx, schedule_id,
                    int(DecisionTaskFailedCause.BadBinary),
                    f"binary {binary_checksum!r} is marked bad for "
                    "this domain",
                    identity,
                )
                return
            txn = self._txn(ctx, ms, version)
            had_buffered = txn.has_buffered_events()
            completed = txn.add_decision_task_completed(
                schedule_id, started_id, now,
                identity=identity, binary_checksum=binary_checksum,
            )
            # reset points record in the shared StateBuilder replicate
            # path (mutable_state.replicate_decision_task_completed_
            # event) so active, replicated, and rebuilt state agree
            # stickiness (reference: handleDecisionTaskCompleted).
            # A non-positive timeout would arm an instantly-firing
            # ScheduleToStart timer on every decision — normalize to
            # the standard 5s sticky window
            if sticky_task_list:
                ei.sticky_task_list = sticky_task_list
                ei.sticky_schedule_to_start_timeout = (
                    sticky_schedule_to_start_timeout_seconds
                    if sticky_schedule_to_start_timeout_seconds > 0
                    else 5
                )
            else:
                ms.clear_stickiness()

            handler = DecisionTaskHandler(
                txn, completed.event_id, now, identity=identity,
                had_buffered_events=had_buffered,
                started_event_fn=lambda: ctx.get_event(ms, FIRST_EVENT_ID),
            )
            try:
                handler.handle(decisions)
            except DecisionFailure as failure:
                # reset and fail the decision task instead
                # (reference decisionTaskHandler failDecision path)
                ctx.clear()
                self._fail_decision_task(
                    ctx, schedule_id, failure.cause, str(failure), identity
                )
                return
            # events needing a fresh decision: flushed buffered events, a
            # dropped close, or queries buffered after this decision
            # dispatched (reference handleDecisionTaskCompleted schedules
            # a new decision to carry outstanding buffered queries)
            if not handler.workflow_closed and (
                handler.unhandled_close_dropped
                or self._needs_new_decision(txn, completed.event_id)
                or self.query_registry.buffered_count(
                    domain_id, workflow_id, run_id
                ) > 0
            ):
                txn.add_decision_task_scheduled(now)
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)
            committed.append(True)
            if handler.workflow_closed:
                # no carrier decision will ever run: buffered queries
                # fail NOW instead of hanging out their full timeout
                self.query_registry.fail_all(
                    domain_id, workflow_id, run_id,
                    "workflow closed before the query could run",
                )

        committed: List[bool] = []
        self._update_workflow(domain_id, workflow_id, run_id, action)
        # consistent-query answers apply only when the completion actually
        # committed — a stale/failed completion must not answer queries
        # with state that never took effect
        if committed and query_results:
            self.query_registry.complete(
                domain_id, workflow_id, run_id, query_results
            )

    @staticmethod
    def _needs_new_decision(txn, completed_id: int) -> bool:
        """Flushed buffered events after the completion require a new
        decision so the worker sees them."""
        from cadence_tpu.core.active_transaction import _BUFFERABLE

        return any(
            e.event_id > completed_id and e.event_type in _BUFFERABLE
            for e in txn.batch
        )

    def _fail_decision_task(
        self, ctx, schedule_id: int, cause: int, message: str, identity: str
    ) -> None:
        ms = ctx.load()
        ei = ms.execution_info
        if ei.decision_schedule_id != schedule_id:
            return
        txn = self._txn(ctx, ms, ms.current_version)
        txn.add_decision_task_failed(
            schedule_id, ei.decision_started_id, self.shard.now(),
            cause=cause, identity=identity, details=message.encode(),
        )
        result = txn.close()
        ctx.update_workflow(ms, result)
        self._notify(result)

    def respond_decision_task_failed(
        self, task_token: Dict[str, Any], cause: int = 0,
        details: bytes = b"", identity: str = "",
    ) -> None:
        def action(ctx, ms):
            ei = ms.execution_info
            if (
                ei.decision_schedule_id != task_token["schedule_id"]
                or ei.decision_started_id == EMPTY_EVENT_ID
            ):
                raise EntityNotExistsServiceError("decision not in flight")
            txn = self._txn(ctx, ms, ms.current_version)
            txn.add_decision_task_failed(
                ei.decision_schedule_id, ei.decision_started_id,
                self.shard.now(), cause=cause, identity=identity,
                details=details,
            )
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)

        self._update_workflow(
            task_token["domain_id"], task_token["workflow_id"],
            task_token["run_id"], action,
        )

    # -- activity task lifecycle --------------------------------------

    def record_activity_task_started(
        self, domain_id: str, workflow_id: str, run_id: str,
        schedule_id: int, request_id: str, identity: str = "",
    ) -> Dict[str, Any]:
        def action(ctx, ms):
            ai = ms.get_activity_info(schedule_id)
            if ai is None:
                raise EntityNotExistsServiceError(
                    f"activity {schedule_id} not pending"
                )
            if ai.started_id != EMPTY_EVENT_ID:
                if ai.request_id == request_id:
                    pass  # duplicate dispatch
                else:
                    raise EntityNotExistsServiceError(
                        f"activity {schedule_id} already started"
                    )
            else:
                txn = self._txn(ctx, ms, ms.current_version)
                txn.record_activity_task_started(
                    ai, request_id, identity, self.shard.now()
                )
                result = txn.close()
                ctx.update_workflow(ms, result)
            # the poll response needs the scheduled event's payload:
            # events cache first, history branch on miss
            scheduled_event = ctx.get_event(
                ms, schedule_id,
                first_event_id=max(1, ai.scheduled_event_batch_id),
            )
            return {
                "activity_id": ai.activity_id,
                "scheduled_time": ai.scheduled_time,
                "started_time": ai.started_time,
                "attempt": ai.attempt,
                "heartbeat_details": ai.details,
                "schedule_to_close_timeout_seconds": ai.schedule_to_close_timeout,
                "start_to_close_timeout_seconds": ai.start_to_close_timeout,
                "heartbeat_timeout_seconds": ai.heartbeat_timeout,
                "scheduled_event": scheduled_event,
                "task_token": make_task_token(
                    domain_id, workflow_id, run_id, schedule_id,
                    activity_id=ai.activity_id,
                ),
            }

        return self._update_workflow(domain_id, workflow_id, run_id, action)

    def _respond_activity(
        self, task_token: Dict[str, Any],
        add: Callable[[ActiveTransaction, int, int], None],
    ) -> None:
        schedule_id = task_token["schedule_id"]

        def action(ctx, ms):
            txn = self._txn(ctx, ms, ms.current_version)
            now = self.shard.now()
            try:
                add(txn, schedule_id, now)
                if not ms.has_pending_decision() and not txn.has_buffered_events():
                    txn.add_decision_task_scheduled(now)
            except WorkflowStateError as e:
                raise EntityNotExistsServiceError(str(e))
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)

        self._update_workflow(
            task_token["domain_id"], task_token["workflow_id"],
            task_token["run_id"], action,
        )

    def respond_activity_task_completed(
        self, task_token: Dict[str, Any], result: bytes = b"",
        identity: str = "",
    ) -> None:
        self._respond_activity(
            task_token,
            lambda txn, sid, now: txn.add_activity_task_completed(
                sid, now, result=result, identity=identity
            ),
        )

    def respond_activity_task_failed(
        self, task_token: Dict[str, Any], reason: str = "",
        details: bytes = b"", identity: str = "",
    ) -> None:
        self._respond_activity(
            task_token,
            lambda txn, sid, now: txn.add_activity_task_failed(
                sid, now, reason=reason, details=details, identity=identity
            ),
        )

    def respond_activity_task_canceled(
        self, task_token: Dict[str, Any], details: bytes = b"",
        identity: str = "",
    ) -> None:
        self._respond_activity(
            task_token,
            lambda txn, sid, now: txn.add_activity_task_canceled(
                sid, EMPTY_EVENT_ID, now, details=details, identity=identity
            ),
        )

    def record_activity_task_heartbeat(
        self, task_token: Dict[str, Any], details: bytes = b"",
        identity: str = "",
    ) -> bool:
        """Returns cancel_requested (reference historyEngine
        RecordActivityTaskHeartbeat — state-only update, no event)."""
        schedule_id = task_token["schedule_id"]

        def action(ctx, ms):
            ai = ms.get_activity_info(schedule_id)
            if ai is None:
                raise EntityNotExistsServiceError(
                    f"activity {schedule_id} not pending"
                )
            ai.details = details
            ai.last_heartbeat_updated_time = self.shard.now()
            result = TransactionResult(
                events=[], transfer_tasks=[], timer_tasks=[]
            )
            ctx.update_workflow(ms, result)
            return ai.cancel_requested

        return self._update_workflow(
            task_token["domain_id"], task_token["workflow_id"],
            task_token["run_id"], action,
        )

    def with_workflow(
        self, domain_id: str, workflow_id: str, run_id: str,
        fn: Callable[[WorkflowExecutionContext, MutableState], Any],
    ) -> Any:
        """Run ``fn(ctx, ms)`` under the workflow lock with condition
        retries (read-only callers just return values)."""
        return self._update_workflow(domain_id, workflow_id, run_id, fn)

    def refresh_workflow_tasks(
        self, domain_id: str, workflow_id: str, run_id: str = ""
    ) -> int:
        """Regenerate this run's transfer/timer tasks from its current
        mutable state (reference adminHandler.RefreshWorkflowTasks →
        mutableStateTaskRefresher) — the operator fix for a run whose
        tasks were lost or surgically removed. Returns the task count."""
        from cadence_tpu.core.task_refresher import refresh_tasks

        def action(ctx, ms):
            transfer, timer = refresh_tasks(ms)
            txn = self._txn(ctx, ms, ms.current_version)
            for t in transfer:
                txn.schedule_transfer_task(t)
            for t in timer:
                txn.schedule_timer_task(t)
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)
            return len(transfer) + len(timer)

        return self._update_workflow(domain_id, workflow_id, run_id, action)

    # -- cross-workflow callbacks (invoked by the transfer queue) ------
    # Reference: transferQueueActiveProcessor.go record*Completed/Failed
    # helpers and historyEngine.RecordChildExecutionCompleted — each
    # appends a result event to the source workflow and schedules a
    # decision if none is pending.

    def _record_external_result(
        self, domain_id: str, workflow_id: str, run_id: str,
        mutate: Callable[[ActiveTransaction, MutableState, int], bool],
    ) -> None:
        def action(ctx, ms):
            if not ms.is_workflow_execution_running():
                raise EntityNotExistsServiceError(
                    f"workflow {workflow_id} already closed"
                )
            now = self.shard.now()
            txn = self._txn(ctx, ms, ms.current_version)
            try:
                if not mutate(txn, ms, now):
                    return  # duplicate task; nothing to record
                if not ms.has_pending_decision() and not txn.has_buffered_events():
                    txn.add_decision_task_scheduled(now)
            except WorkflowStateError as e:
                raise EntityNotExistsServiceError(str(e))
            result = txn.close()
            ctx.update_workflow(ms, result)
            self._notify(result)

        self._update_workflow(domain_id, workflow_id, run_id, action)

    def record_child_execution_started(
        self, domain_id: str, workflow_id: str, run_id: str,
        initiated_id: int, child_domain: str, child_workflow_id: str,
        child_run_id: str, workflow_type: str,
    ) -> None:
        def mutate(txn, ms, now):
            ci = ms.get_child_execution_info(initiated_id)
            if ci is None:
                raise WorkflowStateError(f"child {initiated_id} not pending")
            if ci.started_id != EMPTY_EVENT_ID:
                return False  # duplicate start notification
            txn.add_child_started(
                initiated_id, child_domain, child_workflow_id, child_run_id,
                workflow_type, now,
            )
            return True

        self._record_external_result(domain_id, workflow_id, run_id, mutate)

    def record_start_child_execution_failed(
        self, domain_id: str, workflow_id: str, run_id: str,
        initiated_id: int, child_domain: str, child_workflow_id: str,
        workflow_type: str, cause: int,
    ) -> None:
        def mutate(txn, ms, now):
            if ms.get_child_execution_info(initiated_id) is None:
                return False
            txn.add_start_child_failed(
                initiated_id, child_domain, child_workflow_id, workflow_type,
                cause, now,
            )
            return True

        self._record_external_result(domain_id, workflow_id, run_id, mutate)

    def record_child_execution_completed(
        self, domain_id: str, workflow_id: str, run_id: str,
        initiated_id: int, close_event_type: EventType,
        child_run_id: str = "",
        **close_attrs: Any,
    ) -> None:
        """Parent-side close notification (historyEngine.go
        RecordChildExecutionCompleted). ``child_run_id`` backfills the
        started event when the close raced ahead of the started
        notification (ci.started_run_id is unset in exactly that race)."""

        def mutate(txn, ms, now):
            ci = ms.get_child_execution_info(initiated_id)
            if ci is None:
                return False  # already recorded (duplicate)
            if ci.started_id == EMPTY_EVENT_ID:
                # close raced ahead of the started notification: record
                # the started event first so the history stays legal
                txn.add_child_started(
                    initiated_id, ci.domain_name, ci.started_workflow_id,
                    ci.started_run_id or child_run_id,
                    ci.workflow_type_name, now,
                )
            txn.add_child_closed(initiated_id, close_event_type, now, **close_attrs)
            return True

        self._record_external_result(domain_id, workflow_id, run_id, mutate)

    def record_external_cancel_result(
        self, domain_id: str, workflow_id: str, run_id: str,
        initiated_id: int, target_domain: str, target_workflow_id: str,
        target_run_id: str, failed_cause: Optional[int] = None,
    ) -> None:
        def mutate(txn, ms, now):
            if ms.get_request_cancel_info(initiated_id) is None:
                return False
            if failed_cause is None:
                txn.add_external_cancel_requested(
                    initiated_id, target_domain, target_workflow_id,
                    target_run_id, now,
                )
            else:
                txn.add_request_cancel_external_failed(
                    initiated_id, target_domain, target_workflow_id,
                    target_run_id, failed_cause, now,
                )
            return True

        self._record_external_result(domain_id, workflow_id, run_id, mutate)

    def record_external_signal_result(
        self, domain_id: str, workflow_id: str, run_id: str,
        initiated_id: int, target_domain: str, target_workflow_id: str,
        target_run_id: str, control: bytes = b"",
        failed_cause: Optional[int] = None,
    ) -> None:
        def mutate(txn, ms, now):
            if ms.get_signal_info(initiated_id) is None:
                return False
            if failed_cause is None:
                txn.add_external_signaled(
                    initiated_id, target_domain, target_workflow_id,
                    target_run_id, control, now,
                )
            else:
                txn.add_signal_external_failed(
                    initiated_id, target_domain, target_workflow_id,
                    target_run_id, failed_cause, now,
                )
            return True

        self._record_external_result(domain_id, workflow_id, run_id, mutate)

    # -- reads --------------------------------------------------------

    def get_workflow_execution_history(
        self, domain_name: str, workflow_id: str, run_id: str = "",
        first_event_id: int = 1, page_size: int = 0, next_token: int = 0,
        wait_for_new_event: bool = False, long_poll_timeout_s: float = 10.0,
    ) -> Tuple[List[HistoryEvent], int]:
        domain_id = self.domains.get_by_name(domain_name).info.id
        if not run_id:
            run_id = self._current_run_id(domain_id, workflow_id)

        def probe(ctx, ms):
            return ms.next_event_id, ms.is_workflow_execution_running()

        if wait_for_new_event:
            # long-poll: block until events past first_event_id exist.
            # Subscribe BEFORE probing — an event persisted between probe
            # and subscribe must not be missed (reference notifier
            # ordering: watch, then read).
            sub = self.event_notifier.subscribe(
                domain_id, workflow_id, run_id
            )
            try:
                next_id, running = self._update_workflow(
                    domain_id, workflow_id, run_id, probe
                )
                sub.publish(next_id, running)  # seed with current state
                if next_id <= first_event_id and running:
                    sub.wait_for(first_event_id, long_poll_timeout_s)
            finally:
                self.event_notifier.unsubscribe(
                    domain_id, workflow_id, run_id, sub
                )

        def action(ctx, ms):
            return ctx.read_history(
                ms, first_event_id=first_event_id, page_size=page_size,
                next_token=next_token,
            )

        return self._update_workflow(domain_id, workflow_id, run_id, action)

    def describe_workflow_execution(
        self, domain_name: str, workflow_id: str, run_id: str = ""
    ) -> DescribeWorkflowResponse:
        domain_id = self.domains.get_by_name(domain_name).info.id

        def action(ctx, ms):
            ei = ms.execution_info
            return DescribeWorkflowResponse(
                workflow_id=ei.workflow_id,
                run_id=ei.run_id,
                workflow_type=ei.workflow_type_name,
                start_time=ei.start_timestamp,
                close_time=0,
                close_status=int(ei.close_status),
                is_running=ms.is_workflow_execution_running(),
                history_length=ms.next_event_id - 1,
                pending_activities=[
                    {
                        "schedule_id": sid,
                        "activity_id": ai.activity_id,
                        "state": (
                            "STARTED"
                            if ai.started_id != EMPTY_EVENT_ID
                            else "SCHEDULED"
                        ),
                        "attempt": ai.attempt,
                    }
                    for sid, ai in sorted(ms.pending_activities.items())
                ],
                pending_children=[
                    {
                        "initiated_id": cid,
                        "workflow_id": ci.started_workflow_id,
                        "run_id": ci.started_run_id,
                    }
                    for cid, ci in sorted(ms.pending_children.items())
                ],
                search_attributes=dict(ei.search_attributes),
                memo=dict(ei.memo),
            )

        return self._update_workflow(domain_id, workflow_id, run_id, action)

    # -- replication entry points -------------------------------------
    # Reference: historyEngine.go:1914 ReplicateEventsV2 →
    # nDCHistoryReplicator.ApplyEvents; replicatorQueueProcessor serves
    # GetReplicationMessages.

    @property
    def ndc_replicator(self):
        if getattr(self, "_ndc_replicator", None) is None:
            from ..replication.ndc import NDCHistoryReplicator

            cluster_meta = getattr(self, "cluster_metadata", None)

            def is_active_locally(domain_id: str) -> bool:
                if cluster_meta is None:
                    return True
                try:
                    rec = self.domains.get_by_id(domain_id)
                except Exception:
                    return False
                return (
                    rec.replication_config.active_cluster_name
                    == cluster_meta.current_cluster_name
                )

            self._ndc_replicator = NDCHistoryReplicator(
                self.shard, self.domains, self.cache,
                is_active_locally=is_active_locally,
                task_notifier=self._task_notifier,
                timer_notifier=self._timer_notifier,
                rebuild_chunk_size=getattr(self, "rebuild_chunk_size", 0),
                faults=getattr(self, "faults", None),
                checkpoints=getattr(self, "checkpoints", None),
                metrics=getattr(self, "metrics", None),
                serving=getattr(self, "serving", None),
            )
        return self._ndc_replicator

    @property
    def replicator_queue(self):
        if getattr(self, "_replicator_queue", None) is None:
            from ..replication.replicator_queue import ReplicatorQueueProcessor

            cm = getattr(self, "cluster_metadata", None)
            self._replicator_queue = ReplicatorQueueProcessor(
                self.shard,
                remote_clusters=(
                    cm.enabled_remote_clusters() if cm is not None else None
                ),
                metrics=getattr(self, "metrics", None),
                faults=getattr(self, "faults", None),
                checkpoints=getattr(self, "checkpoints", None),
            )
        return self._replicator_queue

    def replicate_events_v2(self, task) -> None:
        """Apply one replicated event batch (HistoryTaskV2)."""
        self.ndc_replicator.apply_events(task)

    def get_replication_messages(self, cluster: str, last_retrieved_id: int,
                                 max_tasks=None):
        return self.replicator_queue.get_replication_messages(
            cluster, last_retrieved_id, max_tasks=max_tasks
        )

    def get_replication_backlog(self, last_retrieved_id: int):
        """Per-run backlog spans past the cursor, no event payloads —
        the adaptive consumer's catch-up probe."""
        return self.replicator_queue.get_replication_backlog(
            last_retrieved_id
        )

    def get_replication_checkpoint(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> bytes:
        """Delta-compressed branch-tip ReplayCheckpoint for snapshot
        shipping (b"" = no shippable snapshot; consumer falls back to
        event shipping)."""
        return self.replicator_queue.get_replication_checkpoint(
            domain_id, workflow_id, run_id
        )

    def get_workflow_history_raw(
        self, domain_id: str, workflow_id: str, run_id: str,
        start_event_id: int, end_event_id: int,
    ):
        """Raw history + version-history items for re-replication
        (reference: adminHandler GetWorkflowExecutionRawHistoryV2)."""
        from ..persistence.records import (
            BranchToken,
            current_version_history,
        )

        resp = self.shard.persistence.execution.get_workflow_execution(
            self.shard.shard_id, domain_id, workflow_id, run_id
        )
        token_str, item_pairs = current_version_history(resp.snapshot)
        if not token_str:
            token_str = (resp.snapshot or {}).get(
                "execution_info", {}
            ).get("branch_token", "")
            if isinstance(token_str, bytes):
                token_str = token_str.decode()
        items = [
            {"event_id": e, "version": v} for e, v in item_pairs
        ]
        branch = BranchToken.from_json(token_str)
        batches, _ = self.shard.persistence.history.read_history_branch(
            branch, start_event_id, end_event_id
        )
        return batches, items

    # -- consistent query (queryRegistry + queryStateMachine) ----------

    def query_workflow(
        self,
        domain_name: str,
        workflow_id: str,
        run_id: str = "",
        query_type: str = "",
        query_args: bytes = b"",
        timeout_s: float = 10.0,
        reject_not_open: bool = False,
    ) -> bytes:
        """Reference historyEngine QueryWorkflow: buffer on a pending
        decision (piggyback on its dispatch) or sync-dispatch a query
        task straight to matching when the workflow is idle."""
        from ..api import QueryFailedError

        domain_id = self.domains.get_by_name(domain_name).info.id
        if not run_id:
            run_id = self._current_run_id(domain_id, workflow_id)

        def probe(ctx, ms):
            return (
                ms.is_workflow_execution_running(),
                ms.has_pending_decision(),
                ms.execution_info.task_list,
            )

        running, pending_decision, task_list = self._update_workflow(
            domain_id, workflow_id, run_id, probe
        )
        if reject_not_open and not running:
            raise QueryFailedError("workflow is not open")

        if pending_decision and running:
            q = self.query_registry.buffer(
                domain_id, workflow_id, run_id, query_type, query_args
            )
            # the decision may have completed between the probe and the
            # buffer (its buffered-query check then saw nothing): re-probe
            # and fall through to the direct path if the workflow is idle
            _, still_pending, task_list = self._update_workflow(
                domain_id, workflow_id, run_id, probe
            )
            if still_pending:
                if not q.wait(timeout_s):
                    self.query_registry.fail(
                        domain_id, workflow_id, run_id, q, "query timed out"
                    )
                    raise QueryFailedError("query timed out")
                if q.error:
                    raise QueryFailedError(q.error)
                return q.result or b""
            self.query_registry.fail(
                domain_id, workflow_id, run_id, q, "rerouted to direct path"
            )

        if self.matching_client is None:
            raise InternalServiceError("matching client not wired for query")
        return self.matching_client.query_workflow(
            domain_id, task_list, workflow_id, run_id,
            query_type, query_args, timeout_s,
        )

    # -- workflow reset (workflowResetor.go) ---------------------------

    def reset_workflow_execution(
        self,
        domain_name: str,
        workflow_id: str,
        run_id: str = "",
        reason: str = "",
        decision_finish_event_id: int = 0,
        request_id: str = "",
        identity: str = "",
    ) -> str:
        """Fork at a decision boundary and restart from there; returns
        the new run id."""
        from .resetor import WorkflowResetor

        domain_id = self.domains.get_by_name(domain_name).info.id
        if not run_id:
            run_id = self._current_run_id(domain_id, workflow_id)
        return WorkflowResetor(self).reset_workflow_execution(
            domain_id, workflow_id, run_id, reason,
            decision_finish_event_id, request_id, identity,
        )

    def reset_sticky_task_list(
        self, domain_name: str, workflow_id: str, run_id: str = ""
    ) -> None:
        """Clear sticky execution attributes (frontend ResetStickyTaskList
        → historyEngine.ResetStickyTaskList)."""
        domain_id = self.domains.get_by_name(domain_name).info.id

        def action(ctx, ms):
            ms.clear_stickiness()
            txn = self._txn(ctx, ms, ms.current_version)
            ctx.update_workflow(ms, txn.close())

        self._update_workflow(domain_id, workflow_id, run_id, action)
