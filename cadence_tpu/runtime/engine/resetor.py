"""User-initiated workflow reset.

Reference: service/history/workflowResetor.go:692,941 — fork the history
branch at a decision boundary, replay the prefix into a fresh run via
the shared StateBuilder (the same replay the TPU kernel accelerates),
fail the in-flight decision with cause ResetWorkflow, carry signals
recorded after the reset point into the new run, terminate the old run,
and persist both atomically-enough (old update + new create).
"""

from __future__ import annotations

import uuid
from typing import List, Optional, Tuple

from cadence_tpu.core.active_transaction import ActiveTransaction
from cadence_tpu.core.enums import (
    DecisionTaskFailedCause,
    EventType,
)
from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.core.state_builder import StateBuilder
from cadence_tpu.core.version_history import VersionHistories

from ..api import BadRequestError, InternalServiceError
from ..persistence.records import (
    BranchToken,
    CreateWorkflowMode,
    WorkflowSnapshot,
)

_DECISION_FINISH_TYPES = frozenset(
    {
        EventType.DecisionTaskCompleted,
        EventType.DecisionTaskFailed,
        EventType.DecisionTaskTimedOut,
    }
)


class WorkflowResetor:
    def __init__(self, engine) -> None:
        self.engine = engine
        self.shard = engine.shard

    # -- public --------------------------------------------------------

    def reset_workflow_execution(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        reason: str,
        decision_finish_event_id: int,
        request_id: str = "",
        identity: str = "",
    ) -> str:
        """Returns the new run id."""
        engine = self.engine
        ctx = engine.cache.get_or_create(domain_id, workflow_id, run_id)
        with ctx.lock:
            ms = ctx.load()
            base_events = self._read_all_events(ctx, ms)
            self._validate(ms, base_events, decision_finish_event_id)

            new_run_id = str(uuid.uuid4())
            new_ms, sb = self._replay_prefix(
                domain_id, workflow_id, new_run_id,
                base_events, decision_finish_event_id,
            )

            # fail the in-flight decision + carry post-reset signals +
            # schedule a fresh decision
            txn = ActiveTransaction(
                new_ms, domain_id, workflow_id, new_run_id,
                new_ms.current_version,
                request_id=request_id or str(uuid.uuid4()),
            )
            now = self.shard.now()
            ei = new_ms.execution_info
            if ei.decision_started_id != EMPTY_EVENT_ID:
                txn.add_decision_task_failed(
                    ei.decision_schedule_id, ei.decision_started_id, now,
                    cause=int(DecisionTaskFailedCause.ResetWorkflow),
                    identity=identity,
                    details=reason.encode(),
                )
            # post-cut signals come from persisted history AND from the
            # old run's buffered events (signals held behind an in-flight
            # decision are not yet in history but must survive the reset)
            carried = self._signals_after(
                base_events, decision_finish_event_id
            ) + [
                e
                for e in ms.buffered_events
                if e.event_type == EventType.WorkflowExecutionSignaled
            ]
            for sig in carried:
                a = sig.attributes
                txn.add_workflow_execution_signaled(
                    a.get("signal_name", ""), a.get("input", b""),
                    a.get("identity", ""), now,
                )
            if not new_ms.has_pending_decision():
                txn.add_decision_task_scheduled(now)
            result = txn.close()

            # terminate the old run if it is still running
            self._close_old_run(ctx, ms, reason, identity)

            # persist the new run on a forked branch
            try:
                self._persist_new_run(
                    ctx, ms, new_ms, result, decision_finish_event_id
                )
            except BaseException as e:
                # the old run is already durably terminated; drop the
                # cached state and surface a precise error so the
                # operator retries the reset (idempotent: the old run
                # terminates at most once, the new run id is fresh)
                ctx.clear()
                raise InternalServiceError(
                    f"reset of {workflow_id}/{run_id} terminated the "
                    f"old run but failed to create the new run: {e}; "
                    "retry the reset"
                ) from e
        engine._notify(result)
        return new_run_id

    # -- internals -----------------------------------------------------

    def _read_all_events(self, ctx, ms: MutableState) -> List[HistoryEvent]:
        events, _ = ctx.read_history(ms)
        return events

    def _validate(
        self, ms: MutableState, events: List[HistoryEvent], finish_id: int
    ) -> None:
        if finish_id <= 1 or finish_id > ms.next_event_id:
            raise BadRequestError(
                f"decision_finish_event_id {finish_id} out of range "
                f"(1, {ms.next_event_id}]"
            )
        # the cut must sit at a decision boundary: the last event kept is
        # DecisionTaskStarted, i.e. the event AT finish_id (if recorded)
        # is a decision finish
        by_id = {e.event_id: e for e in events}
        prev = by_id.get(finish_id - 1)
        if prev is None or prev.event_type != EventType.DecisionTaskStarted:
            at = by_id.get(finish_id)
            if at is None or at.event_type not in _DECISION_FINISH_TYPES:
                raise BadRequestError(
                    "reset point must be a decision finish event "
                    "(DecisionTaskCompleted/Failed/TimedOut)"
                )

    def _replay_prefix(
        self,
        domain_id: str,
        workflow_id: str,
        new_run_id: str,
        events: List[HistoryEvent],
        finish_id: int,
    ) -> Tuple[MutableState, StateBuilder]:
        prefix = [e for e in events if e.event_id < finish_id]
        new_ms = MutableState(domain_id=domain_id)
        if self.engine.domains.get_by_id(domain_id).is_global:
            new_ms.version_histories = VersionHistories.new_empty()
        sb = StateBuilder(
            new_ms,
            domain_resolver=lambda name: (
                self.engine.domains.resolve(name).info.id if name else ""
            ),
        )
        sb.apply_events(
            domain_id, "reset", workflow_id, new_run_id, prefix
        )
        # replay ran in passive mode; the new run continues active
        new_ms.execution_info.run_id = new_run_id
        return new_ms, sb

    def _signals_after(
        self, events: List[HistoryEvent], finish_id: int
    ) -> List[HistoryEvent]:
        return [
            e
            for e in events
            if e.event_id >= finish_id
            and e.event_type == EventType.WorkflowExecutionSignaled
        ]

    def _close_old_run(
        self, ctx, ms: MutableState, reason: str, identity: str
    ) -> None:
        if not ms.is_workflow_execution_running():
            return
        txn = self.engine._txn(ctx, ms, ms.current_version)
        txn.add_workflow_execution_terminated(
            self.shard.now(), reason=f"reset: {reason}", identity=identity
        )
        result = txn.close()
        ctx.update_workflow(ms, result)
        self.engine._notify(result)

    def _persist_new_run(
        self,
        ctx,
        old_ms: MutableState,
        new_ms: MutableState,
        result,
        finish_id: int,
    ) -> None:
        history = self.shard.persistence.history
        base_branch = BranchToken.from_json(
            old_ms.execution_info.branch_token.decode()
        )
        forked = history.fork_history_branch(base_branch, finish_id)
        new_ms.execution_info.branch_token = forked.to_json().encode()
        if new_ms.version_histories is not None:
            new_ms.version_histories.get_current_version_history(
            ).branch_token = new_ms.execution_info.branch_token
        if result.events:
            history.append_history_nodes(
                forked, result.events,
                transaction_id=self.shard.next_task_id(),
            )
        from cadence_tpu.core.task_refresher import refresh_tasks

        # the new run inherits the forked prefix: carry the byte
        # accounting so the 200MB history-size limit doesn't restart
        # from zero after every reset
        new_ms.execution_info.history_size = (
            old_ms.execution_info.history_size
        )

        transfer, timer = refresh_tasks(new_ms)
        ei = new_ms.execution_info
        for t in transfer + timer:
            t.domain_id = t.domain_id or ei.domain_id
            t.workflow_id = t.workflow_id or ei.workflow_id
            t.run_id = t.run_id or ei.run_id
        self.shard.assign_task_ids(transfer, timer)
        snapshot = WorkflowSnapshot(
            domain_id=ei.domain_id,
            workflow_id=ei.workflow_id,
            run_id=ei.run_id,
            snapshot=new_ms.snapshot(),
            next_event_id=new_ms.next_event_id,
            last_write_version=new_ms.current_version,
            transfer_tasks=transfer,
            timer_tasks=timer,
        )
        self.shard.persistence.execution.create_workflow_execution(
            self.shard.shard_id,
            self.shard.range_id,
            CreateWorkflowMode.WORKFLOW_ID_REUSE,
            snapshot,
            prev_run_id=old_ms.execution_info.run_id,
        )
