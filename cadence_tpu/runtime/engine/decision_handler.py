"""Decision application: the 13-type client instruction set.

Reference: service/history/decisionTaskHandler.go (the switch at
:137-173) + decisionChecker.go attribute validation. Each decision
translates into ActiveTransaction adds; validation failures fail the
whole decision task with a typed cause, exactly like the reference's
handleDecisionTaskCompleted failure path."""

from __future__ import annotations

import uuid
from typing import List, Optional, Tuple

from cadence_tpu.core.active_transaction import (
    ActiveTransaction,
    WorkflowStateError,
)
from cadence_tpu.core.enums import (
    ContinueAsNewInitiator,
    DecisionType,
    ParentClosePolicy,
)

from ..api import BadRequestError, Decision


class DecisionFailure(Exception):
    def __init__(self, cause: int, message: str) -> None:
        super().__init__(message)
        self.cause = cause


# DecisionTaskFailedCause values (core.enums.DecisionTaskFailedCause)
_CAUSE_BAD_SCHEDULE_ACTIVITY = 1
_CAUSE_BAD_REQUEST_CANCEL_ACTIVITY = 2
_CAUSE_BAD_START_TIMER = 3
_CAUSE_BAD_CANCEL_TIMER = 4
_CAUSE_BAD_RECORD_MARKER = 5
_CAUSE_BAD_COMPLETE_WORKFLOW = 6
_CAUSE_BAD_FAIL_WORKFLOW = 7
_CAUSE_BAD_CANCEL_WORKFLOW = 8
_CAUSE_BAD_REQUEST_CANCEL_EXTERNAL = 9
_CAUSE_BAD_CONTINUE_AS_NEW = 10
_CAUSE_BAD_START_CHILD = 12
_CAUSE_BAD_SIGNAL_EXTERNAL = 14
_CAUSE_UNHANDLED_DECISION = 15
_CAUSE_BAD_UPSERT_SEARCH_ATTR = 22


class DecisionTaskHandler:
    """Applies one RespondDecisionTaskCompleted's decisions to a txn."""

    def __init__(
        self,
        txn: ActiveTransaction,
        completed_event_id: int,
        now: int,
        identity: str = "",
        had_buffered_events: bool = False,
        started_event_fn=None,
    ) -> None:
        self.txn = txn
        self.completed_id = completed_event_id
        self.now = now
        self.identity = identity
        # lazily fetches the run's WorkflowExecutionStarted event (via
        # the shard events cache) — cron/retry restarts need its input
        self.started_event_fn = started_event_fn
        # captured BEFORE the completion event flushed the buffer — the
        # reference computes hasUnhandledEvents before applying decisions
        self.had_buffered_events = had_buffered_events
        self.workflow_closed = False
        # set when a close decision was dropped because unhandled
        # (buffered) events exist — caller schedules a new decision
        self.unhandled_close_dropped = False

    def handle(self, decisions: List[Decision]) -> None:
        for d in decisions:
            if self.workflow_closed:
                raise DecisionFailure(
                    _CAUSE_UNHANDLED_DECISION,
                    "decision after workflow close decision",
                )
            handler = _HANDLERS.get(d.decision_type)
            if handler is None:
                raise DecisionFailure(
                    _CAUSE_UNHANDLED_DECISION,
                    f"unknown decision type {d.decision_type}",
                )
            handler(self, d.attributes)

    # -- helpers ------------------------------------------------------

    def _require(self, cond: bool, cause: int, msg: str) -> None:
        if not cond:
            raise DecisionFailure(cause, msg)

    def _close_allowed(self) -> bool:
        """A close decision is dropped when buffered events exist — the
        workflow has unhandled work (reference: handleDecisionTaskCompleted
        UnhandledDecision path)."""
        if self.had_buffered_events or self.txn.has_buffered_events():
            self.unhandled_close_dropped = True
            return False
        return True

    # -- per-type handlers --------------------------------------------

    def _schedule_activity(self, a: dict) -> None:
        self._require(
            bool(a.get("activity_id")), _CAUSE_BAD_SCHEDULE_ACTIVITY,
            "activityId is not set",
        )
        self._require(
            bool(a.get("activity_type")), _CAUSE_BAD_SCHEDULE_ACTIVITY,
            "activityType is not set",
        )
        s2c = a.get("schedule_to_close_timeout_seconds", 0)
        s2s = a.get("schedule_to_start_timeout_seconds", 0)
        c2c = a.get("start_to_close_timeout_seconds", 0)
        if s2c:
            s2s = s2s or s2c
            c2c = c2c or s2c
        elif s2s and c2c:
            s2c = s2s + c2c
        else:
            raise DecisionFailure(
                _CAUSE_BAD_SCHEDULE_ACTIVITY,
                "a valid timeout combination is required",
            )
        for v in (s2c, s2s, c2c, a.get("heartbeat_timeout_seconds", 0)):
            self._require(
                v >= 0, _CAUSE_BAD_SCHEDULE_ACTIVITY, "negative timeout"
            )
        retry_policy = a.get("retry_policy")
        if isinstance(retry_policy, dict):
            from cadence_tpu.core.events import RetryPolicy

            retry_policy = RetryPolicy.from_dict(retry_policy)
        if retry_policy is not None:
            from cadence_tpu.utils.backoff import validate_retry_policy

            try:
                validate_retry_policy(retry_policy)
            except (ValueError, TypeError) as e:
                # TypeError covers non-numeric fields from loose JSON
                # ("1" <= 0): every malformed attribute must fail the
                # DECISION, not 500 the respond call into a crash loop
                raise DecisionFailure(_CAUSE_BAD_SCHEDULE_ACTIVITY, str(e))
        try:
            self.txn.add_activity_task_scheduled(
                self.completed_id, self.now,
                activity_id=a["activity_id"],
                activity_type=a.get("activity_type", ""),
                task_list=a.get("task_list", "")
                or self.txn.ms.execution_info.task_list,
                schedule_to_close_timeout_seconds=s2c,
                schedule_to_start_timeout_seconds=s2s,
                start_to_close_timeout_seconds=c2c,
                heartbeat_timeout_seconds=a.get("heartbeat_timeout_seconds", 0),
                input=a.get("input", b""),
                retry_policy=retry_policy,
            )
        except WorkflowStateError as e:
            raise DecisionFailure(_CAUSE_BAD_SCHEDULE_ACTIVITY, str(e))

    def _request_cancel_activity(self, a: dict) -> None:
        activity_id = a.get("activity_id", "")
        self._require(
            bool(activity_id), _CAUSE_BAD_REQUEST_CANCEL_ACTIVITY,
            "activityId is not set",
        )
        event, ai = self.txn.add_activity_task_cancel_requested(
            self.completed_id, activity_id, self.now
        )
        from cadence_tpu.core.ids import EMPTY_EVENT_ID

        if ai is not None and ai.started_id == EMPTY_EVENT_ID:
            # never started: cancel completes immediately
            # (reference: decisionTaskHandler RequestCancelActivity —
            # unstarted activities short-circuit to Canceled)
            self.txn.add_activity_task_canceled(
                ai.schedule_id, event.event_id, self.now
            )

    def _start_timer(self, a: dict) -> None:
        self._require(
            bool(a.get("timer_id")), _CAUSE_BAD_START_TIMER,
            "timerId is not set",
        )
        self._require(
            a.get("start_to_fire_timeout_seconds", 0) > 0,
            _CAUSE_BAD_START_TIMER,
            "a valid StartToFireTimeoutSeconds is not set",
        )
        try:
            self.txn.add_timer_started(
                self.completed_id, a["timer_id"],
                a["start_to_fire_timeout_seconds"], self.now,
            )
        except WorkflowStateError as e:
            raise DecisionFailure(_CAUSE_BAD_START_TIMER, str(e))

    def _cancel_timer(self, a: dict) -> None:
        self._require(
            bool(a.get("timer_id")), _CAUSE_BAD_CANCEL_TIMER,
            "timerId is not set",
        )
        self.txn.add_timer_canceled(
            self.completed_id, a["timer_id"], self.now, identity=self.identity
        )

    def _complete_workflow(self, a: dict) -> None:
        if not self._close_allowed():
            return
        if self._restart_after_close("complete"):
            return
        self.txn.add_workflow_execution_completed(
            self.completed_id, self.now, result=a.get("result", b"")
        )
        self.workflow_closed = True

    def _fail_workflow(self, a: dict) -> None:
        if not self._close_allowed():
            return
        if self._restart_after_close("fail", a.get("reason", "")):
            return
        self.txn.add_workflow_execution_failed(
            self.completed_id, self.now,
            reason=a.get("reason", ""), details=a.get("details", b""),
        )
        self.workflow_closed = True

    def _restart_after_close(self, close: str, reason: str = "") -> bool:
        """Cron/retry continue-as-new instead of closing (reference
        workflowExecutionContext retryWorkflow/cronWorkflow)."""
        from .cron_retry import try_continue_after_close

        try:
            restarted = try_continue_after_close(
                self.txn, self.txn.ms, self.started_event_fn, close,
                self.now, error_reason=reason,
                decision_completed_id=self.completed_id,
            )
        except WorkflowStateError as e:
            raise DecisionFailure(_CAUSE_BAD_CONTINUE_AS_NEW, str(e))
        if restarted:
            self.workflow_closed = True
        return restarted

    def _cancel_workflow(self, a: dict) -> None:
        if not self._close_allowed():
            return
        self._require(
            self.txn.ms.execution_info.cancel_requested,
            _CAUSE_BAD_CANCEL_WORKFLOW,
            "workflow cancellation was not requested",
        )
        self.txn.add_workflow_execution_canceled(
            self.completed_id, self.now, details=a.get("details", b"")
        )
        self.workflow_closed = True

    def _request_cancel_external(self, a: dict) -> None:
        self._require(
            bool(a.get("workflow_id")), _CAUSE_BAD_REQUEST_CANCEL_EXTERNAL,
            "workflowId is not set",
        )
        self.txn.add_request_cancel_external_initiated(
            self.completed_id,
            a.get("domain", "") or self.txn.domain_id,
            a["workflow_id"], a.get("run_id", ""),
            a.get("child_workflow_only", False), self.now,
        )

    def _record_marker(self, a: dict) -> None:
        self._require(
            bool(a.get("marker_name")), _CAUSE_BAD_RECORD_MARKER,
            "markerName is not set",
        )
        self.txn.add_marker_recorded(
            self.completed_id, a["marker_name"], self.now,
            details=a.get("details", b""),
        )

    def _continue_as_new(self, a: dict) -> None:
        if not self._close_allowed():
            return
        ei = self.txn.ms.execution_info
        try:
            self.txn.add_continued_as_new(
                self.completed_id, self.now, str(uuid.uuid4()),
                workflow_type=a.get("workflow_type")
                or ei.workflow_type_name,
                task_list=a.get("task_list", "") or ei.task_list,
                execution_start_to_close_timeout_seconds=a.get(
                    "execution_start_to_close_timeout_seconds", 0
                )
                or ei.workflow_timeout,
                task_start_to_close_timeout_seconds=a.get(
                    "task_start_to_close_timeout_seconds", 0
                )
                or ei.decision_timeout_value,
                input=a.get("input", b""),
                backoff_start_interval_seconds=a.get(
                    "backoff_start_interval_seconds", 0
                ),
                initiator=a.get(
                    "initiator", int(ContinueAsNewInitiator.Decider)
                ),
                cron_schedule=ei.cron_schedule,
            )
        except WorkflowStateError as e:
            raise DecisionFailure(_CAUSE_BAD_CONTINUE_AS_NEW, str(e))
        self.workflow_closed = True

    def _start_child(self, a: dict) -> None:
        self._require(
            bool(a.get("workflow_id")), _CAUSE_BAD_START_CHILD,
            "workflowId is not set",
        )
        self._require(
            bool(a.get("workflow_type")), _CAUSE_BAD_START_CHILD,
            "workflowType is not set",
        )
        self.txn.add_start_child_initiated(
            self.completed_id, self.now,
            domain=a.get("domain", "") or self.txn.domain_id,
            workflow_id=a["workflow_id"],
            workflow_type=a.get("workflow_type", ""),
            task_list=a.get("task_list", "")
            or self.txn.ms.execution_info.task_list,
            input=a.get("input", b""),
            execution_start_to_close_timeout_seconds=a.get(
                "execution_start_to_close_timeout_seconds", 0
            )
            or self.txn.ms.execution_info.workflow_timeout,
            task_start_to_close_timeout_seconds=a.get(
                "task_start_to_close_timeout_seconds", 0
            )
            or self.txn.ms.execution_info.decision_timeout_value,
            parent_close_policy=ParentClosePolicy(
                a.get("parent_close_policy", int(ParentClosePolicy.Terminate))
            ),
        )

    def _signal_external(self, a: dict) -> None:
        self._require(
            bool(a.get("workflow_id")), _CAUSE_BAD_SIGNAL_EXTERNAL,
            "workflowId is not set",
        )
        self._require(
            bool(a.get("signal_name")), _CAUSE_BAD_SIGNAL_EXTERNAL,
            "signalName is not set",
        )
        self.txn.add_signal_external_initiated(
            self.completed_id,
            a.get("domain", "") or self.txn.domain_id,
            a["workflow_id"], a.get("run_id", ""),
            a["signal_name"], a.get("input", b""), a.get("control", b""),
            a.get("child_workflow_only", False), self.now,
        )

    def _upsert_search_attributes(self, a: dict) -> None:
        self._require(
            bool(a.get("search_attributes")), _CAUSE_BAD_UPSERT_SEARCH_ATTR,
            "searchAttributes is not set",
        )
        self.txn.add_upsert_search_attributes(
            self.completed_id, a["search_attributes"], self.now
        )


_HANDLERS = {
    DecisionType.ScheduleActivityTask: DecisionTaskHandler._schedule_activity,
    DecisionType.RequestCancelActivityTask: (
        DecisionTaskHandler._request_cancel_activity
    ),
    DecisionType.StartTimer: DecisionTaskHandler._start_timer,
    DecisionType.CompleteWorkflowExecution: DecisionTaskHandler._complete_workflow,
    DecisionType.FailWorkflowExecution: DecisionTaskHandler._fail_workflow,
    DecisionType.CancelTimer: DecisionTaskHandler._cancel_timer,
    DecisionType.CancelWorkflowExecution: DecisionTaskHandler._cancel_workflow,
    DecisionType.RequestCancelExternalWorkflowExecution: (
        DecisionTaskHandler._request_cancel_external
    ),
    DecisionType.RecordMarker: DecisionTaskHandler._record_marker,
    DecisionType.ContinueAsNewWorkflowExecution: DecisionTaskHandler._continue_as_new,
    DecisionType.StartChildWorkflowExecution: DecisionTaskHandler._start_child,
    DecisionType.SignalExternalWorkflowExecution: DecisionTaskHandler._signal_external,
    DecisionType.UpsertWorkflowSearchAttributes: (
        DecisionTaskHandler._upsert_search_attributes
    ),
}
