"""In-process pub/sub for workflow history progress (long-poll).

Reference: service/history/historyEventNotifier.go — GetHistory with
wait-for-new-event subscribes on the workflow identifier; every persisted
transaction publishes (next_event_id, is_running) so blocked pollers
wake as soon as new events land instead of busy-polling persistence.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

_Identifier = Tuple[str, str, str]  # (domain_id, workflow_id, run_id)


class _Subscription:
    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._latest: Optional[Tuple[int, bool]] = None

    def publish(self, next_event_id: int, is_running: bool) -> None:
        with self._cond:
            self._latest = (next_event_id, is_running)
            self._cond.notify_all()

    def wait_for(
        self, min_next_event_id: int, timeout_s: float
    ) -> Optional[Tuple[int, bool]]:
        """Block until next_event_id > min (or the run closes)."""
        import time

        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                if self._latest is not None:
                    next_id, running = self._latest
                    if next_id > min_next_event_id or not running:
                        return self._latest
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self._cond.wait(remaining)


class HistoryEventNotifier:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._subs: Dict[_Identifier, List[_Subscription]] = {}

    def subscribe(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> _Subscription:
        sub = _Subscription()
        with self._lock:
            self._subs.setdefault(
                (domain_id, workflow_id, run_id), []
            ).append(sub)
        return sub

    def unsubscribe(
        self, domain_id: str, workflow_id: str, run_id: str,
        sub: _Subscription,
    ) -> None:
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            subs = self._subs.get(key, [])
            if sub in subs:
                subs.remove(sub)
            if not subs:
                self._subs.pop(key, None)

    def notify(
        self, domain_id: str, workflow_id: str, run_id: str,
        next_event_id: int, is_running: bool,
    ) -> None:
        with self._lock:
            subs = list(self._subs.get((domain_id, workflow_id, run_id), []))
        for sub in subs:
            sub.publish(next_event_id, is_running)
