"""Shard-level LRU of individual history events.

Reference: service/history/eventsCache.go:66-148 — events whose details
are needed again after their transaction (the activity-scheduled event
for poll responses, the child-initiated event for the transfer queue's
start-child processing) are cached per (domain, workflow, run,
event_id) at write time; a miss pages the history branch.

The mutable state's ``cached_events`` staging list (the transition
surface writes there, mutableStateBuilder eventsCache analog) is
drained into this cache when the transaction persists — keeping the
per-workflow state bounded regardless of history length.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Optional, Tuple

from cadence_tpu.core.events import HistoryEvent

Key = Tuple[str, str, str, int]


class EventsCache:
    def __init__(self, max_entries: int = 4096) -> None:
        self._max = max_entries
        self._lock = threading.Lock()
        self._entries: "OrderedDict[Key, HistoryEvent]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def put(
        self, domain_id: str, workflow_id: str, run_id: str,
        event: HistoryEvent,
    ) -> None:
        key = (domain_id, workflow_id, run_id, event.event_id)
        with self._lock:
            self._entries[key] = event
            self._entries.move_to_end(key)
            while len(self._entries) > self._max:
                self._entries.popitem(last=False)

    def get(
        self, domain_id: str, workflow_id: str, run_id: str, event_id: int,
    ) -> Optional[HistoryEvent]:
        key = (domain_id, workflow_id, run_id, event_id)
        with self._lock:
            event = self._entries.get(key)
            if event is not None:
                self._entries.move_to_end(key)
                self.hits += 1
            else:
                self.misses += 1
            return event

    def delete_workflow(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> None:
        prefix = (domain_id, workflow_id, run_id)
        with self._lock:
            for key in [k for k in self._entries if k[:3] == prefix]:
                del self._entries[key]

    def size(self) -> int:
        with self._lock:
            return len(self._entries)
