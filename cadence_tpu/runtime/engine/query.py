"""Consistent-query registry: buffered → started → completed.

Reference: service/history/queryRegistry.go + queryStateMachine.go:40-77
— queries against a workflow with a pending decision task are buffered
and piggybacked on the next decision task dispatch
(RecordDecisionTaskStarted response carries them); the worker answers
them in RespondDecisionTaskCompleted.query_results. Queries against an
idle workflow dispatch directly to matching (sync query task).
"""

from __future__ import annotations

import threading
import uuid
from typing import Any, Dict, List, Optional, Tuple


class QueryStateName:
    BUFFERED = 0
    STARTED = 1
    COMPLETED = 2


class QueryState:
    """One in-flight query's 3-state machine."""

    def __init__(self, query_type: str, query_args: bytes) -> None:
        self.id = str(uuid.uuid4())
        self.query_type = query_type
        self.query_args = query_args
        self.state = QueryStateName.BUFFERED
        self.result: Optional[bytes] = None
        self.error: Optional[str] = None
        self._done = threading.Event()

    def start(self) -> None:
        if self.state == QueryStateName.BUFFERED:
            self.state = QueryStateName.STARTED

    def complete(self, result: Optional[bytes], error: Optional[str]) -> None:
        self.state = QueryStateName.COMPLETED
        self.result = result
        self.error = error
        self._done.set()

    def wait(self, timeout_s: float) -> bool:
        return self._done.wait(timeout_s)


class QueryRegistry:
    """Per-shard registry keyed by workflow run."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._queries: Dict[Tuple[str, str, str], List[QueryState]] = {}

    def buffer(
        self, domain_id: str, workflow_id: str, run_id: str,
        query_type: str, query_args: bytes,
    ) -> QueryState:
        q = QueryState(query_type, query_args)
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            self._queries.setdefault(key, []).append(q)
        return q

    def take_buffered(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> List[QueryState]:
        """Queries to attach to a decision task dispatch: buffered ones
        move to started; already-started-but-unanswered ones are
        RE-attached (the worker that first carried them may have died —
        re-delivery keeps them answerable until the caller times out)."""
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            out = [
                q
                for q in self._queries.get(key, [])
                if q.state != QueryStateName.COMPLETED
            ]
            for q in out:
                q.start()
        return out

    def buffered_count(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> int:
        """Queries not yet attached to any decision dispatch — the count
        that justifies scheduling a fresh decision task."""
        with self._lock:
            return sum(
                1
                for q in self._queries.get(
                    (domain_id, workflow_id, run_id), []
                )
                if q.state == QueryStateName.BUFFERED
            )

    def complete(
        self, domain_id: str, workflow_id: str, run_id: str,
        results: Dict[str, Dict[str, Any]],
    ) -> int:
        """Complete queries by id from a worker's query_results map
        ({query_id: {"result": bytes} | {"error": str}})."""
        key = (domain_id, workflow_id, run_id)
        done = 0
        with self._lock:
            pending = self._queries.get(key, [])
            by_id = {q.id: q for q in pending}
            for qid, res in results.items():
                q = by_id.get(qid)
                if q is None:
                    continue
                q.complete(res.get("result"), res.get("error"))
                done += 1
            self._queries[key] = [
                q for q in pending if q.state != QueryStateName.COMPLETED
            ]
            if not self._queries[key]:
                del self._queries[key]
        return done

    def fail(
        self, domain_id: str, workflow_id: str, run_id: str,
        query: QueryState, error: str,
    ) -> None:
        """Fail ONE query (e.g. its caller's timeout) without touching
        other callers' pending queries on the same run."""
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            pending = self._queries.get(key, [])
            if query in pending:
                pending.remove(query)
                if not pending:
                    del self._queries[key]
        query.complete(None, error)

    def fail_all(
        self, domain_id: str, workflow_id: str, run_id: str, error: str
    ) -> None:
        key = (domain_id, workflow_id, run_id)
        with self._lock:
            for q in self._queries.pop(key, []):
                q.complete(None, error)

    def pending_count(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> int:
        with self._lock:
            return len(self._queries.get((domain_id, workflow_id, run_id), []))
