"""History engine: the workflow-mutation core.

Reference: service/history/historyEngine.go + decisionHandler.go +
workflowExecutionContext.go + historyCache.go. Every mutation follows
the same discipline: acquire the per-workflow lock, load mutable state,
build an ActiveTransaction, persist events + state + queue tasks under
the shard's range_id and the load-time next_event_id condition, retrying
the whole body on ConditionFailedError (the Update_History_Loop)."""

from .engine import HistoryEngine
