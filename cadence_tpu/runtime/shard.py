"""Shard context: per-shard sequencing, ack levels, range fencing.

Reference: service/history/shardContext.go — every history-shard write
carries the shard's range_id; task IDs are allocated monotonically from
range-scoped blocks so a stolen shard can never mint colliding or
regressing IDs (taskID = range_id << 24 | seq, renewing the lease when a
block exhausts, mirroring the reference's transferSequenceNumber block
scheme)."""

from __future__ import annotations

from typing import Dict, Optional

from cadence_tpu.utils.clock import RealTimeSource, TimeSource
from cadence_tpu.utils.locks import make_guarded, make_rlock

from .persistence.errors import (
    EntityNotExistsError,
    ShardOwnershipLostError,
)
from .persistence.interfaces import PersistenceBundle
from .persistence.records import ShardInfo

BLOCK_BITS = 24
BLOCK_SIZE = 1 << BLOCK_BITS


class ShardContext:
    def __init__(
        self,
        shard_id: int,
        persistence: PersistenceBundle,
        owner: str = "",
        time_source: Optional[TimeSource] = None,
    ) -> None:
        self.shard_id = shard_id
        self.persistence = persistence
        self.owner = owner
        self.time_source = time_source or RealTimeSource()
        self._lock = make_rlock("ShardContext._lock")
        self._remote_cluster_time: dict = make_guarded(
            {}, "ShardContext._remote_cluster_time", self._lock
        )
        self._remote_time_listeners: list = make_guarded(
            [], "ShardContext._remote_time_listeners", self._lock
        )
        self._fenced = False
        self._info = self._acquire()
        self._next_task_seq = 0

    # -- lease --------------------------------------------------------

    def _acquire(self) -> ShardInfo:
        try:
            info = self.persistence.shard.get_shard(self.shard_id)
        except EntityNotExistsError:
            info = ShardInfo(shard_id=self.shard_id, range_id=0)
            self.persistence.shard.create_shard(info)
        info.owner = self.owner
        self._bump_range_with_retry(info)
        return info

    def _bump_range_with_retry(self, info: ShardInfo) -> None:
        """Bump ``info.range_id`` durably, surviving the torn-write
        reality: a bump whose ack was lost LANDED — re-reading the row
        and seeing our bump (same range, our owner) IS success, and a
        transient error simply retries. A bump by someone ELSE means
        the shard moved mid-acquire: re-bump from their lease so our
        writes still fence theirs (last-acquirer-wins, exactly the
        reference's steal semantics)."""
        last_exc = None
        for _ in range(4):
            prev = info.range_id
            info.range_id = prev + 1
            try:
                self.persistence.shard.update_shard(
                    info, previous_range_id=prev
                )
                return
            except Exception as e:
                last_exc = e
                try:
                    stored = self.persistence.shard.get_shard(self.shard_id)
                except Exception:
                    info.range_id = prev
                    continue
                if (
                    stored.range_id == info.range_id
                    and stored.owner == info.owner
                ):
                    return  # our torn write landed
                # someone else's lease (or a stale read): adopt and retry
                info.__dict__.update(stored.__dict__)
                info.owner = self.owner
        raise last_exc

    @property
    def range_id(self) -> int:
        """The current lease for stamping writes. Raises once the shard
        is fenced for a reshard handoff: the context bumped its OWN
        lease, so only an explicit refusal stops it from minting valid
        writes against a shard that is being moved (clients retry
        through the ring and land on the new owner after the flip)."""
        with self._lock:
            if self._fenced:
                raise ShardOwnershipLostError(
                    self.shard_id, f"shard {self.shard_id} fenced for reshard"
                )
            return self._info.range_id

    @property
    def fenced(self) -> bool:
        with self._lock:
            return self._fenced

    def fence(self) -> None:
        """Reshard handoff step (2): bump the lease (anything still
        holding the old range_id fences at the store — a stolen shard
        can never mint regressing task IDs) and refuse all further
        writes/task-ID mints from THIS context. Idempotent, and it
        survives torn lease writes (chaos on persistence.shard)."""
        with self._lock:
            if self._fenced:
                return
            self._bump_range_with_retry(self._info)
            self._next_task_seq = 0
            self._fenced = True

    def renew_range(self) -> None:
        """Bump the lease (new task-ID block; fences older owners)."""
        with self._lock:
            prev = self._info.range_id
            self._info.range_id += 1
            self.persistence.shard.update_shard(
                self._info, previous_range_id=prev
            )
            self._next_task_seq = 0

    # -- task id sequencing -------------------------------------------

    def next_task_id(self) -> int:
        with self._lock:
            if self._fenced:
                raise ShardOwnershipLostError(
                    self.shard_id, f"shard {self.shard_id} fenced for reshard"
                )
            if self._next_task_seq >= BLOCK_SIZE:
                self.renew_range()
            tid = (self._info.range_id << BLOCK_BITS) | self._next_task_seq
            self._next_task_seq += 1
            return tid

    def assign_task_ids(self, *task_lists) -> None:
        """Stamp task_id on every task in the given lists."""
        for tasks in task_lists:
            for t in tasks:
                t.task_id = self.next_task_id()

    # -- ack levels ---------------------------------------------------

    def _update(self) -> None:
        """Persist ack-level/cursor state under the CURRENT lease.
        Same-range writes are idempotent (the condition still matches
        after a torn write lands), so transient store errors get a
        bounded retry; a genuine fence (newer range) surfaces."""
        last_exc = None
        for _ in range(3):
            try:
                self.persistence.shard.update_shard(
                    self._info, previous_range_id=self._info.range_id
                )
                return
            except ShardOwnershipLostError:
                raise
            except Exception as e:
                last_exc = e
        raise last_exc

    def get_transfer_ack_level(self) -> int:
        with self._lock:
            return self._info.transfer_ack_level

    def update_transfer_ack_level(self, level: int) -> None:
        with self._lock:
            self._info.transfer_ack_level = level
            self._update()

    def get_timer_ack_level(self) -> int:
        with self._lock:
            return self._info.timer_ack_level

    def update_timer_ack_level(self, level: int) -> None:
        with self._lock:
            self._info.timer_ack_level = level
            self._update()

    def ensure_cluster_ack_levels(self, cluster: str) -> None:
        """Checkpoint the standby cursors at standby-plane construction.
        Without a persisted per-cluster level the getters would fall
        back to the LIVE active ack level — which moves past standby-
        owned tasks, letting queue GC delete rows the standby never
        verified and making a failover rewind a no-op."""
        with self._lock:
            changed = False
            if cluster not in self._info.cluster_transfer_ack_level:
                self._info.cluster_transfer_ack_level[cluster] = (
                    self._info.transfer_ack_level
                )
                changed = True
            if cluster not in self._info.cluster_timer_ack_level:
                self._info.cluster_timer_ack_level[cluster] = (
                    self._info.timer_ack_level
                )
                changed = True
            if changed:
                self._update()

    def get_cluster_transfer_ack_level(self, cluster: str) -> int:
        """Per-remote-cluster standby cursor; falls back to the shard's
        own transfer ack level (ref shardContext.go clusterTransferAckLevel)."""
        with self._lock:
            return self._info.cluster_transfer_ack_level.get(
                cluster, self._info.transfer_ack_level
            )

    def update_cluster_transfer_ack_level(self, cluster: str, level: int) -> None:
        with self._lock:
            self._info.cluster_transfer_ack_level[cluster] = level
            self._update()

    def get_cluster_timer_ack_level(self, cluster: str) -> int:
        with self._lock:
            return self._info.cluster_timer_ack_level.get(
                cluster, self._info.timer_ack_level
            )

    def update_cluster_timer_ack_level(self, cluster: str, level: int) -> None:
        with self._lock:
            self._info.cluster_timer_ack_level[cluster] = level
            self._update()

    # -- remote cluster clocks (ref shardContext.go SetCurrentTime) ----

    def set_remote_cluster_current_time(self, cluster: str, now_ns: int) -> None:
        """Advance the view of a remote cluster's clock (fed by its
        replication stream); standby timer processing fires against this
        clock, never the local one."""
        with self._lock:
            cur = self._remote_cluster_time.get(cluster, 0)
            if now_ns > cur:
                self._remote_cluster_time[cluster] = now_ns
            # snapshot under the lock; fire outside it (listener code
            # must not run under the shard lock)
            listeners = list(self._remote_time_listeners)
        for listener in listeners:
            listener(cluster, now_ns)

    def get_remote_cluster_current_time(self, cluster: str) -> int:
        with self._lock:
            return self._remote_cluster_time.get(cluster, 0)

    def add_remote_time_listener(self, fn) -> None:
        # under the lock: registration races with the replication
        # pump's snapshot in set_remote_cluster_current_time (the
        # sanitizer's GUARDED-FIELD-RACE caught the bare append)
        with self._lock:
            self._remote_time_listeners.append(fn)

    def remove_remote_time_listener(self, fn) -> None:
        """Detach a listener (standby processor stop): a dead processor
        must not stay reachable from the shard's listener list."""
        with self._lock:
            try:
                self._remote_time_listeners.remove(fn)
            except ValueError:
                pass

    def get_replication_ack_level(self) -> int:
        with self._lock:
            return self._info.replication_ack_level

    def update_replication_ack_level(self, level: int) -> None:
        with self._lock:
            self._info.replication_ack_level = level
            self._update()

    # -- time ---------------------------------------------------------

    def now(self) -> int:
        return self.time_source.now()
