"""History service assembly: controller + engines + queue processors.

Reference: /root/reference/service/history/service.go + handler.go —
the history service owns a shard controller whose per-shard engines are
wired to transfer/timer queue processors, a matching client for task
pushes, and a history client for cross-shard workflow calls.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from cadence_tpu.utils.clock import TimeSource
from cadence_tpu.utils.log import get_logger

from .controller import ShardController, _ShardHandle
from .domains import DomainCache
from .engine.engine import HistoryEngine
from .membership import Monitor
from .persistence.interfaces import PersistenceBundle
from .queues import (
    QueueGC,
    TimerQueueProcessor,
    TimerQueueStandbyProcessor,
    TransferQueueProcessor,
    TransferQueueStandbyProcessor,
)
from .shard import ShardContext


class HistoryService:
    """One history host: all shards this host owns, fully wired."""

    def __init__(
        self,
        num_shards: int,
        persistence: PersistenceBundle,
        domain_cache: DomainCache,
        monitor: Monitor,
        time_source: Optional[TimeSource] = None,
        queue_worker_count: int = 4,
        cluster_metadata=None,
        metrics=None,
        rebuild_chunk_size: int = 0,
        faults=None,
        queue_exhausted_retry_delay_s: Optional[float] = None,
        checkpoints=None,
        serving=None,
        rate_limiter=None,
        queue_executor=None,
    ) -> None:
        from cadence_tpu.utils.metrics import Scope

        self.cluster_metadata = cluster_metadata
        self.persistence = persistence
        self.domains = domain_cache
        self.monitor = monitor
        self._time = time_source
        self._queue_workers = queue_worker_count
        # per-task-type queue triples + standby hold depth + replication
        # lag all hang off this scope (reference common/metrics/defs.go
        # task-type scopes); a real registry by default so canary/tests
        # can assert on it via service.metrics.registry
        self.metrics = metrics if metrics is not None else Scope()
        # rebuild_many device-chunk rows; 0 = backend-resolved default
        # (dynamicconfig history.rebuildChunkSize via bootstrap)
        self.rebuild_chunk_size = rebuild_chunk_size
        # chaos: a testing.faults.FaultSchedule threaded into every
        # queue processor and the replication planes; None in any
        # non-chaos deployment (no hook objects are even constructed).
        # queue_exhausted_retry_delay_s shrinks the park interval so a
        # park-then-drain chaos run completes at test-scale (None =
        # the production default)
        self.faults = faults
        self._queue_park_delay_s = queue_exhausted_retry_delay_s
        # checkpoint.CheckpointManager (config `checkpoint:` section):
        # every shard's state rebuilder resumes replays from durable
        # snapshots and writes fresh ones. None = cold rebuilds only.
        self.checkpoints = checkpoints
        # serving.ResidentEngine (config `serving:` section): hot
        # workflows' state rows stay device-resident; every persisted
        # event batch marks the lane behind (O(1)), serving reads
        # answer from the resident row with the Δ composed. None =
        # every serving read is a cold rebuild
        self.serving = serving
        # overload control (ISSUE 15): a MultiStageRateLimiter every
        # owned shard's engine consults on ingress writes — sheds with
        # the retryable ServiceBusyError + retry-after. None = never
        # shed at this layer (the frontend's limiter still applies)
        self.rate_limiter = rate_limiter
        # queues.ParallelQueueExecutor (config `queues.parallelism`):
        # the shared conflict-keyed wave scheduler every owned shard's
        # transfer/timer pumps register with. None (the default) keeps
        # the sequential per-queue pump threads.
        self.queue_executor = queue_executor
        # the serving tick pump (serving/pump.py), started when the
        # engine carries a configured cadence (serving.tickIntervalMs)
        self._tick_pump = None
        # config.ReshardingConfig (`resharding:` section) — read by the
        # admin reshard verbs; None = defaults (enabled)
        self.resharding_config = None
        # the ONE ReshardCoordinator per host: the admin verbs and the
        # capacity autopilot share it (two coordinators would each hold
        # their own lock — "one plan at a time" must be host-wide)
        self._resharder = None
        self._resharder_lock = threading.Lock()
        # runtime.autopilot.CapacityController (config `autopilot:`
        # section), attached by bootstrap/Onebox; None = manual capacity
        self.autopilot = None
        self._log = get_logger(
            "cadence_tpu.history.service", host=monitor.self_identity
        )
        # late-bound clients (wire() resolves the construction cycle:
        # processors need clients; clients need the controller)
        self.matching_client = None
        self.history_client = None
        # config.ReplicationConfig (`replication:` section) — adaptive
        # transport + pump backoff knobs; None = defaults (adaptive on)
        self.replication_config = None
        # remote-cluster pull plane: cluster -> (client, fetcher,
        # transport); each owned shard gets a ReplicationTaskProcessor
        # per entry, all sharing the link's transport/estimator
        self._replication_sources: Dict[str, tuple] = {}
        # remote clusters this host stands by for (standby queue planes)
        self.standby_clusters: List[str] = []
        if cluster_metadata is not None:
            self.standby_clusters = list(
                cluster_metadata.enabled_remote_clusters()
            )
        self.controller = ShardController(
            num_shards, persistence, domain_cache, monitor,
            engine_factory=self._build_shard, time_source=time_source,
        )
        # failover: when a domain becomes active here, rewind the active
        # cursors to the standby cursor of the cluster it came from so
        # the skipped passive span is re-verified (idempotent handlers)
        domain_cache.add_failover_listener(self._on_domain_failover)

    def wire(self, matching_client, history_client) -> "HistoryService":
        self.matching_client = matching_client
        self.history_client = history_client
        return self

    def start(self) -> None:
        if self.matching_client is None or self.history_client is None:
            raise RuntimeError("HistoryService.wire() must be called first")
        if self.queue_executor is not None:
            # before acquire_shards: _build_shard registers each shard's
            # pumps with the executor, which must already be pumping
            # (start() is idempotent — a shared executor across services
            # starts once)
            self.queue_executor.start()
        self.controller.acquire_shards()
        if (self.serving is not None
                and getattr(self.serving, "tick_interval_s", 0) > 0):
            from cadence_tpu.serving.pump import TickPump

            # bounded staleness: the pump composes write-heavy lanes'
            # persist-feed debt at the configured cadence even with
            # zero read traffic (serving_staleness_ms is the proof)
            self._tick_pump = TickPump(
                self.serving, self.serving.tick_interval_s,
                metrics=self.metrics,
            ).start()
        if self.autopilot is not None:
            self.autopilot.start()

    def stop(self) -> None:
        if self.autopilot is not None:
            # the controller goes FIRST: a retune or reshard proposal
            # racing the drain below would act on shards mid-teardown
            self.autopilot.stop()
        if self._tick_pump is not None:
            # pump drain-on-stop FIRST: its final tick composes Δs
            # staged since the last cycle, so the lane flush below
            # writes tip-accurate snapshots
            self._tick_pump.stop()
            self._tick_pump = None
        if self.serving is not None:
            # flush every resident lane back through the checkpoint
            # plane before the shards go away (clean drain: the next
            # boot's admissions resume suffix-only)
            self.serving.drain()
        self.controller.stop()
        if self.queue_executor is not None:
            self.queue_executor.stop()

    # -- per-shard assembly --------------------------------------------

    def _build_shard(self, shard: ShardContext) -> _ShardHandle:
        # metrics must ride the CONSTRUCTOR: instrument_methods wraps
        # the per-op triple (and trace spans) at __init__ time, so a
        # post-construction `engine.metrics = ...` left every history
        # API latency in the NOOP registry (found by the telemetry
        # verification drive — p50/p99 read 0 forever)
        engine = HistoryEngine(shard, self.domains, metrics=self.metrics)
        engine.cluster_metadata = self.cluster_metadata
        engine.rebuild_chunk_size = self.rebuild_chunk_size
        engine.faults = self.faults
        engine.checkpoints = self.checkpoints
        engine.serving = self.serving
        engine.rate_limiter = self.rate_limiter
        engine.matching_client = self.matching_client
        has_standby = bool(self.standby_clusters)
        transfer = TransferQueueProcessor(
            shard, engine, self.matching_client, self.history_client,
            worker_count=self._queue_workers,
            standby_clusters=self.standby_clusters,
            metrics=self.metrics,
            faults=self.faults,
            exhausted_retry_delay_s=self._queue_park_delay_s,
            executor=self.queue_executor,
        )
        timer = TimerQueueProcessor(
            shard, engine, matching=self.matching_client,
            worker_count=self._queue_workers,
            standby_clusters=self.standby_clusters,
            metrics=self.metrics,
            faults=self.faults,
            exhausted_retry_delay_s=self._queue_park_delay_s,
            executor=self.queue_executor,
        )
        processors = [transfer, timer]
        notifiers = [transfer.notify]
        timer_notifiers = [timer.notify]
        local_cluster = (
            self.cluster_metadata.current_cluster_name
            if self.cluster_metadata is not None else ""
        )

        def transfer_handover(level, _t=transfer):
            _t.ack.rewind(level)
            _t.notify()

        def timer_handover(level, _t=timer):
            _t.ack.rewind(level)
            _t.notify()

        for cluster in self.standby_clusters:
            ts = TransferQueueStandbyProcessor(
                shard, engine, cluster, local_cluster=local_cluster,
                on_handover=transfer_handover, metrics=self.metrics,
                faults=self.faults,
                exhausted_retry_delay_s=self._queue_park_delay_s,
            )
            tm = TimerQueueStandbyProcessor(
                shard, engine, cluster, local_cluster=local_cluster,
                on_handover=timer_handover, metrics=self.metrics,
                faults=self.faults,
                exhausted_retry_delay_s=self._queue_park_delay_s,
            )
            processors += [ts, tm]
            notifiers.append(ts.notify)
            timer_notifiers.append(tm.notify)
        if has_standby:
            processors.append(QueueGC(
                shard, transfer, timer, self.standby_clusters
            ))
        engine._task_notifier = lambda: [n() for n in notifiers]
        engine._timer_notifier = lambda: [n() for n in timer_notifiers]
        # pull-replication consumers: one per registered source cluster
        # (reference replicationTaskProcessor per shard per remote).
        # AFTER the notifier assignment: touching engine.ndc_replicator
        # materializes it with whatever notifiers exist at that moment
        for cluster, (client, fetcher, transport) in (
            self._replication_sources.items()
        ):
            from .replication import (
                HistoryRereplicator,
                ReplicationTaskProcessor,
            )

            rerepl = HistoryRereplicator(
                client, engine.ndc_replicator, transport=transport,
                metrics=self.metrics,
            )
            rc = self.replication_config
            processors.append(
                ReplicationTaskProcessor(
                    shard, engine.ndc_replicator, fetcher,
                    rereplicator=rerepl, metrics=self.metrics,
                    transport=transport,
                    backoff_max_s=(
                        rc.backoff_max_s if rc is not None else 5.0
                    ),
                )
            )
        for p in processors:
            p.start()
        return _ShardHandle(shard, engine, processors)

    def enable_replication_from(self, cluster: str, client) -> None:
        """Register a remote source cluster's pull client (an in-proc
        adapter or rpc.RemoteClusterRPCClient) BEFORE start(): every
        owned shard then runs a ReplicationTaskProcessor draining that
        cluster's replicator queue (reference replicationTaskFetcher +
        replicationTaskProcessor assembly, service/history/service.go).

        The link also gets one AdaptiveTransport (estimator + mode
        controller, shared across the shards' processors the way the
        fetcher is) unless the `replication:` config disables it."""
        from .replication import ReplicationTaskFetcher
        from .replication.transport import AdaptiveTransport

        rc = self.replication_config
        transport = None
        if rc is None or rc.adaptive:
            transport = AdaptiveTransport(
                client, cluster,
                hysteresis=rc.hysteresis if rc is not None else 1.5,
                min_dwell=rc.min_dwell if rc is not None else 2,
                min_gap_events=(
                    rc.min_gap_events if rc is not None else 32
                ),
                snapshot_bytes_prior=(
                    rc.snapshot_bytes_prior
                    if rc is not None else 64 * 1024.0
                ),
                metrics=self.metrics,
            )
        self._replication_sources[cluster] = (
            client, ReplicationTaskFetcher(cluster, client), transport
        )

    def _on_domain_failover(
        self, domain_id: str, old_cluster: str, new_cluster: str
    ) -> None:
        meta = self.cluster_metadata
        if meta is None or new_cluster != meta.current_cluster_name:
            return
        if old_cluster not in self.standby_clusters:
            return
        with self.controller._lock:
            handles = list(self.controller._handles.values())
        for handle in handles:
            shard = handle.shard
            for p in handle.processors:
                if isinstance(p, TransferQueueProcessor):
                    p.ack.rewind(
                        shard.get_cluster_transfer_ack_level(old_cluster)
                    )
                    p.notify()
                elif isinstance(p, TimerQueueProcessor):
                    p.ack.rewind(
                        (shard.get_cluster_timer_ack_level(old_cluster), 0)
                    )
                    p.notify()
        self._log.info(
            f"domain {domain_id} failed over {old_cluster}->{new_cluster}; "
            "rewound active queue cursors to standby levels"
        )

    # -- serving plane -------------------------------------------------

    def serving_read(
        self, domain_id: str, workflow_id: str, run_id: str = ""
    ):
        """Serving-plane decision/query read (config `serving:`): a hot
        workflow answers straight from its resident lane (Δs composed
        first); a miss seats the workflow — the next read is resident.
        Returns a serving.ResidentRead; None when the serving caps
        cannot pack the history (``serving_cold_read_failures`` — the
        rebuild verbs stay the recovery path); raises RuntimeError when
        the section is disabled (callers fall back to the rebuild
        path)."""
        import time as _time

        if self.serving is None:
            raise RuntimeError("serving: section not enabled")
        t0 = _time.perf_counter()
        engine = self.controller.get_engine(workflow_id)
        shard = engine.shard
        if not run_id:
            run_id = shard.persistence.execution.get_current_execution(
                shard.shard_id, domain_id, workflow_id
            ).run_id
        got = self.serving.resident_row(
            workflow_id, run_id, domain_id=domain_id
        )
        if got is not None:
            # same accounting as the engine's own read verbs, so
            # resident-hit latency never vanishes from the histogram
            # depending on which entry point answered
            scope = self.metrics.tagged(layer="serving")
            scope.inc("serving_resident_hits")
            scope.record(
                "serving_read_seconds", _time.perf_counter() - t0
            )
            return got
        resp = shard.persistence.execution.get_workflow_execution(
            shard.shard_id, domain_id, workflow_id, run_id
        )
        branch_token = resp.snapshot["execution_info"]["branch_token"]
        return self.serving.read_through(
            domain_id, workflow_id, run_id, branch_token
        )

    # -- resharding ----------------------------------------------------

    def reshard_coordinator(self):
        """The host's ONE ReshardCoordinator, built lazily: the admin
        verbs and the capacity autopilot both call through here, so
        their plans serialize on the same coordinator lock — one plan
        at a time is a host property, not a caller property."""
        with self._resharder_lock:
            if self._resharder is None:
                from cadence_tpu.runtime.resharding import (
                    ReshardCoordinator,
                )

                cfg = self.resharding_config
                self._resharder = ReshardCoordinator(
                    self.persistence,
                    [self.controller],
                    metrics=self.metrics,
                    drain_timeout_s=(
                        cfg.drain_timeout_s if cfg is not None else 10.0
                    ),
                    checkpoint_flush=(
                        cfg.checkpoint_flush if cfg is not None else True
                    ),
                    time_source=self._time,
                )
            return self._resharder

    # -- introspection -------------------------------------------------

    def describe(self) -> dict:
        return self.controller.describe()

    def describe_queue_states(self, shard_id: int) -> dict:
        """Per-queue cursor/depth view of one owned shard (reference
        tools/cli/adminQueueCommands.go DescribeQueue): each processor's
        ack level plus in-flight and parked (standby hold) depths — the
        operator view of a wedged ack sweep. Raises KeyError for a
        shard this host doesn't own (AdminHandler maps to 404)."""
        with self.controller._lock:
            handle = self.controller._handles.get(shard_id)
        if handle is None:
            raise KeyError(shard_id)

        def _level(v):
            return list(v) if isinstance(v, tuple) else v

        queues = []
        for p in handle.processors:
            ack = getattr(p, "ack", None)
            if ack is None:
                continue  # e.g. QueueGC / replication consumers
            queues.append({
                "queue": getattr(p, "name", type(p).__name__),
                "ack_level": _level(ack.ack_level),
                "read_level": _level(ack.read_level),
                "outstanding": ack.outstanding(),
                "held": ack.held(),
            })
        return {"shard_id": shard_id, "queues": queues}

    def drain_queues(self, timeout_s: float = 10.0) -> bool:
        """Wait until every owned shard's queues are quiescent (tests)."""
        ok = True
        with self.controller._lock:
            handles = list(self.controller._handles.values())
        for handle in handles:
            for p in handle.processors:
                ok = p.drain(timeout_s) and ok
        return ok

    # -- replication plane ---------------------------------------------
    # Reference: handler.go GetReplicationMessages / ReplicateEventsV2.

    def replicate_events_v2(self, task) -> None:
        engine = self.controller.get_engine(task.workflow_id)
        engine.replicate_events_v2(task)

    def get_replication_messages(
        self, shard_id: int, last_retrieved_id: int, cluster: str,
        max_tasks=None,
    ):
        engine = self.controller.get_engine_for_shard(shard_id)
        return engine.get_replication_messages(
            cluster, last_retrieved_id, max_tasks=max_tasks
        )

    def get_workflow_history_raw(
        self, domain_id: str, workflow_id: str, run_id: str,
        start_event_id: int, end_event_id: int,
    ):
        engine = self.controller.get_engine(workflow_id)
        return engine.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start_event_id, end_event_id
        )

    def get_replication_backlog(
        self, shard_id: int, last_retrieved_id: int
    ):
        engine = self.controller.get_engine_for_shard(shard_id)
        return engine.get_replication_backlog(last_retrieved_id)

    def get_replication_checkpoint(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> bytes:
        engine = self.controller.get_engine(workflow_id)
        return engine.get_replication_checkpoint(
            domain_id, workflow_id, run_id
        )
