"""History service assembly: controller + engines + queue processors.

Reference: /root/reference/service/history/service.go + handler.go —
the history service owns a shard controller whose per-shard engines are
wired to transfer/timer queue processors, a matching client for task
pushes, and a history client for cross-shard workflow calls.
"""

from __future__ import annotations

from typing import List, Optional

from cadence_tpu.utils.clock import TimeSource
from cadence_tpu.utils.log import get_logger

from .controller import ShardController, _ShardHandle
from .domains import DomainCache
from .engine.engine import HistoryEngine
from .membership import Monitor
from .persistence.interfaces import PersistenceBundle
from .queues import TimerQueueProcessor, TransferQueueProcessor
from .shard import ShardContext


class HistoryService:
    """One history host: all shards this host owns, fully wired."""

    def __init__(
        self,
        num_shards: int,
        persistence: PersistenceBundle,
        domain_cache: DomainCache,
        monitor: Monitor,
        time_source: Optional[TimeSource] = None,
        queue_worker_count: int = 4,
        cluster_metadata=None,
    ) -> None:
        self.cluster_metadata = cluster_metadata
        self.persistence = persistence
        self.domains = domain_cache
        self.monitor = monitor
        self._time = time_source
        self._queue_workers = queue_worker_count
        self._log = get_logger(
            "cadence_tpu.history.service", host=monitor.self_identity
        )
        # late-bound clients (wire() resolves the construction cycle:
        # processors need clients; clients need the controller)
        self.matching_client = None
        self.history_client = None
        self.controller = ShardController(
            num_shards, persistence, domain_cache, monitor,
            engine_factory=self._build_shard, time_source=time_source,
        )

    def wire(self, matching_client, history_client) -> "HistoryService":
        self.matching_client = matching_client
        self.history_client = history_client
        return self

    def start(self) -> None:
        if self.matching_client is None or self.history_client is None:
            raise RuntimeError("HistoryService.wire() must be called first")
        self.controller.acquire_shards()

    def stop(self) -> None:
        self.controller.stop()

    # -- per-shard assembly --------------------------------------------

    def _build_shard(self, shard: ShardContext) -> _ShardHandle:
        engine = HistoryEngine(shard, self.domains)
        engine.cluster_metadata = self.cluster_metadata
        engine.matching_client = self.matching_client
        transfer = TransferQueueProcessor(
            shard, engine, self.matching_client, self.history_client,
            worker_count=self._queue_workers,
        )
        timer = TimerQueueProcessor(
            shard, engine, matching=self.matching_client,
            worker_count=self._queue_workers,
        )
        engine._task_notifier = transfer.notify
        engine._timer_notifier = timer.notify
        transfer.start()
        timer.start()
        return _ShardHandle(shard, engine, [transfer, timer])

    # -- introspection -------------------------------------------------

    def describe(self) -> dict:
        return self.controller.describe()

    def drain_queues(self, timeout_s: float = 10.0) -> bool:
        """Wait until every owned shard's queues are quiescent (tests)."""
        ok = True
        with self.controller._lock:
            handles = list(self.controller._handles.values())
        for handle in handles:
            for p in handle.processors:
                ok = p.drain(timeout_s) and ok
        return ok

    # -- replication plane ---------------------------------------------
    # Reference: handler.go GetReplicationMessages / ReplicateEventsV2.

    def replicate_events_v2(self, task) -> None:
        engine = self.controller.get_engine(task.workflow_id)
        engine.replicate_events_v2(task)

    def get_replication_messages(
        self, shard_id: int, last_retrieved_id: int, cluster: str
    ):
        engine = self.controller.get_engine_for_shard(shard_id)
        return engine.get_replication_messages(cluster, last_retrieved_id)

    def get_workflow_history_raw(
        self, domain_id: str, workflow_id: str, run_id: str,
        start_event_id: int, end_event_id: int,
    ):
        engine = self.controller.get_engine(workflow_id)
        return engine.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start_event_id, end_event_id
        )
