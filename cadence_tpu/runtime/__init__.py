"""Host runtime: the control plane around the TPU replay data path.

Layers (reference: SURVEY.md §1-2 of this repo):

  persistence/  five-manager storage contract (shard / execution /
                history-tree / task / metadata / visibility) with
                in-memory and SQLite backends
  shard/        shard context + controller (range-id fencing, task-id
                sequencing, ack levels)
  engine/       history engine: workflow mutations, decision pipeline,
                workflow execution context, caches
  queues/       transfer + timer queue processors
  matching/     task-list dispatch (sync match + backlog)
  frontend/     public API surface
  membership/   host ring (static resolver for onebox; pluggable)
  replication/  cross-cluster NDC replication runtime
"""
