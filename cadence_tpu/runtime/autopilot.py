"""Capacity autopilot: closed-loop control from admission rates to
shard topology.

Every prior PR left a manual knob at the end of its control story: the
overload plane's ``history.rps``/``matching.rps``/domain quotas are
operator-set constants, the serving engine's admission quota is frozen
at boot, and hot/cold shards wait for an operator to call the reshard
admin verbs. This module closes the loop. A ``CapacityController``
runs a sense → decide → actuate epoch:

* **sense** — one ``metrics.Window`` over the host registry yields the
  interval's REAL percentiles and rates (not cumulative-since-boot):
  admitted p99, shed fraction, serving staleness, observed admitted
  rps, per-domain rps, and per-shard queue depths;
* **decide** — per-signal EWMAs feed a hysteresis gate with the
  replication plane's challenger-must-win discipline (an overload /
  recovery verdict must win ``min_dwell`` CONSECUTIVE epochs to flip —
  a band-edge oscillation can never flap the gate). Each actuator has a
  cooldown (epochs) and a bounded step (``max_step_frac`` per epoch);
  a do-no-harm guardrail watches p99 after the controller's own recent
  actions and, on a self-inflicted regression, FREEZES actuation and
  reverts every rate to the last-known-good snapshot;
* **actuate** — two planes. Rates: programmatic dynamicconfig
  overrides (``dynamicconfig.LayeredClient``) + live hooks into the
  already-built limiters/engine, so ``history.rps``, ``matching.rps``,
  ``history.domainRps`` and the serving admission quota retune without
  a restart. Topology: split/merge/rebalance plans proposed to the
  (shared, one-per-host) ``ReshardCoordinator`` — several
  reconfigurations may be batched into one epoch, but plans execute
  strictly one at a time (the coordinator's own lock enforces it), and
  a failed plan backs the proposer off on a ``BackoffLadder`` — never a
  hot retry against a store that just aborted a handoff.

Deployment: in-process for the Onebox, and on real deployments every
history host runs the same controller but only the membership-elected
actuator (the host that ``resolver("history")`` hashes the
``capacity-autopilot`` key to) actuates; the rest sense and stand by —
a host loss moves the key, and the next epoch elects the survivor.
Operators keep the last word: ``autopilot_pause`` / ``autopilot_resume``
/ ``autopilot_status`` admin verbs, and every decision is traced (PR 9
spans) and counted in ``AUTOPILOT_METRICS``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from cadence_tpu.utils import locks
from cadence_tpu.utils.backoff import BackoffLadder
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP, Scope, Window
from cadence_tpu.utils.tracing import TRACER

# the dynamicconfig keys the rate plane actuates (the same keys
# operators set by hand in the dynamicconfig file; the override layer
# shadows the file, remove_value un-shadows it)
KEY_HISTORY_RPS = "history.rps"
KEY_HISTORY_DOMAIN_RPS = "history.domainRps"
KEY_MATCHING_RPS = "matching.rps"
KEY_SERVING_QUOTA_RPS = "serving.quotaRps"

RATE_KEYS = (
    KEY_HISTORY_RPS,
    KEY_HISTORY_DOMAIN_RPS,
    KEY_MATCHING_RPS,
    KEY_SERVING_QUOTA_RPS,
)

ELECTION_KEY = "capacity-autopilot"


class Ewma:
    """Exponentially-weighted moving average; seeded by the first
    observation (no zero-bias warmup — the first epoch's reading IS the
    state, which matters for a controller that must not actuate off an
    artificial ramp from zero)."""

    def __init__(self, alpha: float) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError("ewma: alpha must be in (0, 1]")
        self.alpha = float(alpha)
        self.value: Optional[float] = None

    def observe(self, sample: float) -> float:
        if self.value is None:
            self.value = float(sample)
        else:
            self.value += self.alpha * (float(sample) - self.value)
        return self.value

    def get(self, default: float = 0.0) -> float:
        return self.value if self.value is not None else default


class HysteresisGate:
    """Two-state overload gate with the challenger-must-win discipline
    (``ReplicationModeController``): flipping requires the challenger
    state to win ``min_dwell`` CONSECUTIVE observations; any
    non-winning observation resets the streak. Engage above ``hi``;
    disengage below ``hi / hysteresis``. A signal oscillating at the
    band edge alternates win/non-win and can never accumulate a streak
    — the no-flap property test pins this."""

    def __init__(
        self, hi: float, hysteresis: float, min_dwell: int
    ) -> None:
        if hi <= 0:
            raise ValueError("hysteresis gate: hi must be > 0")
        if hysteresis < 1.0:
            raise ValueError("hysteresis gate: hysteresis must be >= 1")
        if min_dwell < 1:
            raise ValueError("hysteresis gate: min_dwell must be >= 1")
        self.hi = float(hi)
        self.lo = float(hi) / float(hysteresis)
        self.min_dwell = int(min_dwell)
        self.engaged = False
        self.switches = 0
        self._streak = 0

    def observe(self, value: float) -> bool:
        """Feed one epoch's pressure reading; returns the (possibly
        flipped) engaged state."""
        if self.engaged:
            challenger_wins = value < self.lo
        else:
            challenger_wins = value > self.hi
        if challenger_wins:
            self._streak += 1
            if self._streak >= self.min_dwell:
                self.engaged = not self.engaged
                self.switches += 1
                self._streak = 0
        else:
            self._streak = 0
        return self.engaged


def derive_rate(
    current: float,
    observed_rps: float,
    overloaded: bool,
    *,
    max_step_frac: float,
    headroom_frac: float,
    min_rps: float,
    max_rps: float,
) -> float:
    """One epoch's rate derivation — pure, so the property tests can
    pin it directly.

    Overloaded: step DOWN by the full bounded step (shedding load is
    the point; half-measures prolong the brownout). Healthy: track the
    observed admitted rate plus headroom, clamped to one bounded step
    from ``current`` in either direction, so the limit follows traffic
    down in quiet phases and opens up under growth — monotone in
    ``observed_rps`` and never moving more than ``max_step_frac`` per
    epoch (modulo the absolute min/max clamps)."""
    if overloaded:
        desired = current * (1.0 - max_step_frac)
    else:
        target = observed_rps * (1.0 + headroom_frac)
        desired = min(
            max(target, current * (1.0 - max_step_frac)),
            current * (1.0 + max_step_frac),
        )
    return min(max(desired, min_rps), max_rps)


@dataclasses.dataclass
class EpochReading:
    """What one sense pass saw (the decide stage's only input, and the
    ``status()`` payload's ``last_reading``)."""

    span_s: float = 0.0
    admitted: int = 0
    shed: int = 0
    shed_frac: float = 0.0
    p99_ms: float = 0.0
    staleness_p99_ms: float = 0.0
    observed_rps: float = 0.0
    domain_rps: Dict[str, float] = dataclasses.field(default_factory=dict)
    shard_depths: Dict[int, int] = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["shard_depths"] = {str(k): v for k, v in self.shard_depths.items()}
        return d


@dataclasses.dataclass
class _Action:
    """One past actuation, kept for the guardrail's lookback."""

    epoch: int
    kind: str          # "rate" | "reshard"
    key: str
    pre_p99_ms: float  # the p99 EWMA the controller saw BEFORE acting


class CapacityController:
    """The sense → decide → actuate epoch loop (one per history host).

    Construction wires the actuation surface explicitly so the Onebox,
    the bootstrap, and the tests all feed the same controller:

    * ``registry`` — the host ``metrics.Registry`` to sense from;
    * ``overrides`` — the ``dynamicconfig.InMemoryClient`` override
      layer rates are written through (so late-bound readers of the
      dynamicconfig keys see the controller's values);
    * ``rate_hooks`` — key → callable(rps) applied on top of the
      override write for limiters sized at boot
      (``MultiStageRateLimiter.set_global_rate``,
      ``ResidentEngine.retune_admission``);
    * ``resharder`` — the shared per-host ``ReshardCoordinator`` (or a
      zero-arg factory returning it, resolved lazily so construction
      never races shard acquisition); None disables the topology plane;
    * ``shard_load_fn`` — zero-arg callable returning {shard_id:
      queue depth}; defaults to summing outstanding+held over the
      ``history`` service's owned shards; injectable for tests;
    * ``monitor`` — membership for single-actuator election; None means
      standalone (always the actuator).
    """

    def __init__(
        self,
        config=None,
        *,
        registry=None,
        overrides=None,
        rate_hooks: Optional[Dict[str, Callable[[float], None]]] = None,
        initial_rates: Optional[Dict[str, float]] = None,
        resharder=None,
        history=None,
        monitor=None,
        shard_load_fn: Optional[Callable[[], Dict[int, int]]] = None,
        metrics: Optional[Scope] = None,
        clock: Callable[[], float] = time.monotonic,
    ) -> None:
        from cadence_tpu.config.static import AutopilotConfig

        self.config = config if config is not None else AutopilotConfig()
        self.config.validate()
        cfg = self.config
        self._registry = registry
        self._window = Window(registry) if registry is not None else None
        self.overrides = overrides
        self.rate_hooks = dict(rate_hooks or {})
        self._resharder = resharder
        self.history = history
        self.monitor = monitor
        self._shard_load_fn = shard_load_fn
        self._metrics = (
            metrics if metrics is not None else NOOP
        ).tagged(layer="autopilot")
        self._clock = clock
        self._log = get_logger("cadence_tpu.autopilot")

        self._lock = locks.make_lock("CapacityController._lock")
        # the rate plane's current setpoints (key -> rps). Seeded from
        # initial_rates (bootstrap passes the boot-time dynamicconfig
        # values) so epoch 0 steps from the operator's config, not from
        # a built-in constant
        self._rates: Dict[str, float] = locks.make_guarded(
            {}, "CapacityController._rates", self._lock
        )
        for key, rps in (initial_rates or {}).items():
            self._rates[key] = float(rps)
        # actuator key -> first epoch it may act again
        self._cooldowns: Dict[str, int] = locks.make_guarded(
            {}, "CapacityController._cooldowns", self._lock
        )

        self._epoch = 0
        self._p99 = Ewma(cfg.ewma_alpha)
        self._shed = Ewma(cfg.ewma_alpha)
        # demand = OFFERED rate (admitted + shed per second), smoothed.
        # Tracking admitted alone could never discover latent demand
        # above the current limit — a too-low limit sheds the excess,
        # admitted equals the limit, and the loop locks itself down.
        # Shed traffic IS demand; count it
        self._demand = Ewma(cfg.ewma_alpha)
        # sticky: set the first time an interval carries any offered
        # traffic. Merges are gated on it — "cold" is only evidence
        # relative to load the controller has actually seen, so an
        # idle-at-boot cluster keeps its operator-provisioned topology
        # instead of collapsing to min_shards on zero information
        self._saw_traffic = False
        self._gate = HysteresisGate(1.0, cfg.hysteresis, cfg.min_dwell)
        self._last_reading: Optional[EpochReading] = None
        # guardrail state: recent actions (bounded lookback) + the
        # last-known-good rate snapshot taken at the end of every
        # healthy, freeze-free epoch
        self._recent_actions: "deque[_Action]" = deque(
            maxlen=cfg.guardrail_window * 8
        )
        self._last_known_good: Dict[str, float] = dict(self._rates)
        self._frozen_until_epoch = -1
        self.guardrail_freezes = 0
        # reshard plane: its own ladder — a failed plan must never be
        # hot-retried; block proposals until the ladder's horizon
        self._reshard_ladder = BackoffLadder(
            max(cfg.epoch_interval_s, 0.001), cfg.backoff_max_s
            if cfg.backoff_max_s >= cfg.epoch_interval_s
            else cfg.epoch_interval_s,
        )
        self._reshard_block_until = 0.0
        self.reshard_failures = 0

        self._paused = False
        self._pause_reason = ""
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.epochs_run = 0
        self.errors = 0

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "CapacityController":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="capacity-autopilot", daemon=True
        )
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout_s)
            self._thread = None

    def _run(self) -> None:
        # the third BackoffLadder adoption site: an epoch that BLOWS UP
        # (sense path raising through a sick store, a dead resolver)
        # must not spin the loop at full cadence against the failure
        ladder = BackoffLadder(
            self.config.epoch_interval_s,
            max(self.config.backoff_max_s, self.config.epoch_interval_s),
            jitter=0.25,
        )
        delay = self.config.epoch_interval_s
        while not self._stop.wait(delay):
            try:
                self.run_epoch_once()
                ladder.success()
                delay = self.config.epoch_interval_s
            except Exception as e:  # noqa: BLE001 — loop must survive
                self.errors += 1
                self._metrics.inc("autopilot_errors")
                self._log.warn(f"autopilot epoch failed ({e}); backoff")
                delay = ladder.failure()

    # -- operator verbs ------------------------------------------------

    def pause(self, reason: str = "") -> None:
        with self._lock:
            self._paused = True
            self._pause_reason = reason or "operator pause"
        self._metrics.inc("autopilot_pauses")
        self._log.info(f"autopilot paused: {self._pause_reason}")

    def resume(self) -> None:
        with self._lock:
            self._paused = False
            self._pause_reason = ""
        self._metrics.inc("autopilot_resumes")
        self._log.info("autopilot resumed")

    def status(self) -> dict:
        with self._lock:
            rates = dict(self._rates)
            cooldowns = dict(self._cooldowns)
            paused, reason = self._paused, self._pause_reason
        reading = self._last_reading
        return {
            "enabled": True,
            "paused": paused,
            "pause_reason": reason,
            "leader": self._is_leader(),
            "epoch": self._epoch,
            "epochs_run": self.epochs_run,
            "errors": self.errors,
            "overloaded": self._gate.engaged,
            "saw_traffic": self._saw_traffic,
            "gate_switches": self._gate.switches,
            "p99_ewma_ms": self._p99.get(),
            "shed_ewma_frac": self._shed.get(),
            "frozen": self._is_frozen(),
            "guardrail_freezes": self.guardrail_freezes,
            "reshard_failures": self.reshard_failures,
            "rates": rates,
            "cooldowns": cooldowns,
            "last_known_good": dict(self._last_known_good),
            "last_reading": reading.to_dict() if reading else None,
        }

    # -- election ------------------------------------------------------

    def _is_leader(self) -> bool:
        """Single-actuator election: the membership ring hashes the
        well-known key to exactly one history host; everyone computes
        it, exactly one matches. No monitor (standalone / Onebox) or an
        empty ring (boot) -> act."""
        if self.monitor is None:
            return True
        try:
            resolver = self.monitor.resolver("history")
            if resolver.member_count() == 0:
                return True
            owner = resolver.lookup(ELECTION_KEY)
            return owner.identity == self.monitor.whoami().identity
        except Exception:  # noqa: BLE001 — a sick ring must not actuate
            return False

    # -- the epoch -----------------------------------------------------

    def run_epoch_once(self) -> dict:
        """One full sense → decide → actuate pass (the loop body; also
        the test/bench entry point — no thread required). Returns a
        summary dict of what happened."""
        t0 = time.perf_counter()
        self._epoch += 1
        span = TRACER.trace(
            "autopilot.epoch", service="autopilot",
            epoch=str(self._epoch),
        )
        summary = {
            "epoch": self._epoch, "acted": False, "retunes": 0,
            "plans": 0, "froze": False, "skipped": None,
        }
        with span:
            reading = self._sense()
            self._last_reading = reading
            overloaded = self._decide(reading)
            span.annotate(
                f"p99_ewma={self._p99.get():.1f}ms "
                f"shed_ewma={self._shed.get():.3f} "
                f"overloaded={overloaded}"
            )
            with self._lock:
                paused = self._paused
            if paused:
                summary["skipped"] = "paused"
                self._metrics.inc("autopilot_skipped_epochs")
                span.annotate("skipped: paused")
            elif not self._is_leader():
                # non-leaders sense (their EWMAs stay warm for a
                # failover) but never actuate
                summary["skipped"] = "not-leader"
                self._metrics.inc("autopilot_skipped_epochs")
                span.annotate("skipped: not leader")
            elif self._guardrail_trips():
                self._freeze_and_revert(span)
                summary["froze"] = True
            elif self._is_frozen():
                summary["skipped"] = "frozen"
                self._metrics.inc("autopilot_skipped_epochs")
                span.annotate("skipped: frozen")
            else:
                summary["retunes"] = self._actuate_rates(
                    reading, overloaded, span
                )
                summary["plans"] = self._actuate_topology(reading, span)
                summary["acted"] = (
                    summary["retunes"] + summary["plans"] > 0
                )
                # a healthy epoch refreshes the revert target — but
                # only once its OWN actions' dust has settled (nothing
                # still inside the guardrail's lookback pending
                # judgment), so a freeze can never revert INTO the
                # rates that caused the regression
                cutoff = self._epoch - self.config.guardrail_window
                settled = not any(
                    a.epoch >= cutoff for a in self._recent_actions
                )
                if not self._gate.engaged and settled:
                    with self._lock:
                        self._last_known_good = dict(self._rates)
        self.epochs_run += 1
        self._metrics.inc("autopilot_epochs")
        self._metrics.record(
            "autopilot_epoch_seconds", time.perf_counter() - t0
        )
        self._metrics.gauge(
            "autopilot_overload_engaged", 1.0 if self._gate.engaged else 0.0
        )
        self._metrics.gauge(
            "autopilot_frozen", 1.0 if self._is_frozen() else 0.0
        )
        with self._lock:
            now_paused = self._paused
        self._metrics.gauge(
            "autopilot_paused", 1.0 if now_paused else 0.0
        )
        return summary

    # -- sense ---------------------------------------------------------

    def _sense(self) -> EpochReading:
        if self._window is None:
            return EpochReading(shard_depths=self._shard_depths())
        r = self._window.advance()
        span_s = max(r.span_s, 1e-9)

        decision = r.timer_stats("serve_decision")
        admitted = decision.count
        p99_ms = decision.p99 * 1000.0
        if admitted == 0:
            # no serving traffic this interval — fall back to the
            # history op latency plane so the controller still senses
            # an ingest-only workload. Exclude non-workload ops:
            # worker long-polls are SUPPLY asking for work (an idle
            # cluster with workers attached long-polls continuously)
            # and domain CRUD is the operator's control plane —
            # counting either would feed phantom rps into the demand
            # EWMA and open the cold-merge gate on a cluster that has
            # never executed a workflow
            def _workload(t):
                op = dict(t).get("operation", "")
                return not (op.startswith("poll_for_") or "domain" in op)

            lat = r.timer_stats("latency", where=_workload)
            admitted = lat.count
            p99_ms = lat.p99 * 1000.0
        shed = r.counter("serve_shed") + r.counter("frontend_requests_shed")
        shed_frac = shed / max(1, shed + admitted)
        staleness = r.timer_stats("serving_staleness_ms")

        domain_rps: Dict[str, float] = {}
        for tags in r.timer_tags("serve_decision"):
            dom = dict(tags).get("domain")
            if dom:
                st = r.timer_stats("serve_decision", dict(tags))
                domain_rps[dom] = domain_rps.get(dom, 0.0) + (
                    st.count / span_s
                )

        reading = EpochReading(
            span_s=span_s,
            admitted=admitted,
            shed=shed,
            shed_frac=shed_frac,
            p99_ms=p99_ms,
            # serving_staleness_ms is recorded in ms already
            staleness_p99_ms=staleness.p99,
            observed_rps=admitted / span_s,
            domain_rps=domain_rps,
            shard_depths=self._shard_depths(),
        )
        self._metrics.gauge("autopilot_sensed_p99_ms", reading.p99_ms)
        self._metrics.gauge(
            "autopilot_sensed_shed_frac", reading.shed_frac
        )
        return reading

    def _shard_depths(self) -> Dict[int, int]:
        if self._shard_load_fn is not None:
            try:
                return dict(self._shard_load_fn())
            except Exception:  # noqa: BLE001
                return {}
        if self.history is None:
            return {}
        depths: Dict[int, int] = {}
        try:
            controller = self.history.controller
            with controller._lock:
                shard_ids = list(controller._handles.keys())
            for sid in shard_ids:
                try:
                    desc = self.history.describe_queue_states(sid)
                except KeyError:
                    continue  # lost between listing and describing
                depths[sid] = sum(
                    q["outstanding"] + q["held"] for q in desc["queues"]
                )
        except Exception:  # noqa: BLE001 — sensing must never throw
            return depths
        return depths

    # -- decide --------------------------------------------------------

    def _decide(self, reading: EpochReading) -> bool:
        cfg = self.config
        # epochs with zero admitted traffic carry no latency signal;
        # hold the p99 EWMA rather than decaying it toward 0 (which
        # would disengage the gate during a total brownout)
        if reading.admitted > 0:
            self._p99.observe(reading.p99_ms)
        if reading.admitted + reading.shed > 0:
            self._saw_traffic = True
        self._shed.observe(reading.shed_frac)
        self._demand.observe(
            (reading.admitted + reading.shed)
            / max(reading.span_s, 1e-9)
        )
        self._metrics.gauge("autopilot_demand_rps", self._demand.get())
        # shed with HEALTHY latency is the limiter being the
        # bottleneck, not the backend — the cure is opening the limit
        # up, so it must not engage the gate (feeding raw shed into
        # the pressure would be a death spiral: lower limit -> more
        # shed -> more pressure -> lower limit, all the way to
        # min_rps). Shed escalates pressure only once latency is at
        # or past target: then the backend really is saturated
        p99_pressure = self._p99.get() / cfg.target_p99_ms
        pressure = p99_pressure
        if p99_pressure >= 1.0:
            pressure = max(
                pressure, self._shed.get() / cfg.target_shed_frac
            )
        self._metrics.gauge("autopilot_pressure", pressure)
        return self._gate.observe(pressure)

    # -- guardrail -----------------------------------------------------

    def _is_frozen(self) -> bool:
        return self._epoch <= self._frozen_until_epoch

    def _guardrail_trips(self) -> bool:
        """Do-no-harm: did p99 regress past ``guardrail_regression`` ×
        the level it held BEFORE our recent actions, while also above
        target? Correlation, not causation — the controller prefers a
        false freeze (operators' config keeps working) over a feedback
        loop chasing its own tail."""
        if self._is_frozen():
            return False
        cfg = self.config
        cutoff = self._epoch - cfg.guardrail_window
        recent = [a for a in self._recent_actions if a.epoch >= cutoff]
        if not recent:
            return False
        baseline = min(a.pre_p99_ms for a in recent)
        now = self._p99.get()
        return (
            now > cfg.target_p99_ms
            and now > max(baseline, 1e-9) * cfg.guardrail_regression
        )

    def _freeze_and_revert(self, span) -> None:
        cfg = self.config
        self._frozen_until_epoch = self._epoch + cfg.freeze_epochs
        self.guardrail_freezes += 1
        self._metrics.inc("autopilot_guardrail_freezes")
        with self._lock:
            good = dict(self._last_known_good)
        reverted = 0
        for key, rps in good.items():
            if self._apply_rate(key, rps):
                reverted += 1
        self._metrics.inc("autopilot_reverts", max(reverted, 1))
        self._recent_actions.clear()
        span.annotate(
            f"GUARDRAIL FREEZE: reverted {reverted} rates to "
            f"last-known-good; frozen until epoch "
            f"{self._frozen_until_epoch}"
        )
        self._log.warn(
            "autopilot guardrail tripped: p99 regressed after our own "
            f"actions; reverted {reverted} rates, frozen "
            f"{cfg.freeze_epochs} epochs"
        )

    # -- actuate: rates ------------------------------------------------

    def _apply_rate(self, key: str, rps: float) -> bool:
        """Write one setpoint through the override layer + live hook.
        Returns True when the setpoint materially changed."""
        with self._lock:
            cur = self._rates.get(key)
            if cur is not None and abs(rps - cur) <= 0.01 * max(cur, 1e-9):
                return False
            self._rates[key] = rps
        if self.overrides is not None:
            self.overrides.set_value(key, rps)
        hook = self.rate_hooks.get(key)
        if hook is not None:
            hook(rps)
        self._metrics.tagged(key=key).gauge("autopilot_rate_rps", rps)
        return True

    def _actuate_rates(
        self, reading: EpochReading, overloaded: bool, span
    ) -> int:
        cfg = self.config
        retunes = 0
        pre_p99 = self._p99.get()
        n_domains = max(len(reading.domain_rps), 1)
        for key in RATE_KEYS:
            with self._lock:
                if self._cooldowns.get(key, 0) > self._epoch:
                    cooling = True
                else:
                    cooling = False
                current = self._rates.get(key)
            if current is None:
                continue  # no setpoint wired for this key on this host
            if cooling:
                self._metrics.inc("autopilot_cooldown_skips")
                continue
            if key == KEY_HISTORY_DOMAIN_RPS:
                # per-domain cap follows the HOTTEST domain + headroom
                observed = max(
                    reading.domain_rps.values(),
                    default=self._demand.get() / n_domains,
                )
            else:
                # smoothed OFFERED rate: shed traffic is demand too
                observed = self._demand.get()
            new = derive_rate(
                current, observed, overloaded,
                max_step_frac=cfg.max_step_frac,
                headroom_frac=cfg.headroom_frac,
                min_rps=cfg.min_rps,
                max_rps=cfg.max_rps,
            )
            if self._apply_rate(key, new):
                retunes += 1
                with self._lock:
                    self._cooldowns[key] = (
                        self._epoch + 1 + cfg.cooldown_epochs
                    )
                self._recent_actions.append(_Action(
                    epoch=self._epoch, kind="rate", key=key,
                    pre_p99_ms=pre_p99,
                ))
                self._metrics.inc("autopilot_rate_retunes")
                span.annotate(
                    f"retune {key}: {current:.1f} -> {new:.1f} rps"
                )
        return retunes

    # -- actuate: topology ---------------------------------------------

    def _resolve_resharder(self):
        r = self._resharder
        return r() if callable(r) else r

    def _actuate_topology(self, reading: EpochReading, span) -> int:
        cfg = self.config
        resharder = self._resolve_resharder()
        if resharder is None or not reading.shard_depths:
            return 0
        if self._clock() < self._reshard_block_until:
            self._metrics.inc("autopilot_cooldown_skips")
            span.annotate("reshard plane: backing off after failure")
            return 0
        with self._lock:
            if self._cooldowns.get("reshard", 0) > self._epoch:
                self._metrics.inc("autopilot_cooldown_skips")
                return 0

        depths = reading.shard_depths
        mean = sum(depths.values()) / len(depths)
        n_shards = len(depths)
        plans: List[tuple] = []

        # hot shards: depth over the absolute floor AND a clear outlier
        hot = sorted(
            (
                sid for sid, d in depths.items()
                if d >= cfg.hot_shard_depth
                and d > cfg.hot_shard_factor * max(mean, 1.0)
            ),
            key=lambda s: -depths[s],
        )
        for sid in hot:
            if n_shards + len([p for p in plans if p[0] == "split"]) \
                    >= cfg.max_shards:
                break
            plans.append(("split", sid))

        # cold pairs: only when the gate is disengaged (never shrink
        # capacity during an overload), both shards are near-idle, AND
        # the controller has seen real traffic at least once — "cold"
        # relative to a load that never existed is not evidence, and an
        # idle-at-boot cluster must keep its provisioned topology
        if not self._gate.engaged and not plans and self._saw_traffic:
            cold = sorted(
                (
                    sid for sid, d in depths.items()
                    if d <= cfg.cold_shard_frac * max(mean, 1.0)
                ),
                key=lambda s: depths[s],
            )
            while (
                len(cold) >= 2
                and n_shards - len(plans) > cfg.min_shards
            ):
                src, tgt = cold.pop(0), cold.pop(0)
                plans.append(("merge", src, tgt))
                cold.insert(0, tgt)  # the survivor can absorb again

        executed = 0
        for plan in plans[: cfg.max_plans_per_epoch]:
            try:
                # one-plan-at-a-time: the coordinator's lock serializes;
                # we just submit sequentially and stop on first failure
                if plan[0] == "split":
                    resharder.split(plan[1])
                    span.annotate(f"split shard {plan[1]}")
                else:
                    resharder.merge(plan[1], plan[2])
                    span.annotate(
                        f"merge shard {plan[1]} -> {plan[2]}"
                    )
                executed += 1
                self._metrics.inc("autopilot_reshard_plans")
                self._recent_actions.append(_Action(
                    epoch=self._epoch, kind="reshard",
                    key=f"{plan[0]}:{plan[1]}",
                    pre_p99_ms=self._p99.get(),
                ))
                self._reshard_ladder.success()
            except Exception as e:  # noqa: BLE001 — incl. ReshardError
                # the coordinator already rolled the plan back; OUR job
                # is to not hot-retry a store that just aborted a
                # handoff — back off on the ladder and stop this epoch
                self.reshard_failures += 1
                self._metrics.inc("autopilot_reshard_failures")
                self._reshard_block_until = (
                    self._clock() + self._reshard_ladder.failure()
                )
                span.annotate(
                    f"reshard {plan[0]} failed ({e}); backing off"
                )
                self._log.warn(
                    f"autopilot reshard {plan} failed ({e}); "
                    "backing off, no hot retry"
                )
                break
        if executed:
            with self._lock:
                self._cooldowns["reshard"] = (
                    self._epoch + 1 + cfg.reshard_cooldown_epochs
                )
        return executed
