"""Standby queue processors: verify-and-discharge for passive domains.

Reference: service/history/transferQueueStandbyProcessor.go and
timerQueueStandbyProcessor.go — each remote cluster gets a standby
variant of the transfer/timer pipelines with its own persisted ack
cursor. A standby processor never executes a task's active side effect
(no matching pushes, no timeout events); it *verifies* the task against
the replicated state:

  * the state shows replication already delivered the outcome (decision
    started, activity closed, timer fired, workflow closed) → the task
    is discharged and the standby cursor advances;
  * the outcome hasn't replicated yet → the task is held and re-read
    after a standby delay (``DeferTask``), converging when replication
    catches up (the rereplication path heals gaps);
  * side effects that DO belong on the standby side run here: visibility
    records (started/closed/upsert) and retention-driven deletion.

Timer standby fires against the REMOTE cluster's clock
(``RemoteTimerGate`` advanced by the replication stream's
``source_time_ns``), mirroring timerGate.go:164 — a standby cluster
whose local clock runs ahead must not judge a remote timer "due" before
the owning cluster would.

Failover: the processors are verification-based and idempotent, so the
active side takes over lost ground by rewinding its cursor to the
standby cursor (``QueueAckManager.rewind``) when a domain fails over to
this cluster — re-reading the span the active processor had skipped as
passive (ref transferQueueProcessor.go failover processor).
"""

from __future__ import annotations

import threading
from typing import Optional

from cadence_tpu.core.enums import TimerTaskType, TransferTaskType, WorkflowState
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.tasks import TimerTask, TransferTask
from cadence_tpu.core.timer_sequence import TimerSequence
from cadence_tpu.runtime.api import EntityNotExistsServiceError
from cadence_tpu.runtime.persistence.records import VisibilityRecord
from cadence_tpu.utils.log import get_logger

from .ack import QueueAckManager
from .allocator import DeferTask, defer_task
from .base import (
    QueueProcessorBase,
    ResumeCursor,
    make_fault_hook,
    read_due_timers,
    run_task_attempts,
    sweep_ack,
    task_span,
    timed_task,
)
from .timer_gate import RemoteTimerGate


class QueueGC:
    """Range-deletes task rows below the MINIMUM ack level across the
    active processor and every standby cursor (ref
    transferQueueProcessor.go completeTransferLoop /
    timerQueueProcessor.go completeTimersLoop). Owns deletion whenever
    standby planes share the task stream — per-task deletes would starve
    the slower cursor."""

    def __init__(
        self,
        shard,
        transfer_active,
        timer_active,
        standby_clusters,
        interval_s: float = 0.1,
    ) -> None:
        self.shard = shard
        self.transfer_active = transfer_active
        self.timer_active = timer_active
        self.standby_clusters = list(standby_clusters)
        self._interval = interval_s
        self._stopped = threading.Event()
        # last collected levels: skip the range-delete round-trips when
        # no cursor moved since the previous tick
        self._last_transfer_min = 0
        self._last_timer_min = 0
        self._gclog = get_logger(
            "cadence_tpu.queue.gc", shard=shard.shard_id
        )
        self._thread = threading.Thread(
            target=self._loop, name=f"queue-gc-{shard.shard_id}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def notify(self) -> None:
        pass

    def stop(self) -> None:
        self._stopped.set()

    def drain(self, timeout_s: float = 5.0) -> bool:
        self.collect()
        return True

    def collect(self) -> None:
        transfer_min = min(
            [self.transfer_active.ack.ack_level]
            + [
                self.shard.get_cluster_transfer_ack_level(c)
                for c in self.standby_clusters
            ]
        )
        if transfer_min > self._last_transfer_min:
            self.shard.persistence.execution.range_complete_transfer_tasks(
                self.shard.shard_id, 0, transfer_min
            )
            self._last_transfer_min = transfer_min
        timer_min = min(
            [self.timer_active.ack.ack_level[0]]
            + [
                self.shard.get_cluster_timer_ack_level(c)
                for c in self.standby_clusters
            ]
        )
        if timer_min > self._last_timer_min:
            self.shard.persistence.execution.range_complete_timer_tasks(
                self.shard.shard_id, 0, timer_min
            )
            self._last_timer_min = timer_min

    def _loop(self) -> None:
        while not self._stopped.wait(self._interval):
            try:
                self.collect()
            except Exception:
                # with standby planes, GC is the ONLY row deletion; a
                # persistent failure means unbounded task-table growth
                self._gclog.exception("queue GC collect failed")


def _fv_increment(engine) -> int:
    """The topology's failover-version increment (0 when the engine
    carries no cluster metadata — the allocator then arms handovers
    from stood-by membership only)."""
    cm = getattr(engine, "cluster_metadata", None)
    return cm.failover_version_increment if cm is not None else 0


class _StandbyAllocator:
    """Owns a task iff its domain is ACTIVE in ``cluster`` (i.e. this
    cluster stands by for it)."""

    def __init__(self, domains, cluster: str,
                 local_cluster: str = "",
                 failover_version_increment: int = 0) -> None:
        self.domains = domains
        self.cluster = cluster
        self.local_cluster = local_cluster
        # cycle detection: fv >= increment means the domain has failed
        # over at least once (registration versions live in cycle 0) —
        # the arm condition for a plane that observes a flip-to-local
        # WITHOUT ever having stood by (its first read of the span can
        # race the flip; see classify)
        self._increment = failover_version_increment
        # domains this allocator has stood by for — a later flip to
        # locally-active means a failover whose held span must hand
        # over to the active processor
        self._stood_by: set = set()
        # newest failover version observed per domain: a worker that
        # read the record BEFORE a failover must not re-arm the claim
        # AFTER another worker consumed the handover (the stale re-add
        # would rewind the active cursor a second time)
        self._seen_version: dict = {}
        # failover version whose handover this plane already claimed:
        # the claim is once PER OBSERVED FAILOVER (keyed by version),
        # not per stood-by membership — a plane whose first read of a
        # task span lands AFTER the flip never stood by for it, yet the
        # active processor may have skipped that span as standby-owned
        # while the domain record still named the old owner. Without a
        # version-keyed claim that span is silently discharged by both
        # planes and its tasks are lost (the failover drill caught the
        # race: the handed-over decision task vanished and the
        # workflow never completed on the new active side).
        self._claimed_fv: dict = {}
        self._claim_lock = threading.Lock()

    def classify(self, domain_id: str) -> str:
        """'owned' (verify here) | 'handover' (domain just became
        locally active via a failover this plane has not handed over
        yet — give the span to the active plane, ONCE per failover
        observation) | 'other' (not ours)."""
        try:
            rec = self.domains.get_by_id(domain_id)
        except Exception:
            return "other"
        if not rec.is_global:
            return "other"
        active = rec.replication_config.active_cluster_name
        with self._claim_lock:
            fv = rec.failover_version
            if fv < self._seen_version.get(domain_id, -1):
                return "other"  # stale record from before a failover
            self._seen_version[domain_id] = fv
            if active == self.cluster:
                self._stood_by.add(domain_id)
                return "owned"
            if active == self.local_cluster:
                ever_failed_over = (
                    self._increment > 0 and fv >= self._increment
                )
                if (domain_id in self._stood_by or ever_failed_over) \
                        and self._claimed_fv.get(domain_id) != fv:
                    return "handover"
            return "other"

    def claim_handover(self, domain_id: str) -> bool:
        """Compare-and-consume: exactly ONE concurrent caller wins the
        handover for a domain's observed failover version (two pool
        workers can both classify 'handover' for the same failover).
        Without consumption, every future task of the now-local domain
        would rewind the active cursor forever."""
        with self._claim_lock:
            fv = self._seen_version.get(domain_id)
            armed = domain_id in self._stood_by or (
                self._increment > 0
                and fv is not None
                and fv >= self._increment
            )
            if not armed or fv is None \
                    or self._claimed_fv.get(domain_id) == fv:
                return False
            self._claimed_fv[domain_id] = fv
            self._stood_by.discard(domain_id)
            return True

    def rearm_handover(self, domain_id: str) -> None:
        """Give the claim back (the handover callback failed)."""
        with self._claim_lock:
            self._claimed_fv.pop(domain_id, None)
            self._stood_by.add(domain_id)


class TransferQueueStandbyProcessor(QueueProcessorBase):
    """Transfer standby variant for one remote cluster."""

    def __init__(
        self,
        shard,
        engine,
        cluster: str,
        visibility=None,
        worker_count: int = 2,
        batch_size: int = 64,
        local_cluster: str = "",
        on_handover=None,
        metrics=None,
        faults=None,
        exhausted_retry_delay_s=None,
        executor=None,
    ) -> None:
        self.shard = shard
        self.engine = engine
        self.cluster = cluster
        self.visibility = (
            visibility if visibility is not None
            else shard.persistence.visibility
        )
        self._slog = get_logger(
            "cadence_tpu.queue.transfer-standby",
            shard=shard.shard_id, cluster=cluster,
        )
        # called with an ack LEVEL when a domain this plane stood by
        # for fails over HERE: rewinds the active cursor over the held
        # span (closes the race where a standby worker observes the
        # flipped domain before the failover listener rewinds, and the
        # rewind target has already moved past the held span)
        self._on_handover = on_handover
        self._allocator = _StandbyAllocator(
            engine.domains, cluster, local_cluster=local_cluster,
            failover_version_increment=_fv_increment(engine),
        )
        shard.ensure_cluster_ack_levels(cluster)
        ack = QueueAckManager(
            shard.get_cluster_transfer_ack_level(cluster),
            update_shard_ack=lambda lvl: shard.update_cluster_transfer_ack_level(
                cluster, lvl
            ),
        )
        super().__init__(
            name=f"transfer-standby-{cluster}-{shard.shard_id}",
            ack=ack,
            read_batch=lambda level, n: shard.persistence.execution.get_transfer_tasks(
                shard.shard_id, level, 2**62, n
            ),
            process_task=self._process,
            # the ACTIVE processor owns task-row deletion; standby only
            # advances its own cursor
            complete_task=lambda t: None,
            task_key=lambda t: t.task_id,
            worker_count=worker_count,
            batch_size=batch_size,
            metrics=metrics,
            faults=faults,
            exhausted_retry_delay_s=exhausted_retry_delay_s,
            shard_id=shard.shard_id,
            executor=executor,
        )

    # -- verification dispatch ----------------------------------------

    def _process(self, task: TransferTask) -> None:
        cls = self._allocator.classify(task.domain_id)
        if cls != "owned":
            if cls == "handover" and self._on_handover is not None \
                    and self._allocator.claim_handover(task.domain_id):
                try:
                    # rewind the active plane over the whole held span:
                    # this plane's ack level lower-bounds every task it
                    # has read but not discharged
                    self._on_handover(
                        min(task.task_id - 1, self.ack.ack_level)
                    )
                except Exception:
                    self._allocator.rearm_handover(task.domain_id)
                    raise
            return  # locally-active (or other-cluster) task: not ours
        handler = {
            TransferTaskType.DecisionTask: self._verify_decision,
            TransferTaskType.ActivityTask: self._verify_activity,
            TransferTaskType.CloseExecution: self._verify_close,
            TransferTaskType.CancelExecution: self._verify_cancel,
            TransferTaskType.SignalExecution: self._verify_signal,
            TransferTaskType.StartChildExecution: self._verify_start_child,
            TransferTaskType.RecordWorkflowStarted: self._record_started,
            TransferTaskType.UpsertWorkflowSearchAttributes: self._upsert,
            TransferTaskType.ResetWorkflow: lambda t: None,
        }.get(task.task_type)
        if handler is None:
            return
        handler(task)

    def _read(self, task, reader):
        try:
            return self.engine.with_workflow(
                task.domain_id, task.workflow_id, task.run_id,
                lambda ctx, ms: reader(ms),
            )
        except EntityNotExistsServiceError:
            return None  # workflow gone: task verified trivially

    def _verify_decision(self, task: TransferTask) -> None:
        # done once replication shows the decision started (or moved on)
        def read(ms):
            ei = ms.execution_info
            return (
                ms.has_pending_decision()
                and ei.decision_schedule_id == task.schedule_id
                and ei.decision_started_id == EMPTY_EVENT_ID
            )

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_activity(self, task: TransferTask) -> None:
        def read(ms):
            ai = ms.get_activity_info(task.schedule_id)
            return ai is not None and ai.started_id == EMPTY_EVENT_ID

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_close(self, task: TransferTask) -> None:
        # standby records closed visibility once the close replicated
        def read(ms):
            if ms.is_workflow_execution_running():
                return "running"
            ei = ms.execution_info
            return VisibilityRecord(
                domain_id=task.domain_id,
                workflow_id=task.workflow_id,
                run_id=task.run_id,
                workflow_type=ei.workflow_type_name,
                start_time=ei.start_timestamp,
                close_time=ei.last_updated_timestamp or self.shard.now(),
                close_status=int(ei.close_status),
                history_length=ms.next_event_id - 1,
                memo=dict(ei.memo),
                search_attributes=dict(ei.search_attributes),
            )

        rec = self._read(task, read)
        if rec == "running":
            raise DeferTask(task.domain_id)
        if rec is not None and self.visibility is not None:
            self.visibility.record_workflow_execution_closed(rec)

    def _verify_cancel(self, task: TransferTask) -> None:
        def read(ms):
            return ms.get_request_cancel_info(task.initiated_id) is not None

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_signal(self, task: TransferTask) -> None:
        def read(ms):
            return ms.get_signal_info(task.initiated_id) is not None

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_start_child(self, task: TransferTask) -> None:
        def read(ms):
            ci = ms.get_child_execution_info(task.initiated_id)
            return ci is not None and ci.started_id == EMPTY_EVENT_ID

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _record_started(self, task: TransferTask) -> None:
        from .transfer import open_visibility_record

        rec = self._read(task, lambda ms: open_visibility_record(task, ms))
        if rec is not None and self.visibility is not None:
            self.visibility.record_workflow_execution_started(rec)

    def _upsert(self, task: TransferTask) -> None:
        from .transfer import open_visibility_record

        rec = self._read(task, lambda ms: open_visibility_record(task, ms))
        if rec is not None and self.visibility is not None:
            self.visibility.upsert_workflow_execution(rec)


class TimerQueueStandbyProcessor:
    """Timer standby variant for one remote cluster: fires against the
    remote cluster's clock, verifies outcomes against replicated state."""

    _TASK_RETRY_COUNT = 3

    def __init__(
        self,
        shard,
        engine,
        cluster: str,
        worker_count: int = 2,
        batch_size: int = 64,
        local_cluster: str = "",
        on_handover=None,
        metrics=None,
        faults=None,
        exhausted_retry_delay_s=None,
    ) -> None:
        from cadence_tpu.utils.metrics import NOOP

        self.shard = shard
        self.engine = engine
        self.cluster = cluster
        self._on_handover = on_handover
        self._exhausted_retry_delay_s = exhausted_retry_delay_s
        self.name = f"timer-standby-{cluster}-{shard.shard_id}"
        self._fault_hook = make_fault_hook(
            faults, f"queue.{self.name}", shard_id=shard.shard_id
        )
        self._log = get_logger(
            "cadence_tpu.queue.timer-standby",
            shard=shard.shard_id, cluster=cluster,
        )
        self._metrics = (metrics or NOOP).tagged(
            service="history_queue",
            queue=f"timer-standby-{cluster}-{shard.shard_id}",
        )
        shard.ensure_cluster_ack_levels(cluster)
        self.ack = QueueAckManager(
            (shard.get_cluster_timer_ack_level(cluster), 0),
            update_shard_ack=lambda lvl: shard.update_cluster_timer_ack_level(
                cluster, lvl[0]
            ),
        )
        # paged-read resume cursor; any forced read rewind (failover,
        # defer retry firing) must drop it or the span would be skipped
        self._resume = ResumeCursor()
        self.ack.on_read_rewind = self._drop_resume
        self.gate = RemoteTimerGate()
        self.gate.set_current_time(
            shard.get_remote_cluster_current_time(cluster)
        )
        shard.add_remote_time_listener(self._on_remote_time)
        self._allocator = _StandbyAllocator(
            engine.domains, cluster, local_cluster=local_cluster,
            failover_version_increment=_fv_increment(engine),
        )
        self._stopped = threading.Event()
        self._paused = threading.Event()  # reshard fence: intake off
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=worker_count,
            thread_name_prefix=f"timer-standby-{cluster}-{shard.shard_id}",
        )
        self._batch_size = batch_size
        self._pump_thread = threading.Thread(
            target=self._pump,
            name=f"timer-standby-{cluster}-{shard.shard_id}-pump",
            daemon=True,
        )

    def _on_remote_time(self, cluster: str, now_ns: int) -> None:
        if cluster == self.cluster:
            self.gate.set_current_time(now_ns)

    def _drop_resume(self) -> None:
        self._resume.drop()
        self.gate.update(0)

    def start(self) -> None:
        self._pump_thread.start()

    def notify(self) -> None:
        self.gate.update(0)

    def stop(self) -> None:
        self._stopped.set()
        self.gate.update(0)
        self._pool.shutdown(wait=False)
        # detach from the shard or this dead processor stays reachable
        # (and notified) through the remote-time listener list forever
        self.shard.remove_remote_time_listener(self._on_remote_time)

    def drain(self, timeout_s: float = 5.0, *, deadline=None) -> bool:
        import time

        if deadline is None:
            deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ack.outstanding() == 0:
                return True
            time.sleep(0.01)
        return False

    # -- reshard fence -------------------------------------------------

    def pause_intake(self) -> None:
        self._paused.set()

    def resume_intake(self) -> None:
        self._paused.clear()
        self.gate.update(0)

    def fence_drain(self, deadline: float):
        """Pause intake, drain in-flight verifications, return the
        standby (ts, id) ack watermark."""
        self.pause_intake()
        if not self.drain(deadline=deadline):
            raise TimeoutError(
                f"queue {self.name} failed to drain for reshard handoff "
                f"({self.ack.outstanding()} in flight)"
            )
        sweep_ack(self.ack, self._log, self.name)
        return self.ack.ack_level

    # -- pump (remote-clock-gated) ------------------------------------

    def _pump(self) -> None:
        while not self._stopped.is_set():
            self.gate.wait(max_wait_s=0.05)
            if self._stopped.is_set():
                return
            try:
                self._process_due()
            except Exception:
                self._log.exception("standby timer pump failed")
            sweep_ack(self.ack, self._log, self.name)
            self._metrics.gauge("task_outstanding", self.ack.outstanding())
            self._metrics.gauge("task_held", self.ack.held())

    def _process_due(self) -> None:
        if self._paused.is_set():
            return
        remote_now = self.gate.current_time()
        if remote_now <= 0:
            return  # no view of the remote clock yet: nothing is "due"
        # begin() BEFORE reading the ack level: a rewind between the
        # two bumps the generation and invalidates this scan's store
        key, gen = self._resume.begin()
        min_ts = self.ack.ack_level[0]

        def offer(task, key):
            if self.ack.add(key):
                self._pool.submit(self._run_task, task, key)

        # (ts, id)-cursor paging, persisted across wakes: a span of
        # HELD tasks (waiting on replication) must not hide the due
        # tasks behind it — retention deletes and other domains' timers
        # keep flowing during replication lag, however large the span
        self._resume.store_if_current(
            read_due_timers(
                self.shard.persistence.execution, self.shard.shard_id,
                min_ts, remote_now + 1, self._batch_size, key, offer,
            ),
            gen,
        )
        future = self.shard.persistence.execution.get_timer_tasks(
            self.shard.shard_id, remote_now + 1, 2**62, 1
        )
        if future:
            self.gate.update(future[0].visibility_timestamp)

    def _run_task(self, task: TimerTask, key) -> None:
        with task_span(self.name, task), \
                timed_task(self._metrics, task) as scope:
            finished = run_task_attempts(
                self._process, task, key, self.ack, self._stopped,
                self._log, scope, self.name,
                retry_count=self._TASK_RETRY_COUNT,
                exhausted_retry_delay_s=self._exhausted_retry_delay_s,
                fault_hook=self._fault_hook,
            )
        if not finished:
            return  # parked (deferred / exhausted-retry) or stopping
        # no task-row deletion on standby; cursor-only
        self.ack.complete(key)

    # -- verification handlers ----------------------------------------

    def _process(self, task: TimerTask) -> None:
        if task.task_type == TimerTaskType.DeleteHistoryEvent:
            # retention runs on every cluster (ref timerQueueStandby
            # taskExecutor executeDeleteHistoryEventTask)
            self._delete_history(task)
            return
        cls = self._allocator.classify(task.domain_id)
        if cls != "owned":
            if cls == "handover" and self._on_handover is not None \
                    and self._allocator.claim_handover(task.domain_id):
                try:
                    self._on_handover(
                        min(
                            (task.visibility_timestamp, task.task_id - 1),
                            self.ack.ack_level,
                        )
                    )
                except Exception:
                    self._allocator.rearm_handover(task.domain_id)
                    raise
            return
        handler = {
            TimerTaskType.UserTimer: self._verify_user_timer,
            TimerTaskType.ActivityTimeout: self._verify_activity_timeout,
            TimerTaskType.DecisionTimeout: self._verify_decision_timeout,
            TimerTaskType.WorkflowTimeout: self._verify_workflow_timeout,
            TimerTaskType.ActivityRetryTimer: lambda t: None,  # active-only
            TimerTaskType.WorkflowBackoffTimer: self._verify_backoff,
        }.get(task.task_type)
        if handler is None:
            return
        handler(task)

    def _read(self, task, reader):
        try:
            return self.engine.with_workflow(
                task.domain_id, task.workflow_id, task.run_id,
                lambda ctx, ms: reader(ms),
            )
        except EntityNotExistsServiceError:
            return None

    def _remote_now(self) -> int:
        return self.gate.current_time()

    def _verify_user_timer(self, task: TimerTask) -> None:
        remote_now = self._remote_now()

        def read(ms):
            if not ms.is_workflow_execution_running():
                return False
            for ti in ms.pending_timers.values():
                if ti.expiry_time <= remote_now:
                    return True  # fired remotely but not yet replicated
            return False

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_activity_timeout(self, task: TimerTask) -> None:
        remote_now = self._remote_now()

        def read(ms):
            if not ms.is_workflow_execution_running():
                return False
            seq = TimerSequence(ms)
            for expiry, _sid, _tt, _ai in seq._activity_timeout_candidates():
                if expiry <= remote_now:
                    return True
            return False

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_decision_timeout(self, task: TimerTask) -> None:
        def read(ms):
            ei = ms.execution_info
            return (
                ms.is_workflow_execution_running()
                and ms.has_pending_decision()
                and ei.decision_schedule_id == task.event_id
            )

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_workflow_timeout(self, task: TimerTask) -> None:
        remote_now = self._remote_now()

        def read(ms):
            if not ms.is_workflow_execution_running():
                return False
            ei = ms.execution_info
            if ei.workflow_timeout <= 0:
                return False
            expiry = ei.start_timestamp + ei.workflow_timeout * 1_000_000_000
            return expiry <= remote_now

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _verify_backoff(self, task: TimerTask) -> None:
        def read(ms):
            return (
                ms.is_workflow_execution_running()
                and not ms.has_pending_decision()
                and ms.execution_info.last_processed_event == EMPTY_EVENT_ID
            )

        if self._read(task, read):
            raise DeferTask(task.domain_id)

    def _delete_history(self, task: TimerTask) -> None:
        from .retention import delete_workflow_retention

        delete_workflow_retention(self.shard, self.engine, task)
