"""Task allocator: should THIS cluster process a task actively?

Reference: service/history/taskAllocator.go — during/after failover,
each queue task is checked against the domain's active cluster; a
standby cluster must not fire timers or dispatch tasks for a domain it
is passive for (the active side does; the standby's state converges via
replication instead).
"""

from __future__ import annotations


class TaskAllocator:
    def __init__(self, domains, cluster_metadata=None) -> None:
        self.domains = domains
        self.cluster_metadata = cluster_metadata

    def should_process(self, domain_id: str) -> bool:
        """True if the task's domain is active here (or local-only, or
        the cluster is single-cluster)."""
        return self.owning_cluster(domain_id) is None

    def owning_cluster(self, domain_id: str) -> "str | None":
        """None when the task's domain is active here; otherwise the
        remote cluster the domain is active in (whose standby plane —
        if one runs here — owns the task)."""
        if self.cluster_metadata is None:
            return None
        try:
            rec = self.domains.get_by_id(domain_id)
        except Exception:
            return None  # unknown domain: let the handler surface it
        if not rec.is_global:
            return None
        active = rec.replication_config.active_cluster_name
        if active == self.cluster_metadata.current_cluster_name:
            return None
        return active


class DeferTask(Exception):
    """Raised by a processor handler when the task must NOT be executed
    or completed now (domain is passive here). The runner abandons the
    task back to the queue after a standby delay — mirroring the
    reference's standby task processors, which hold tasks until the
    domain fails over or replication catches up."""


STANDBY_RETRY_DELAY_S = 0.5


def defer_task(ack, key, delay_s: float = STANDBY_RETRY_DELAY_S) -> None:
    """Hold a deferred (passive-domain / standby-unverified) task: the
    ack entry stays outstanding — blocking the ack sweep so queue GC
    cannot delete the row — and becomes re-readable after the standby
    delay (QueueAckManager.defer)."""
    ack.defer(key, delay_s)
