"""Timer gates: wake the timer pump when the next deadline arrives.

Reference: /root/reference/service/history/timerGate.go — LocalTimerGate
(:91) wraps a local clock; RemoteTimerGate (:164) fires on the remote
(standby) cluster's reported time, advanced by SetCurrentTime.
"""

from __future__ import annotations

import threading
from typing import Optional

from cadence_tpu.utils.clock import RealTimeSource, TimeSource


class LocalTimerGate:
    """Fires when the local clock passes the earliest update()d deadline."""

    def __init__(self, time_source: Optional[TimeSource] = None) -> None:
        self._time = time_source or RealTimeSource()
        self._cond = threading.Condition()
        self._deadline_ns: Optional[int] = None
        self._fired = threading.Event()

    def update(self, deadline_ns: int) -> bool:
        """Arm (or re-arm earlier); True if this became the next deadline."""
        with self._cond:
            if self._deadline_ns is None or deadline_ns < self._deadline_ns:
                self._deadline_ns = deadline_ns
                self._cond.notify_all()
                return True
            return False

    def wait(self, max_wait_s: float = 0.1) -> bool:
        """Block until the deadline passes (True) or max_wait_s (False)."""
        with self._cond:
            deadline = self._deadline_ns
            now = self._time.now()
            if deadline is not None and now >= deadline:
                self._deadline_ns = None
                return True
            wait_s = max_wait_s
            if deadline is not None:
                wait_s = min(max_wait_s, (deadline - now) / 1e9)
            self._cond.wait(max(0.0, min(wait_s, max_wait_s)))
            now = self._time.now()
            if self._deadline_ns is not None and now >= self._deadline_ns:
                self._deadline_ns = None
                return True
            return False

    def fire_after(self) -> Optional[int]:
        with self._cond:
            return self._deadline_ns


class RemoteTimerGate:
    """Fires against the standby cluster's clock (SetCurrentTime)."""

    def __init__(self) -> None:
        self._cond = threading.Condition()
        self._current_ns = 0
        self._deadline_ns: Optional[int] = None

    def set_current_time(self, now_ns: int) -> None:
        with self._cond:
            if now_ns > self._current_ns:
                self._current_ns = now_ns
                self._cond.notify_all()

    def current_time(self) -> int:
        with self._cond:
            return self._current_ns

    def update(self, deadline_ns: int) -> bool:
        with self._cond:
            if self._deadline_ns is None or deadline_ns < self._deadline_ns:
                self._deadline_ns = deadline_ns
                self._cond.notify_all()
                return True
            return False

    def wait(self, max_wait_s: float = 0.1) -> bool:
        with self._cond:
            if (
                self._deadline_ns is not None
                and self._current_ns >= self._deadline_ns
            ):
                self._deadline_ns = None
                return True
            self._cond.wait(max_wait_s)
            if (
                self._deadline_ns is not None
                and self._current_ns >= self._deadline_ns
            ):
                self._deadline_ns = None
                return True
            return False
