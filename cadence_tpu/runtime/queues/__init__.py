"""Per-shard queue processors: transfer, timer, replication.

TPU-native rebuild of the reference history-service queue machinery
(/root/reference/service/history/queueProcessor.go, queueAckMgr.go,
taskProcessor.go, timerQueueProcessorBase.go, timerGate.go,
transferQueueActiveProcessor.go, timerQueueActiveProcessor.go).

These are host-side pull pipelines feeding the engine; on TPU the
corresponding data-plane work (replay, task refresh) runs as device
batches, while the queues remain the control plane that orders, acks,
and retries work items.
"""

from .ack import QueueAckManager
from .base import QueueProcessorBase
from .effects import Footprint, TASK_FOOTPRINTS, build_conflict_matrix
from .standby import (
    QueueGC,
    TimerQueueStandbyProcessor,
    TransferQueueStandbyProcessor,
)
from .timer import TimerQueueProcessor
from .timer_gate import LocalTimerGate, RemoteTimerGate
from .transfer import TransferQueueProcessor

__all__ = [
    "Footprint",
    "QueueAckManager",
    "QueueGC",
    "QueueProcessorBase",
    "TASK_FOOTPRINTS",
    "build_conflict_matrix",
    "TimerQueueProcessor",
    "TimerQueueStandbyProcessor",
    "LocalTimerGate",
    "RemoteTimerGate",
    "TransferQueueProcessor",
    "TransferQueueStandbyProcessor",
]
