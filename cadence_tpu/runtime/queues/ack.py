"""Ordered ack levels over out-of-order task completion.

Reference: /root/reference/service/history/queueAckMgr.go — tasks are
read in order, complete in any order; the ack level advances over the
longest finished prefix and is checkpointed into shardInfo.

Entry states: RUNNING (handed to a worker), DONE (swept by
update_ack_level), DEFERRED (held: the handler raised DeferTask and the
task must be re-read later), RETRY (the defer delay elapsed; the next
pump read may re-take it). A DEFERRED/RETRY entry keeps blocking the
ack sweep — the cursor must never pass a task that was read but not
processed, or queue GC would delete it unexecuted.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from cadence_tpu.utils.locks import make_guarded, make_lock

_RUNNING = 0
_DONE = 1
_DEFERRED = 2
_RETRY = 3


class QueueAckManager:
    def __init__(
        self,
        ack_level,
        update_shard_ack: Optional[Callable[[object], None]] = None,
    ) -> None:
        self._lock = make_lock("QueueAckManager._lock")
        self.ack_level = ack_level  # int task_id or (ts, task_id) for timers
        self.read_level = ack_level
        self._outstanding: Dict[object, int] = make_guarded(
            {}, "QueueAckManager._outstanding", self._lock
        )  # key → state
        self._update_shard_ack = update_shard_ack
        # last level KNOWN to have persisted: a transient checkpoint
        # failure leaves this behind ack_level, and the next sweep
        # retries the checkpoint even if the level didn't move again
        # (otherwise a failed final sweep would lag forever and a
        # restart re-processes the whole span)
        self._persisted_level = ack_level
        # cached min RETRY key (None = no retries): _bump_read_locked
        # consults it on every add(), so it must not rescan the dict
        self._retry_min = None
        # pumps that keep their own read cursor (the timer pumps'
        # _resume_key) register here; called whenever the read level is
        # FORCED backwards (rewind / a defer retry firing) so the
        # cursor can't skip the span the ack wants re-read
        self.on_read_rewind: Optional[Callable[[], None]] = None
        # bumped on every rewind: offers stamped with an older
        # generation belong to a batch read BEFORE the rewind and must
        # not land — their add()/set_read_level would re-bump the read
        # cursor past the rewound span, and the ack sweep would then
        # jump the hole without the span ever re-processing (the
        # failover drill caught exactly this: a handover rewind racing
        # an in-flight read batch lost the handed-over decision task)
        self._generation = 0

    def generation(self) -> int:
        """Stamp for a read batch: capture BEFORE reading, pass to
        add()/set_read_level() — a rewind between read and offer then
        rejects the stale batch instead of skipping the rewound span."""
        with self._lock:
            return self._generation

    def add(self, key, generation: Optional[int] = None) -> bool:
        """Register a read task; False if already outstanding (dup read)
        or already acked (a completed frontier row re-read because queue
        GC deletes exclusively below the ack level). A RETRY entry (its
        defer delay elapsed) is re-taken. ``generation`` (from
        ``generation()`` at read time) rejects offers from a batch read
        before a rewind."""
        with self._lock:
            if generation is not None and generation != self._generation:
                return False
            if key <= self.ack_level:
                return False
            state = self._outstanding.get(key)
            if state is None:
                self._outstanding[key] = _RUNNING
                self._bump_read_locked(key)
                return True
            if state == _RETRY:
                self._outstanding[key] = _RUNNING
                if key == self._retry_min:
                    self._recompute_retry_min_locked()
                return True
            return False

    def add_batch(self, keys, generation: Optional[int] = None):
        """Batched ``add()``: one lock acquisition for a whole read
        batch (the parallel executor's collect path — a 64-task wave
        would otherwise take this lock 64 times per cycle). Per-key
        semantics are identical to ``add()``; returns the taken flags
        in key order. A stale ``generation`` rejects the batch whole."""
        out = []
        with self._lock:
            if generation is not None and generation != self._generation:
                return [False] * len(keys)
            for key in keys:
                if key <= self.ack_level:
                    out.append(False)
                    continue
                state = self._outstanding.get(key)
                if state is None:
                    self._outstanding[key] = _RUNNING
                    self._bump_read_locked(key)
                    out.append(True)
                elif state == _RETRY:
                    self._outstanding[key] = _RUNNING
                    if key == self._retry_min:
                        self._recompute_retry_min_locked()
                    out.append(True)
                else:
                    out.append(False)
        return out

    def _recompute_retry_min_locked(self) -> None:
        self._retry_min = min(
            (k for k, s in self._outstanding.items() if s == _RETRY),
            default=None,
        )

    def _bump_read_locked(self, level) -> None:
        """Advance the read level, but never past a fired retry: its
        ready() rewind happens ONCE, so skipping over it would strand
        the task (read but never re-read) and wedge the ack sweep."""
        if self._retry_min is not None and level >= self._retry_min:
            return
        if level > self.read_level:
            self.read_level = level

    def complete(self, key) -> None:
        with self._lock:
            if key in self._outstanding:
                self._outstanding[key] = _DONE

    def update_ack_level(self):
        """Advance over the finished prefix; checkpoint to the shard
        when the level moved OR a previous checkpoint failed (persisted
        level lagging). The checkpoint happens under the lock so a
        concurrent rewind() cannot be overwritten by a stale higher
        level; a checkpoint error propagates (the pump logs it) with
        the persisted marker unchanged, so the next sweep retries."""
        with self._lock:
            for key in sorted(self._outstanding):
                if self._outstanding[key] != _DONE:
                    break
                del self._outstanding[key]
                self.ack_level = key
            level = self.ack_level
            if (
                level != self._persisted_level
                and self._update_shard_ack is not None
            ):
                self._update_shard_ack(level)
                self._persisted_level = level
        return level

    def rewind(self, level) -> None:
        """Move the cursor back to ``level`` (failover reprocessing: the
        new active side re-reads from the standby cursor; verification-
        based handlers make re-execution idempotent). Persisted
        immediately (under the lock, so no concurrent checkpoint can
        overwrite it): a restart re-initializes from the shard cursor
        and the failover event will not re-fire."""
        with self._lock:
            if level >= self.ack_level:
                return
            self.ack_level = level
            if level < self.read_level:
                self.read_level = level
            # completed-but-unswept entries above the rewound level must
            # not let update_ack_level jump straight back over the span
            # being re-verified
            for key in [k for k in self._outstanding if k > level]:
                del self._outstanding[key]
            self._recompute_retry_min_locked()
            # invalidate any in-flight read batch: its remaining offers
            # would re-bump the read cursor over the rewound span
            self._generation += 1
            if self._update_shard_ack is not None:
                self._update_shard_ack(level)
                self._persisted_level = level
            hook = self.on_read_rewind
        if hook is not None:
            hook()

    def set_read_level(self, level, generation: Optional[int] = None) -> None:
        with self._lock:
            if generation is not None and generation != self._generation:
                return  # batch read before a rewind: cursor stays put
            self._bump_read_locked(level)

    def outstanding(self) -> int:
        """In-flight work items. Parked entries (DEFERRED/RETRY) are not
        counted — they still block the ack sweep, but drain()/quiesce
        checks must not wait on tasks that are parked indefinitely."""
        with self._lock:
            return sum(
                1 for s in self._outstanding.values()
                if s in (_RUNNING, _DONE)
            )

    def held(self) -> int:
        """Parked (DEFERRED/RETRY) entries — the standby hold depth: a
        passive-domain span awaiting replication/failover wedges the ack
        sweep exactly this deep (the task_held gauge's source)."""
        with self._lock:
            return sum(
                1 for s in self._outstanding.values()
                if s not in (_RUNNING, _DONE)
            )

    def defer(self, key, delay_s: float) -> None:
        """Hold a read-but-unprocessable task (passive domain / standby
        verification pending). The entry stays outstanding — blocking
        the ack sweep, so queue GC cannot delete the row — and becomes
        re-takeable (RETRY) after ``delay_s``, when the read level also
        rewinds so the pump re-reads it."""
        with self._lock:
            if self._outstanding.get(key) != _RUNNING:
                return
            self._outstanding[key] = _DEFERRED

        def ready() -> None:
            with self._lock:
                if self._outstanding.get(key) != _DEFERRED:
                    return
                self._outstanding[key] = _RETRY
                self.read_level = self.ack_level
                if self._retry_min is None or key < self._retry_min:
                    self._retry_min = key
                hook = self.on_read_rewind
            if hook is not None:
                hook()

        t = threading.Timer(delay_s, ready)
        t.daemon = True
        t.start()

    def abandon(self, key) -> None:
        """Un-register a task WITHOUT completing it. Unlike defer(),
        the entry is dropped entirely — only safe when the caller KNOWS
        the task will be re-read before the sweep passes it (legacy
        callers); prefer defer()."""
        with self._lock:
            if self._outstanding.pop(key, None) == _RETRY:
                self._recompute_retry_min_locked()
            self.read_level = self.ack_level
            hook = self.on_read_rewind
        if hook is not None:
            hook()
