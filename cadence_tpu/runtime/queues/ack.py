"""Ordered ack levels over out-of-order task completion.

Reference: /root/reference/service/history/queueAckMgr.go — tasks are
read in order, complete in any order; the ack level advances over the
longest finished prefix and is checkpointed into shardInfo.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Tuple


class QueueAckManager:
    def __init__(
        self,
        ack_level,
        update_shard_ack: Optional[Callable[[object], None]] = None,
    ) -> None:
        self._lock = threading.Lock()
        self.ack_level = ack_level  # int task_id or (ts, task_id) for timers
        self.read_level = ack_level
        self._outstanding: Dict[object, bool] = {}  # key → done
        self._update_shard_ack = update_shard_ack

    def add(self, key) -> bool:
        """Register a read task; False if already outstanding (dup read)
        or already acked (a completed frontier row re-read because queue
        GC deletes exclusively below the ack level)."""
        with self._lock:
            if key in self._outstanding or key <= self.ack_level:
                return False
            self._outstanding[key] = False
            if key > self.read_level:
                self.read_level = key
            return True

    def complete(self, key) -> None:
        with self._lock:
            if key in self._outstanding:
                self._outstanding[key] = True

    def update_ack_level(self):
        """Advance over the finished prefix; checkpoint to the shard
        only when the level actually moved."""
        with self._lock:
            before = self.ack_level
            for key in sorted(self._outstanding):
                if not self._outstanding[key]:
                    break
                del self._outstanding[key]
                self.ack_level = key
            level = self.ack_level
        if level != before and self._update_shard_ack is not None:
            self._update_shard_ack(level)
        return level

    def rewind(self, level) -> None:
        """Move the cursor back to ``level`` (failover reprocessing: the
        new active side re-reads from the standby cursor; verification-
        based handlers make re-execution idempotent)."""
        with self._lock:
            if level >= self.ack_level:
                return
            self.ack_level = level
            if level < self.read_level:
                self.read_level = level
            # completed-but-unswept entries above the rewound level must
            # not let update_ack_level jump straight back over the span
            # being re-verified
            for key in [k for k in self._outstanding if k > level]:
                del self._outstanding[key]
        # persist immediately: a restart re-initializes from the shard
        # cursor, and the failover event will not re-fire
        if self._update_shard_ack is not None:
            self._update_shard_ack(level)

    def set_read_level(self, level) -> None:
        with self._lock:
            if level > self.read_level:
                self.read_level = level

    def outstanding(self) -> int:
        with self._lock:
            return len(self._outstanding)

    def abandon(self, key) -> None:
        """Un-register a task WITHOUT completing it: the pump will
        re-read it later (deferred standby tasks). The read level rewinds
        to the ack level so nothing between ack and read is skipped;
        still-outstanding keys dedup via add()."""
        with self._lock:
            self._outstanding.pop(key, None)
            self.read_level = self.ack_level
