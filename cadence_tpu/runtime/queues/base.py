"""Queue processor pump: batched reads → worker pool → ordered acks.

Reference: /root/reference/service/history/queueProcessor.go:160-257
(processBatch + pump), taskProcessor.go:119-313 (worker pool with
per-task retry). The pump wakes on notify or poll interval, reads a
batch past the read level, hands tasks to the pool, and periodically
checkpoints the ack level into shardInfo.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

import contextlib
import time as _time

from cadence_tpu.runtime.api import EntityNotExistsServiceError
from cadence_tpu.utils.locks import make_lock
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP, Scope
from cadence_tpu.utils.tracing import NOOP_SPAN, TRACER

from .ack import QueueAckManager
from .allocator import DeferTask, defer_task
from .effects import task_effect_scope

_TASK_RETRY_COUNT = 3


class ResumeCursor:
    """Paged-read resume cursor with a drop generation.

    A forced read rewind (failover handover, a defer retry firing)
    must WIN over a scan already in flight: ``drop()`` bumps the
    generation, and ``store_if_current`` refuses to save a cursor
    computed before the drop. All transitions are locked — the pump
    thread and ack-hook threads race on this state."""

    def __init__(self) -> None:
        self._lock = make_lock("ResumeCursor._lock")
        self._key = None
        self._gen = 0

    def begin(self):
        with self._lock:
            return self._key, self._gen

    def store_if_current(self, key, gen) -> None:
        with self._lock:
            if gen == self._gen:
                self._key = key

    def drop(self) -> None:
        with self._lock:
            self._gen += 1
            self._key = None


def read_due_timers(
    execution, shard_id: int, min_ts: int, max_ts: int, batch_size: int,
    resume_key, offer, max_pages: int = 16,
):
    """Page the due-timer window with an exclusive (ts, id) resume
    cursor, shared by the active and standby timer pumps.

    Calls ``offer(task, key)`` for every row read. Pages at most
    ``max_pages`` per call; returns the cursor for the NEXT call —
    ``None`` when the window was fully scanned (the next wake restarts
    from the ack level, which also re-reads any fired defer-retries),
    else the last page's key so a held span larger than one call's
    budget keeps advancing instead of re-reading the same rows forever.
    """
    after = resume_key
    for _ in range(max_pages):
        batch = execution.get_timer_tasks(
            shard_id, min_ts, max_ts, batch_size, after_key=after
        )
        for task in batch:
            offer(task, (task.visibility_timestamp, task.task_id))
        if len(batch) < batch_size:
            return None
        after = (batch[-1].visibility_timestamp, batch[-1].task_id)
    return after


_ATTEMPT_BACKOFF_S = (0.05, 0.2)  # between in-line attempts
_EXHAUSTED_RETRY_DELAY_S = 5.0    # park interval after the budget


def make_fault_hook(faults, site: str, shard_id=None):
    """Chaos hook bound to one queue site and its shard, or None (the
    zero-cost default) — shared by the active and standby processor
    families so the site-naming convention can't drift between them.
    ``shard_id`` makes shard-pinned FaultRules matchable at queue
    sites (the replication hooks pass theirs at fire time)."""
    if faults is None:
        return None
    from cadence_tpu.testing.faults import hook

    return hook(faults, site, shard_id=shard_id)


def sweep_ack(ack, log, name: str) -> None:
    """One ack sweep that survives a transient checkpoint failure: the
    in-memory level advanced and the ack manager retries the lagging
    shardInfo persist on its next sweep — the pump thread must outlive
    the error (shared by all three pump implementations)."""
    try:
        ack.update_ack_level()
    except Exception:
        log.exception(f"queue {name} ack sweep failed")


def run_task_attempts(
    process, task, key, ack, stopped, log, scope, name,
    retry_count: int = _TASK_RETRY_COUNT,
    exhausted_retry_delay_s: Optional[float] = None,
    fault_hook=None,
) -> bool:
    """Shared queue-task attempt loop (active transfer/timer + standby
    twins — ONE copy, they had drifted). Returns True when the caller
    should run its completion step (success, or the task is permanently
    stale); False when the task was parked or the processor is
    stopping.

    Transient failures back off between attempts, and an EXHAUSTED
    budget parks the task for a deferred retry instead of acking it
    away — a sub-second dependency outage must not permanently drop a
    task (the reference never acks an errored task). A genuinely
    poisoned task retries at the defer cadence until an operator
    removes it (admin remove-task).

    ``fault_hook`` (testing.faults: the bound ``fire`` of a
    FaultSchedule site) runs inside each attempt, so an injected fault
    exercises exactly this backoff/park machinery; ``exhausted_retry_
    delay_s`` lets chaos runs shrink the park interval to test-scale
    (None = the production default)."""
    if exhausted_retry_delay_s is None:
        exhausted_retry_delay_s = _EXHAUSTED_RETRY_DELAY_S
    last_exc = None
    for attempt in range(retry_count):
        if stopped.is_set():
            return False
        try:
            if fault_hook is not None:
                fault_hook(str(getattr(task, "task_type", "")))
            # attribute persistence calls to this task for the effect
            # witness (testing/effect_witness.py); zero-cost when no
            # recorder is installed
            with task_effect_scope(name, getattr(task, "task_type", "")):
                process(task)
            return True
        except DeferTask:
            defer_task(ack, key)
            return False
        except EntityNotExistsServiceError:
            return True  # stale task: workflow/decision moved on
        except Exception as e:
            last_exc = e
            scope.inc("task_errors")
            if attempt < retry_count - 1:
                stopped.wait(_ATTEMPT_BACKOFF_S[
                    min(attempt, len(_ATTEMPT_BACKOFF_S) - 1)
                ])
    # log.error, not log.exception: this runs OUTSIDE the except block
    # (sys.exc_info is clear), so the final error — the operator's clue
    # for a poisoned task — rides in the message instead
    log.error(
        f"queue {name} task {key} failed {retry_count} attempts "
        f"(last: {type(last_exc).__name__}: {last_exc}); "
        f"parked for retry in {exhausted_retry_delay_s}s"
    )
    defer_task(ack, key, exhausted_retry_delay_s)
    return False


@contextlib.contextmanager
def timed_task(metrics: Scope, task):
    """Standard queue-task triple, tagged by task type: requests counter
    on entry, latency timer on exit; the yielded scope takes the error
    counter (shared by the transfer/timer/standby pipelines)."""
    scope = metrics.tagged(task_type=str(getattr(task, "task_type", "?")))
    scope.inc("task_requests")
    t0 = _time.perf_counter()
    try:
        yield scope
    finally:
        scope.record("task_latency", _time.perf_counter() - t0)


def task_span(queue_name: str, task):
    """Join the workflow's trace for one queue-task execution.

    Queue tasks run on pump-pool threads, so thread-local propagation
    cannot reach them; the engine binds ``("wf", workflow_id) →
    TraceContext`` at persist time (utils/tracing.py) and this lookup
    reconnects the asynchronous hop — the span (and everything the task
    does in this thread: persistence calls, matching add-task, fault
    annotations) lands in the SAME trace the frontend request started.
    No binding (the overwhelmingly common unsampled case) costs one
    len() check and returns the shared no-op. Shared by the active and
    standby processor families plus replication apply."""
    ctx = TRACER.lookup(("wf", getattr(task, "workflow_id", None)))
    if ctx is None:
        return NOOP_SPAN
    return TRACER.span(
        f"queue.{queue_name}", service="history_queue", parent=ctx,
        task_type=str(getattr(task, "task_type", "?")),
        task_id=getattr(task, "task_id", ""),
    )


class QueueProcessorBase:
    def __init__(
        self,
        name: str,
        ack: QueueAckManager,
        read_batch: Callable[[object, int], List[object]],
        process_task: Callable[[object], None],
        complete_task: Callable[[object], None],
        task_key: Callable[[object], object],
        worker_count: int = 4,
        batch_size: int = 64,
        poll_interval_s: float = 0.05,
        metrics: Optional[Scope] = None,
        faults=None,
        exhausted_retry_delay_s: Optional[float] = None,
        shard_id: Optional[int] = None,
        executor=None,
    ) -> None:
        self.name = name
        self.ack = ack
        # chaos hook: fired inside every task attempt under the site
        # "queue.<name>"; None = zero-cost
        self._fault_hook = make_fault_hook(
            faults, f"queue.{name}", shard_id=shard_id
        )
        self._exhausted_retry_delay_s = exhausted_retry_delay_s
        self._read_batch = read_batch
        self._process_task = process_task
        self._complete_task = complete_task
        self._task_key = task_key
        self._batch_size = batch_size
        self._poll_interval = poll_interval_s
        self._log = get_logger(f"cadence_tpu.queue.{name}")
        self._metrics = (metrics or NOOP).tagged(
            service="history_queue", queue=name
        )
        self._notify = threading.Event()
        self._stopped = threading.Event()
        # reshard fence: intake paused (no new batch reads) while
        # in-flight tasks run to completion — the drain-to-watermark
        # step of an ownership handoff
        self._paused = threading.Event()
        # executor mode (queues.parallelism > 0): the shared
        # ParallelQueueExecutor owns the pump thread and worker pool —
        # this processor only contributes collect/run hooks. notify()
        # must NOT set self._notify in that mode: drain() reads it as
        # "pump has pending work", and nothing would ever clear it.
        self._executor = executor
        if executor is None:
            self._pool = ThreadPoolExecutor(
                max_workers=worker_count,
                thread_name_prefix=f"{name}-worker",
            )
            self._pump_thread = threading.Thread(
                target=self._pump, name=f"{name}-pump", daemon=True
            )
        else:
            self._pool = None
            self._pump_thread = None

    def start(self) -> None:
        if self._executor is not None:
            self._executor.register(self)
            return
        self._pump_thread.start()

    def notify(self) -> None:
        if self._executor is not None:
            self._executor.notify()
            return
        self._notify.set()

    def stop(self) -> None:
        self._stopped.set()
        self._notify.set()
        if self._executor is not None:
            self._executor.unregister(self)
            return
        self._pool.shutdown(wait=False)

    def drain(self, timeout_s: float = 5.0, *,
              deadline: Optional[float] = None) -> bool:
        """Wait until no tasks are outstanding (for tests/shutdown).
        ``deadline`` (time.monotonic value) overrides ``timeout_s`` —
        the reshard coordinator passes one shared deadline across every
        pump it drains."""
        import time

        if deadline is None:
            deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self.ack.outstanding() == 0 and (
                self._paused.is_set() or not self._notify.is_set()
            ):
                return True
            time.sleep(0.01)
        return False

    # -- reshard fence -------------------------------------------------

    def pause_intake(self) -> None:
        """Stop reading new batches; in-flight tasks run to completion."""
        self._paused.set()

    def resume_intake(self) -> None:
        self._paused.clear()
        self._notify.set()

    def fence_drain(self, deadline: float):
        """Reshard handoff step (2): pause intake, drain in-flight work,
        and return the recorded ack watermark — everything at/below it
        is durably complete; everything above it moves with the shard.
        Raises TimeoutError when the pump cannot quiesce by ``deadline``
        (the coordinator rolls the handoff back)."""
        self.pause_intake()
        if not self.drain(deadline=deadline):
            raise TimeoutError(
                f"queue {self.name} failed to drain for reshard handoff "
                f"({self.ack.outstanding()} in flight)"
            )
        sweep_ack(self.ack, self._log, self.name)
        return self.ack.ack_level

    # -- pump ----------------------------------------------------------

    def _pump(self) -> None:
        while not self._stopped.is_set():
            self._notify.wait(timeout=self._poll_interval)
            self._notify.clear()
            if self._stopped.is_set():
                return
            try:
                self._process_batch()
            except Exception:
                self._log.exception(f"queue {self.name} batch failed")
            sweep_ack(self.ack, self._log, self.name)
            # in-flight depth + parked depth (standby "hold depth": a
            # DeferTask-parked span wedging the ack sweep; reference
            # defs.go task-type queue gauges)
            self._metrics.gauge("task_outstanding", self.ack.outstanding())
            self._metrics.gauge("task_held", self.ack.held())

    def _process_batch(self) -> None:
        while not self._stopped.is_set():
            if self._paused.is_set():
                return
            # generation BEFORE the read: a rewind (failover handover,
            # reshard fence) landing between this read and the offers
            # below invalidates the whole batch — otherwise the stale
            # offers re-bump the read cursor over the rewound span and
            # the ack sweep jumps it without re-processing a single
            # task of the handed-over span
            gen = self.ack.generation()
            batch = self._read_batch(self.ack.read_level, self._batch_size)
            if not batch:
                return
            for task in batch:
                key = self._task_key(task)
                if not self.ack.add(key, generation=gen):
                    continue  # already outstanding (or batch rewound)
                self._pool.submit(self._run_task, task, key)
            # advance the read cursor past everything READ, including
            # keys add() rejected (parked/running/done): add() only
            # advances it for newly-taken keys, so a full batch of
            # already-outstanding tasks would otherwise re-read the
            # identical rows forever and never leave this loop (no ack
            # sweep, 100% CPU). Parked tasks are still re-read later —
            # their retry timers rewind the read level to the ack level.
            self.ack.set_read_level(self._task_key(batch[-1]), generation=gen)
            if len(batch) < self._batch_size:
                return

    def _run_task(self, task, key) -> None:
        with task_span(self.name, task), \
                timed_task(self._metrics, task) as scope:
            finished = run_task_attempts(
                self._process_task, task, key, self.ack, self._stopped,
                self._log, scope, self.name,
                exhausted_retry_delay_s=self._exhausted_retry_delay_s,
                fault_hook=self._fault_hook,
            )
        if not finished:
            return  # parked (deferred / exhausted-retry) or stopping
        try:
            self._complete_task(task)
        except Exception:
            self._log.exception(f"queue {self.name} complete({key}) failed")
        self.ack.complete(key)

    # -- parallel executor hooks ---------------------------------------

    def parallel_collect(self, limit: int):
        """Executor-mode batch read: one generation-stamped batch taken
        through ``ack.add_batch`` but NOT executed — the shared
        ParallelQueueExecutor schedules the returned ``(task, key)``
        rows into conflict waves. Mirrors one ``_process_batch``
        iteration (same rewind discipline: generation captured before
        the read, cursor bump stamped with it)."""
        if self._paused.is_set() or self._stopped.is_set():
            return [], 0
        gen = self.ack.generation()
        batch = self._read_batch(self.ack.read_level, limit)
        if not batch:
            return [], gen
        keys = [self._task_key(t) for t in batch]
        taken = self.ack.add_batch(keys, generation=gen)
        self.ack.set_read_level(keys[-1], generation=gen)
        return (
            [(t, k) for t, k, ok in zip(batch, keys, taken) if ok],
            gen,
        )

    def parallel_run(self, task, key) -> None:
        """Executor-mode execution of one collected task: the exact
        sequential attempt path (trace span, timing, effect scope,
        fault hook, retry/park, completion)."""
        self._run_task(task, key)
