"""Transfer queue processor (active side).

Reference: /root/reference/service/history/transferQueueActiveProcessor.go
:238-1099 — per-shard pull pipeline over transfer tasks: push decision/
activity tasks to matching, record visibility, close-execution fan-out
(parent notification + parent-close policy), external cancel/signal,
child-workflow start.
"""

from __future__ import annotations

from typing import Optional

from cadence_tpu.core.enums import (
    CancelExternalWorkflowFailedCause,
    ChildWorkflowFailedCause,
    CloseStatus,
    EventType,
    ParentClosePolicy,
    SignalExternalWorkflowFailedCause,
    TransferTaskType,
)
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.tasks import TransferTask
from cadence_tpu.runtime.api import (
    EntityNotExistsServiceError,
    SignalRequest,
    StartWorkflowRequest,
    WorkflowExecutionAlreadyStartedServiceError,
)
from cadence_tpu.runtime.persistence.records import VisibilityRecord
from cadence_tpu.utils.log import get_logger

from .ack import QueueAckManager
from .allocator import DeferTask, TaskAllocator
from .base import QueueProcessorBase

def open_visibility_record(task, ms) -> VisibilityRecord:
    """Open-execution visibility record from mutable state (shared by
    the active and standby transfer pipelines)."""
    ei = ms.execution_info
    return VisibilityRecord(
        domain_id=task.domain_id,
        workflow_id=task.workflow_id,
        run_id=task.run_id,
        workflow_type=ei.workflow_type_name,
        start_time=ei.start_timestamp,
        execution_time=ei.start_timestamp,
        memo=dict(ei.memo),
        search_attributes=dict(ei.search_attributes),
    )


# close status → the child-close event type recorded in the parent
_CLOSE_EVENT = {
    int(CloseStatus.Completed): EventType.ChildWorkflowExecutionCompleted,
    int(CloseStatus.Failed): EventType.ChildWorkflowExecutionFailed,
    int(CloseStatus.Canceled): EventType.ChildWorkflowExecutionCanceled,
    int(CloseStatus.Terminated): EventType.ChildWorkflowExecutionTerminated,
    int(CloseStatus.TimedOut): EventType.ChildWorkflowExecutionTimedOut,
}


class TransferQueueProcessor(QueueProcessorBase):
    def __init__(
        self,
        shard,
        engine,
        matching,  # MatchingEngine or matching client
        history_client,  # routed history client for cross-workflow calls
        visibility=None,  # VisibilityManager
        worker_count: int = 4,
        batch_size: int = 64,
        standby_clusters=(),
        metrics=None,
        faults=None,
        exhausted_retry_delay_s=None,
        executor=None,
    ) -> None:
        self.shard = shard
        self.engine = engine
        self.matching = matching
        self.history_client = history_client
        # when standby variants share this shard's task stream, they own
        # the passive-domain tasks of THEIR clusters (this processor
        # skips past those) and the min-ack QueueGC owns row deletion
        # (per-task delete would starve the standby cursors). A passive
        # task no standby plane covers is still held via DeferTask.
        self.standby_clusters = frozenset(standby_clusters)
        has_standby = bool(self.standby_clusters)
        self.has_standby = has_standby
        self.visibility = (
            visibility
            if visibility is not None
            else shard.persistence.visibility
        )
        self._tlog = get_logger(
            "cadence_tpu.queue.transfer", shard=shard.shard_id
        )
        self._allocator = TaskAllocator(
            engine.domains, getattr(engine, "cluster_metadata", None)
        )
        ack = QueueAckManager(
            shard.get_transfer_ack_level(),
            update_shard_ack=shard.update_transfer_ack_level,
        )
        super().__init__(
            name=f"transfer-{shard.shard_id}",
            ack=ack,
            read_batch=lambda level, n: shard.persistence.execution.get_transfer_tasks(
                shard.shard_id, level, 2**62, n
            ),
            process_task=self._process,
            complete_task=(
                (lambda t: None) if has_standby
                else lambda t: shard.persistence.execution.complete_transfer_task(
                    shard.shard_id, t.task_id
                )
            ),
            task_key=lambda t: t.task_id,
            worker_count=worker_count,
            batch_size=batch_size,
            metrics=metrics,
            faults=faults,
            exhausted_retry_delay_s=exhausted_retry_delay_s,
            shard_id=shard.shard_id,
            executor=executor,
        )

    # -- dispatch ------------------------------------------------------

    def _process(self, task: TransferTask) -> None:
        owner = self._allocator.owning_cluster(task.domain_id)
        if owner is not None:
            if owner in self.standby_clusters:
                # that cluster's standby variant owns this task; skip
                # past it. On failover the service rewinds this cursor
                # to the standby cursor and the verification-based
                # handlers re-run the span idempotently.
                return
            # no standby plane covers the owning cluster: hold until
            # failover makes us active
            raise DeferTask(task.domain_id)
        handler = {
            TransferTaskType.DecisionTask: self._process_decision,
            TransferTaskType.ActivityTask: self._process_activity,
            TransferTaskType.CloseExecution: self._process_close,
            TransferTaskType.CancelExecution: self._process_cancel,
            TransferTaskType.SignalExecution: self._process_signal,
            TransferTaskType.StartChildExecution: self._process_start_child,
            TransferTaskType.RecordWorkflowStarted: self._process_record_started,
            TransferTaskType.UpsertWorkflowSearchAttributes: self._process_upsert,
            TransferTaskType.ResetWorkflow: self._process_reset,
        }.get(task.task_type)
        if handler is None:
            self._tlog.info(f"unknown transfer task type {task.task_type}")
            return
        handler(task)

    def _read_state(self, task: TransferTask, reader):
        """Snapshot fields from the workflow's mutable state; None if the
        workflow is gone (stale task)."""
        try:
            return self.engine.with_workflow(
                task.domain_id, task.workflow_id, task.run_id,
                lambda ctx, ms: reader(ms),
            )
        except EntityNotExistsServiceError:
            return None

    # -- per-type handlers ---------------------------------------------

    def _process_decision(self, task: TransferTask) -> None:
        # verify still pending, resolve sticky task list + timeout
        # (transferQueueActiveProcessor.go processDecisionTask)
        def read(ms):
            ei = ms.execution_info
            if (
                not ms.has_pending_decision()
                or ei.decision_schedule_id != task.schedule_id
                or ei.decision_started_id != EMPTY_EVENT_ID
            ):
                return None
            if ms.is_sticky_task_list_enabled():
                return (ei.sticky_task_list, ei.sticky_schedule_to_start_timeout)
            return (task.task_list or ei.task_list, ei.workflow_timeout)

        target = self._read_state(task, read)
        if target is None:
            return
        task_list, timeout = target
        self.matching.add_decision_task(
            task.domain_id, task.workflow_id, task.run_id,
            task_list, task.schedule_id,
            schedule_to_start_timeout_seconds=timeout,
        )

    def _process_activity(self, task: TransferTask) -> None:
        def read(ms):
            ai = ms.get_activity_info(task.schedule_id)
            if ai is None or ai.started_id != EMPTY_EVENT_ID:
                return None
            return (ai.task_list or task.task_list, ai.schedule_to_start_timeout)

        target = self._read_state(task, read)
        if target is None:
            return
        task_list, timeout = target
        self.matching.add_activity_task(
            task.domain_id, task.workflow_id, task.run_id,
            task_list, task.schedule_id,
            schedule_to_start_timeout_seconds=timeout,
        )

    _CLOSE_ATTR_KEYS = {
        EventType.ChildWorkflowExecutionCompleted: ("result",),
        EventType.ChildWorkflowExecutionFailed: ("reason", "details"),
        EventType.ChildWorkflowExecutionCanceled: ("details",),
        EventType.ChildWorkflowExecutionTimedOut: ("timeout_type",),
        EventType.ChildWorkflowExecutionTerminated: (),
    }

    def _child_close_attrs(self, close_event: EventType, attrs: dict) -> dict:
        keys = self._CLOSE_ATTR_KEYS.get(close_event, ())
        return {k: attrs[k] for k in keys if k in attrs}

    def _process_close(self, task: TransferTask) -> None:
        # (transferQueueActiveProcessor.go processCloseExecution)
        def read(ctx, ms):
            ei = ms.execution_info
            # the close event lives in the final batch — read only that
            first = max(1, ei.completion_event_batch_id)
            history, _ = ctx.read_history(ms, first_event_id=first)
            close_attrs = dict(history[-1].attributes) if history else {}
            return {
                "close_attrs": close_attrs,
                "close_status": int(ei.close_status),
                "workflow_type": ei.workflow_type_name,
                "start_time": ei.start_timestamp,
                "close_time": ei.last_updated_timestamp or self.shard.now(),
                "history_length": ms.next_event_id - 1,
                "parent_domain_id": ei.parent_domain_id,
                "parent_workflow_id": ei.parent_workflow_id,
                "parent_run_id": ei.parent_run_id,
                "parent_initiated_id": ei.initiated_id,
                "memo": dict(ei.memo),
                "search_attributes": dict(ei.search_attributes),
                "branch_token": ei.branch_token,
                "children": [
                    {
                        "policy": ci.parent_close_policy,
                        "domain_id": ms.domain_id,
                        "domain_name": ci.domain_name,
                        "workflow_id": ci.started_workflow_id,
                        "run_id": ci.started_run_id,
                    }
                    for ci in ms.pending_children.values()
                    if ci.started_id != EMPTY_EVENT_ID
                ],
            }

        try:
            snap = self.engine.with_workflow(
                task.domain_id, task.workflow_id, task.run_id, read
            )
        except EntityNotExistsServiceError:
            return
        if self.visibility is not None:
            self.visibility.record_workflow_execution_closed(
                VisibilityRecord(
                    domain_id=task.domain_id,
                    workflow_id=task.workflow_id,
                    run_id=task.run_id,
                    workflow_type=snap["workflow_type"],
                    start_time=snap["start_time"],
                    close_time=snap["close_time"],
                    close_status=snap["close_status"],
                    history_length=snap["history_length"],
                    memo=snap["memo"],
                    search_attributes=snap["search_attributes"],
                )
            )
        # notify parent (RecordChildExecutionCompleted); ContinuedAsNew
        # does not notify — the final run will
        close_event = _CLOSE_EVENT.get(snap["close_status"])
        if snap["parent_workflow_id"] and close_event is not None:
            try:
                self.history_client.record_child_execution_completed(
                    snap["parent_domain_id"], snap["parent_workflow_id"],
                    snap["parent_run_id"], snap["parent_initiated_id"],
                    close_event,
                    child_run_id=task.run_id,
                    **self._child_close_attrs(close_event, snap["close_attrs"]),
                )
            except EntityNotExistsServiceError:
                pass  # parent already gone
        # parent close policy over started children
        # (reference: processCloseExecution → parentclosepolicy)
        for child in snap["children"]:
            self._apply_parent_close_policy(child)
        # archival fan-out (reference: processCloseExecution →
        # archivalClient.Archive when the domain has archival enabled)
        client = getattr(self, "archival_client", None)
        if client is not None:
            try:
                client.maybe_archive(task, snap)
            except Exception:
                self._tlog.exception("archival trigger failed")

    def _apply_parent_close_policy(self, child: dict) -> None:
        policy = child["policy"]
        if policy == ParentClosePolicy.Abandon:
            return
        try:
            domain_name = self.engine.domains.resolve(
                child["domain_name"] or child["domain_id"]
            ).info.name
            if policy == ParentClosePolicy.Terminate:
                self.history_client.terminate_workflow_execution(
                    domain_name, child["workflow_id"], child["run_id"],
                    reason="by parent close policy",
                )
            elif policy == ParentClosePolicy.RequestCancel:
                self.history_client.request_cancel_workflow_execution(
                    domain_name, child["workflow_id"], child["run_id"],
                )
        except EntityNotExistsServiceError:
            pass  # child already closed

    def _process_cancel(self, task: TransferTask) -> None:
        # (processCancelExecution: RPC target, then record result)
        failed_cause: Optional[int] = None
        try:
            target_domain_name = self.engine.domains.get_by_id(
                task.target_domain_id
            ).info.name
            self.history_client.request_cancel_workflow_execution(
                target_domain_name, task.target_workflow_id,
                task.target_run_id,
            )
        except EntityNotExistsServiceError:
            failed_cause = int(
                CancelExternalWorkflowFailedCause.UnknownExternalWorkflowExecution
            )
        self.engine.record_external_cancel_result(
            task.domain_id, task.workflow_id, task.run_id,
            task.initiated_id, task.target_domain_id,
            task.target_workflow_id, task.target_run_id,
            failed_cause=failed_cause,
        )

    def _process_signal(self, task: TransferTask) -> None:
        def read(ms):
            si = ms.get_signal_info(task.initiated_id)
            if si is None:
                return None
            return (si.signal_name, si.input, si.control, si.signal_request_id)

        sig = self._read_state(task, read)
        if sig is None:
            return
        signal_name, input_, control, request_id = sig
        failed_cause: Optional[int] = None
        try:
            target_domain_name = self.engine.domains.get_by_id(
                task.target_domain_id
            ).info.name
            self.history_client.signal_workflow_execution(
                SignalRequest(
                    domain=target_domain_name,
                    workflow_id=task.target_workflow_id,
                    run_id=task.target_run_id, signal_name=signal_name,
                    input=input_, request_id=request_id,
                )
            )
        except EntityNotExistsServiceError:
            failed_cause = int(
                SignalExternalWorkflowFailedCause.UnknownExternalWorkflowExecution
            )
        self.engine.record_external_signal_result(
            task.domain_id, task.workflow_id, task.run_id,
            task.initiated_id, task.target_domain_id,
            task.target_workflow_id, task.target_run_id,
            control=control, failed_cause=failed_cause,
        )

    def _process_start_child(self, task: TransferTask) -> None:
        # (processStartChildExecution: read initiated attrs from the
        # events cache — history branch on miss — then start the child
        # with parent linkage, record started/failed in the parent)
        def read(ctx, ms):
            ci = ms.get_child_execution_info(task.initiated_id)
            if ci is None:
                return None
            if ci.started_id != EMPTY_EVENT_ID:
                return {"already_started": True, "ci": ci}
            initiated = ctx.get_event(
                ms, task.initiated_id,
                first_event_id=max(1, ci.initiated_event_batch_id),
            )
            return {
                "already_started": False,
                "ci": ci,
                "initiated_attrs": dict(initiated.attributes)
                if initiated is not None
                else None,
            }

        try:
            snap = self.engine.with_workflow(
                task.domain_id, task.workflow_id, task.run_id, read
            )
        except EntityNotExistsServiceError:
            return
        if snap is None or snap["already_started"]:
            return
        attrs = snap["initiated_attrs"]
        if attrs is None:
            return
        ci = snap["ci"]
        child_domain = self.engine.domains.resolve(
            attrs.get("domain") or ci.domain_name or task.domain_id
        )
        child_domain_name = child_domain.info.name
        child_domain_id = child_domain.info.id
        parent_domain_name = self.engine.domains.get_by_id(
            task.domain_id
        ).info.name
        request = StartWorkflowRequest(
            domain=child_domain_name,
            workflow_id=attrs.get("workflow_id", ci.started_workflow_id),
            workflow_type=attrs.get("workflow_type", ci.workflow_type_name),
            task_list=attrs.get("task_list", ""),
            execution_start_to_close_timeout_seconds=attrs.get(
                "execution_start_to_close_timeout_seconds", 60
            ),
            task_start_to_close_timeout_seconds=attrs.get(
                "task_start_to_close_timeout_seconds", 10
            ),
            input=attrs.get("input", b""),
            request_id=ci.create_request_id,
            workflow_id_reuse_policy=attrs.get(
                "workflow_id_reuse_policy", 0
            ),
            retry_policy=attrs.get("retry_policy"),
            cron_schedule=attrs.get("cron_schedule", ""),
            parent_domain=parent_domain_name,
            parent_workflow_id=task.workflow_id,
            parent_run_id=task.run_id,
            parent_initiated_id=task.initiated_id,
        )
        try:
            child_run_id = self.history_client.start_workflow_execution(
                request, domain_id=child_domain_id
            )
        except WorkflowExecutionAlreadyStartedServiceError:
            self.engine.record_start_child_execution_failed(
                task.domain_id, task.workflow_id, task.run_id,
                task.initiated_id, child_domain_name,
                request.workflow_id, request.workflow_type,
                cause=int(ChildWorkflowFailedCause.WorkflowAlreadyRunning),
            )
            return
        self.engine.record_child_execution_started(
            task.domain_id, task.workflow_id, task.run_id,
            task.initiated_id, child_domain_name,
            request.workflow_id, child_run_id, request.workflow_type,
        )

    def _open_visibility_record(self, task: TransferTask):
        return self._read_state(
            task, lambda ms: open_visibility_record(task, ms)
        )

    def _process_record_started(self, task: TransferTask) -> None:
        rec = self._open_visibility_record(task)
        if rec is not None and self.visibility is not None:
            self.visibility.record_workflow_execution_started(rec)

    def _process_upsert(self, task: TransferTask) -> None:
        rec = self._open_visibility_record(task)
        if rec is not None and self.visibility is not None:
            self.visibility.upsert_workflow_execution(rec)

    def _process_reset(self, task: TransferTask) -> None:
        # reset-workflow fan-out is driven by the resetor; the transfer
        # task only records visibility of the reset point in the reference
        self._tlog.info(
            f"reset transfer task for {task.workflow_id} (handled by resetor)"
        )
