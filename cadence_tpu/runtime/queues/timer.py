"""Timer queue processor (active side).

Reference: /root/reference/service/history/timerQueueActiveProcessor.go
:244-687 + timerQueueProcessorBase.go — time-ordered pull pipeline over
timer tasks: user timers, the four activity timeout kinds, decision
timeouts, activity retry timers, workflow backoff (cron/retry) timers,
workflow timeout, retention-driven history deletion. The pump sleeps on
a LocalTimerGate armed with the earliest unfired deadline.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from cadence_tpu.core.active_transaction import WorkflowStateError
from cadence_tpu.core.enums import TimeoutType, TimerTaskType
from cadence_tpu.core.ids import EMPTY_EVENT_ID
from cadence_tpu.core.tasks import TimerTask
from cadence_tpu.core.timer_sequence import TimerSequence
from cadence_tpu.runtime.api import EntityNotExistsServiceError
from cadence_tpu.utils.log import get_logger

from cadence_tpu.utils.metrics import NOOP

from .ack import QueueAckManager
from .allocator import DeferTask, TaskAllocator, defer_task
from .base import (
    ResumeCursor,
    make_fault_hook,
    read_due_timers,
    run_task_attempts,
    sweep_ack,
    task_span,
    timed_task,
)
from .timer_gate import LocalTimerGate

_TIMEOUT_REASON = "cadenceInternal:Timeout"


class TimerQueueProcessor:
    """Pump + worker pool keyed on (visibility_timestamp, task_id)."""

    def __init__(
        self,
        shard,
        engine,
        matching=None,
        worker_count: int = 4,
        batch_size: int = 64,
        standby_clusters=(),
        metrics=None,
        faults=None,
        exhausted_retry_delay_s=None,
        executor=None,
    ) -> None:
        self.shard = shard
        self.engine = engine
        self.matching = matching
        self._exhausted_retry_delay_s = exhausted_retry_delay_s
        self.standby_clusters = frozenset(standby_clusters)
        self.has_standby = bool(self.standby_clusters)
        self.name = f"timer-{shard.shard_id}"
        self._fault_hook = make_fault_hook(
            faults, f"queue.{self.name}", shard_id=shard.shard_id
        )
        self._log = get_logger("cadence_tpu.queue.timer", shard=shard.shard_id)
        self._metrics = (metrics or NOOP).tagged(
            service="history_queue", queue=f"timer-{shard.shard_id}"
        )
        self.ack = QueueAckManager(
            (shard.get_timer_ack_level(), 0),
            update_shard_ack=lambda lvl: shard.update_timer_ack_level(lvl[0]),
        )
        # paged-read resume cursor; any forced read rewind (failover,
        # defer retry firing) must drop it or the span would be skipped
        self._resume = ResumeCursor()
        self.ack.on_read_rewind = self._drop_resume
        self.gate = LocalTimerGate(time_source=shard.time_source)
        self._allocator = TaskAllocator(
            engine.domains, getattr(engine, "cluster_metadata", None)
        )
        self._stopped = threading.Event()
        self._paused = threading.Event()  # reshard fence: intake off
        self._batch_size = batch_size
        # executor mode (queues.parallelism > 0): the shared
        # ParallelQueueExecutor polls via parallel_collect; the gate,
        # pool, and pump thread stay unused
        self._executor = executor
        if executor is None:
            self._pool = ThreadPoolExecutor(
                max_workers=worker_count,
                thread_name_prefix=f"timer-{shard.shard_id}",
            )
            self._pump_thread = threading.Thread(
                target=self._pump, name=f"timer-{shard.shard_id}-pump",
                daemon=True,
            )
        else:
            self._pool = None
            self._pump_thread = None

    def _drop_resume(self) -> None:
        self._resume.drop()
        self.gate.update(0)

    def start(self) -> None:
        if self._executor is not None:
            self._executor.register(self)
            return
        self._pump_thread.start()

    def notify(self) -> None:
        if self._executor is not None:
            self._executor.notify()
            return
        # a new timer may be earlier than anything armed: wake now
        self.gate.update(0)

    def stop(self) -> None:
        self._stopped.set()
        self.gate.update(0)
        if self._executor is not None:
            self._executor.unregister(self)
            return
        self._pool.shutdown(wait=False)

    def drain(self, timeout_s: float = 5.0, *, deadline=None) -> bool:
        import time

        if deadline is None:
            deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._paused.is_set():
                # reshard fence: quiescent once nothing is in flight —
                # due-but-unread timers stay in the store and move to
                # the new owner past the recorded watermark
                if self.ack.outstanding() == 0:
                    return True
                time.sleep(0.01)
                continue
            now = self.shard.now()
            batch = self.shard.persistence.execution.get_timer_tasks(
                self.shard.shard_id, self.ack.ack_level[0], now, 1
            )
            if not batch and self.ack.outstanding() == 0:
                return True
            time.sleep(0.01)
        return False

    # -- reshard fence -------------------------------------------------

    def pause_intake(self) -> None:
        self._paused.set()

    def resume_intake(self) -> None:
        self._paused.clear()
        self.gate.update(0)

    def fence_drain(self, deadline: float):
        """Pause intake, drain in-flight timers, return the (ts, id)
        ack watermark (see QueueProcessorBase.fence_drain)."""
        self.pause_intake()
        if not self.drain(deadline=deadline):
            raise TimeoutError(
                f"queue {self.name} failed to drain for reshard handoff "
                f"({self.ack.outstanding()} in flight)"
            )
        sweep_ack(self.ack, self._log, self.name)
        return self.ack.ack_level

    # -- pump ----------------------------------------------------------

    def _pump(self) -> None:
        while not self._stopped.is_set():
            self.gate.wait(max_wait_s=0.05)
            if self._stopped.is_set():
                return
            try:
                self._process_due()
            except Exception:
                self._log.exception("timer pump failed")
            sweep_ack(self.ack, self._log, self.name)
            self._metrics.gauge("task_outstanding", self.ack.outstanding())
            self._metrics.gauge("task_held", self.ack.held())

    def _process_due(self) -> None:
        if self._paused.is_set():
            return
        now = self.shard.now()
        # begin() BEFORE reading the ack level: a rewind between the
        # two bumps the generation and invalidates this scan's store
        key, gen = self._resume.begin()
        min_ts = self.ack.ack_level[0]

        def offer(task, key):
            if self.ack.add(key):
                self._pool.submit(self._run_task, task, key)

        # (ts, id)-cursor paging, persisted across wakes: in-flight or
        # held tasks at the front of the window must not hide due tasks
        # behind them, however large the span
        self._resume.store_if_current(
            read_due_timers(
                self.shard.persistence.execution, self.shard.shard_id,
                min_ts, now + 1, self._batch_size, key, offer,
            ),
            gen,
        )
        # arm the gate with the next future deadline
        future = self.shard.persistence.execution.get_timer_tasks(
            self.shard.shard_id, now + 1, 2**62, 1
        )
        if future:
            self.gate.update(future[0].visibility_timestamp)

    # -- parallel executor hooks ---------------------------------------

    def parallel_collect(self, limit: int):
        """Executor-mode due-window read: the ``_process_due`` scan with
        collection instead of pool submission. Offers are stamped with
        the ack generation so a rewind between this collect and the wave
        execution rejects them (the sequential timer pump relies on the
        resume-cursor drop for the same property; the executor checks
        the generation explicitly before running the wave). No gate
        arming — the executor polls on its own interval."""
        if self._paused.is_set() or self._stopped.is_set():
            return [], 0
        now = self.shard.now()
        key, gen = self._resume.begin()
        agen = self.ack.generation()
        min_ts = self.ack.ack_level[0]
        out = []

        def offer(task, k):
            if self.ack.add(k, generation=agen):
                out.append((task, k))

        self._resume.store_if_current(
            read_due_timers(
                self.shard.persistence.execution, self.shard.shard_id,
                min_ts, now + 1, min(limit, self._batch_size), key, offer,
            ),
            gen,
        )
        return out, agen

    def parallel_run(self, task, key) -> None:
        self._run_task(task, key)

    _TASK_RETRY_COUNT = 3

    def _run_task(self, task: TimerTask, key) -> None:
        with task_span(self.name, task), \
                timed_task(self._metrics, task) as scope:
            finished = run_task_attempts(
                self._process, task, key, self.ack, self._stopped,
                self._log, scope, self.name,
                retry_count=self._TASK_RETRY_COUNT,
                exhausted_retry_delay_s=self._exhausted_retry_delay_s,
                fault_hook=self._fault_hook,
            )
        if not finished:
            return  # parked (deferred / exhausted-retry) or stopping
        if not self.has_standby:   # with standby planes, QueueGC deletes
            try:
                self.shard.persistence.execution.complete_timer_task(
                    self.shard.shard_id, task.visibility_timestamp,
                    task.task_id,
                )
            except Exception:
                self._log.exception(f"complete_timer_task failed for {key}")
        self.ack.complete(key)

    # -- handlers ------------------------------------------------------

    def _process(self, task: TimerTask) -> None:
        owner = self._allocator.owning_cluster(task.domain_id)
        if owner is not None:
            if owner in self.standby_clusters:
                # that cluster's standby variant owns it (incl.
                # retention deletes); failover rewinds this cursor to
                # the standby cursor
                return
            # no standby plane covers the owning cluster: hold the
            # task; it fires here only after a failover makes us active
            raise DeferTask(task.domain_id)
        handler = {
            TimerTaskType.UserTimer: self._process_user_timer,
            TimerTaskType.ActivityTimeout: self._process_activity_timeout,
            TimerTaskType.DecisionTimeout: self._process_decision_timeout,
            TimerTaskType.WorkflowTimeout: self._process_workflow_timeout,
            TimerTaskType.ActivityRetryTimer: self._process_activity_retry,
            TimerTaskType.WorkflowBackoffTimer: self._process_workflow_backoff,
            TimerTaskType.DeleteHistoryEvent: self._process_delete_history,
        }.get(task.task_type)
        if handler is None:
            self._log.info(f"unknown timer task type {task.task_type}")
            return
        handler(task)

    def _mutate(self, task: TimerTask, action) -> None:
        """Engine-locked mutation returning whether events were added."""

        def run(ctx, ms):
            if not ms.is_workflow_execution_running():
                return
            txn = self.engine._txn(ctx, ms, ms.current_version)
            now = self.shard.now()
            try:
                mutated = action(txn, ms, now)
            except WorkflowStateError as e:
                raise EntityNotExistsServiceError(str(e))
            if not mutated:
                return
            if (
                ms.is_workflow_execution_running()
                and not ms.has_pending_decision()
                and not txn.has_buffered_events()
            ):
                txn.add_decision_task_scheduled(now)
            result = txn.close()
            ctx.update_workflow(ms, result)
            self.engine._notify(result)

        self.engine.with_workflow(
            task.domain_id, task.workflow_id, task.run_id, run
        )

    def _process_user_timer(self, task: TimerTask) -> None:
        # processExpiredUserTimer (:302): fire every expired timer
        def action(txn, ms, now):
            fired = False
            for ti in sorted(
                ms.pending_timers.values(),
                key=lambda t: (t.expiry_time, t.started_id),
            ):
                if ti.expiry_time > now:
                    break
                txn.add_timer_fired(ti.timer_id, now)
                fired = True
            return fired

        self._mutate(task, action)

    def _process_activity_timeout(self, task: TimerTask) -> None:
        # processActivityTimeout (:355): sweep every expired armed
        # timeout; retry before recording the terminal timeout event;
        # then re-arm the next activity timer.
        def action(txn, ms, now):
            mutated = False
            seq = TimerSequence(ms)
            handled = set()  # at most one expiry per activity per sweep
            for expiry, schedule_id, timeout_type, ai in list(
                seq._activity_timeout_candidates()
            ):
                if expiry > now:
                    break
                if schedule_id in handled:
                    continue
                if ai.schedule_id not in ms.pending_activities:
                    continue  # closed earlier in this sweep
                handled.add(schedule_id)
                tt = TimeoutType(timeout_type)
                # ScheduleToClose spans all attempts — terminal, no retry
                if tt != TimeoutType.ScheduleToClose:
                    retry_task = ms.retry_activity(
                        ai, now, failure_reason=_TIMEOUT_REASON
                    )
                    if retry_task is not None:
                        txn.schedule_timer_task(retry_task)
                        mutated = True
                        continue
                txn.add_activity_task_timed_out(
                    schedule_id, now, tt,
                    details=ai.details if tt == TimeoutType.Heartbeat else b"",
                )
                mutated = True
            # heartbeat may have moved the deadline without an event:
            # clear created-bits and re-arm the earliest timeout so the
            # durable timer follows the live deadline
            for ai in ms.pending_activities.values():
                ai.timer_task_status = 0
            rearm = seq.activity_timer_task_if_needed()
            if rearm is not None:
                txn.schedule_timer_task(rearm)
                mutated = True
            return mutated

        self._mutate(task, action)

    def _process_decision_timeout(self, task: TimerTask) -> None:
        # processDecisionTimeout: StartToClose times out the in-flight
        # decision and schedules a retry attempt; ScheduleToStart fires
        # only for sticky dispatch and reschedules on the normal list.
        def action(txn, ms, now):
            ei = ms.execution_info
            if (
                not ms.has_pending_decision()
                or ei.decision_schedule_id != task.event_id
            ):
                return False
            tt = TimeoutType(task.timeout_type)
            if tt == TimeoutType.StartToClose:
                if ei.decision_started_id == EMPTY_EVENT_ID:
                    return False
                if ei.decision_attempt != task.schedule_attempt:
                    return False
                txn.add_decision_task_timed_out(
                    ei.decision_schedule_id, ei.decision_started_id, now
                )
                txn.add_decision_task_scheduled(now)
                return True
            # ScheduleToStart: only valid while not yet started (sticky)
            if ei.decision_started_id != EMPTY_EVENT_ID:
                return False
            ms.clear_stickiness()
            txn.add_decision_task_timed_out(
                ei.decision_schedule_id, EMPTY_EVENT_ID, now,
                timeout_type=TimeoutType.ScheduleToStart,
            )
            txn.add_decision_task_scheduled(now)
            return True

        self._mutate(task, action)

    def _process_workflow_timeout(self, task: TimerTask) -> None:
        # processWorkflowTimeout (:687): verify the run really expired;
        # a run with retry budget or a cron schedule restarts instead of
        # closing (reference retryWorkflow/cronWorkflow on timeout)
        from cadence_tpu.core.ids import FIRST_EVENT_ID
        from cadence_tpu.runtime.engine.cron_retry import (
            try_continue_after_close,
        )

        def run(ctx, ms):
            if not ms.is_workflow_execution_running():
                return
            ei = ms.execution_info
            if ei.workflow_timeout <= 0:
                return
            now = self.shard.now()
            expiry = ei.start_timestamp + ei.workflow_timeout * 1_000_000_000
            if expiry > now:
                return
            txn = self.engine._txn(ctx, ms, ms.current_version)
            try:
                if not try_continue_after_close(
                    txn, ms, lambda: ctx.get_event(ms, FIRST_EVENT_ID),
                    "timeout", now, error_reason=_TIMEOUT_REASON,
                ):
                    txn.add_workflow_execution_timed_out(now)
            except WorkflowStateError as e:
                raise EntityNotExistsServiceError(str(e))
            result = txn.close()
            ctx.update_workflow(ms, result)
            self.engine._notify(result)

        self.engine.with_workflow(
            task.domain_id, task.workflow_id, task.run_id, run
        )

    def _process_activity_retry(self, task: TimerTask) -> None:
        # processActivityRetryTimer (:610): push the next attempt
        def read(ms):
            ai = ms.get_activity_info(task.event_id)
            if (
                ai is None
                or ai.started_id != EMPTY_EVENT_ID
                or ai.attempt != task.schedule_attempt
            ):
                return None
            return (ai.task_list, ai.schedule_to_start_timeout)

        try:
            target = self.engine.with_workflow(
                task.domain_id, task.workflow_id, task.run_id,
                lambda ctx, ms: read(ms),
            )
        except EntityNotExistsServiceError:
            return
        if target is None or self.matching is None:
            return
        task_list, timeout = target
        self.matching.add_activity_task(
            task.domain_id, task.workflow_id, task.run_id,
            task_list, task.event_id,
            schedule_to_start_timeout_seconds=timeout,
        )

    def _process_workflow_backoff(self, task: TimerTask) -> None:
        # processWorkflowBackoffTimer: first decision after cron/retry
        def action(txn, ms, now):
            if ms.has_pending_decision():
                return False
            if ms.execution_info.last_processed_event != EMPTY_EVENT_ID:
                return False  # past the first decision already
            txn.add_decision_task_scheduled(now)
            return True

        self._mutate(task, action)

    def _process_delete_history(self, task: TimerTask) -> None:
        # retention GC (timerQueueProcessorBase deleteHistoryEvent)
        from .retention import delete_workflow_retention

        delete_workflow_retention(self.shard, self.engine, task)
