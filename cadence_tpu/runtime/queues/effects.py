"""Queue-task effect footprints + the task-type commutativity matrix.

The dependency-aware parallel queue (ROADMAP) needs a machine-checked
answer to "which queue-task pairs commute?" — the same commutativity
argument "Rethinking State-Machine Replication for Parallelism" uses to
run non-conflicting SMR commands in parallel. This module is the single
source of truth both sides of that proof share:

* **declared footprints** (``TASK_FOOTPRINTS``) — per (plane, task
  type), which persistence *surfaces* the handler reads/writes and
  which cross-workflow effects it fans out. Analysis Pass 5
  (``cadence_tpu/analysis/queue_effects.py``) AST-extracts the real
  handlers and fails the gate when a handler touches persistence
  outside its declaration (``QUEUE-CONFLICT-UNDECLARED``) or fans out
  across workflows without declaring it (``QUEUE-CROSS-WF``);
* **the runtime witness hook** (``task_effect_scope`` +
  ``record_persistence_call``) — the chaos suites install an effect
  recorder (testing/effect_witness.py rides ``wrap_bundle`` like the
  fault client) and every persistence call made while a queue task is
  executing is attributed to that task's (plane, type). The witness
  checker then asserts recorded ⊆ static — the dynamic half of the
  bidirectional proof, run under the ≥10% write-fault storm;
* **the conflict matrix** (``build_conflict_matrix``) — pairwise
  commute/conflict verdicts derived from the footprints, emitted as a
  versioned JSON artifact by ``analysis --emit-conflict-matrix``. The
  future parallel-queue executor gates on this artifact exactly like
  the replay kernel gates on ``--emit-matrix``.

Surface model. Effects are keyed by *surface*, each with a scope that
decides how same-surface touches compose:

* ``workflow`` — rows keyed by (domain, workflow, run): two tasks
  touching the surface conflict only when they target the same
  workflow;
* ``read_shared`` — read-only shared state (domain records): reads
  always commute;
* ``counter`` — commuting read-modify-write (the shard task-id
  sequencer): increments commute with each other, the canonical
  "disjoint up to commuting operations" carve-out.

Cross-workflow effects (``xwf.*``) break per-workflow conflict keying:
a CloseExecution's parent-close-policy fan-out may terminate ANY child
workflow, so it conflicts with every task that touches workflow-scoped
state on a distinct workflow — which is why the matrix carries separate
same-workflow and distinct-workflow verdicts.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import threading
from typing import Dict, FrozenSet, Iterable, Optional, Tuple

from cadence_tpu.core.enums import TimerTaskType, TransferTaskType

# surface name → scope (see module docstring)
SURFACES: Dict[str, str] = {
    "execution": "workflow",     # mutable-state rows (update/delete/create)
    "current_run": "workflow",   # current-run pointer rows
    "history": "workflow",       # history branch nodes
    "queue_tasks": "workflow",   # transfer/timer/replication task rows
    "task_store": "workflow",    # matching task-list rows (per-wf appends)
    "visibility": "workflow",    # per-workflow visibility records
    "checkpoint": "workflow",    # replay checkpoints
    "archival": "workflow",      # archival fan-out records
    "metadata": "read_shared",   # domain records (handlers only read)
    "shard_seq": "counter",      # shard sequencer / lease row (id minting)
}

# cross-workflow effect vocabulary (the xwf.* names Pass 5 extracts)
XWF_EFFECTS = frozenset({
    "xwf.record_child_close",  # notify parent of a child close
    "xwf.terminate",           # parent-close-policy terminate
    "xwf.request_cancel",      # parent-close-policy / external cancel
    "xwf.signal",              # external signal delivery
    "xwf.start_child",         # start a child workflow
})


@dataclasses.dataclass(frozen=True)
class Footprint:
    """One task type's declared effect footprint."""

    reads: FrozenSet[str] = frozenset()
    writes: FrozenSet[str] = frozenset()
    cross_workflow: FrozenSet[str] = frozenset()

    def validate(self) -> None:
        for s in self.reads | self.writes:
            if s not in SURFACES:
                raise ValueError(f"footprint: unknown surface {s!r}")
        for x in self.cross_workflow:
            if x not in XWF_EFFECTS:
                raise ValueError(f"footprint: unknown xwf effect {x!r}")


def _fp(reads: Iterable[str] = (), writes: Iterable[str] = (),
        cross: Iterable[str] = ()) -> Footprint:
    return Footprint(frozenset(reads), frozenset(writes), frozenset(cross))


# effects every queue task pays before its handler runs (domain-owner
# classification via the allocator/domain cache) — merged into the
# declared footprint by effective_footprint(), NOT part of the per-type
# declaration the static extractor diffs handler bodies against
PLANE_COMMON_READS = frozenset({"metadata"})

# the active-side event-mint footprint: an engine transaction close
# persists the execution row + minted task rows with ids from the shard
# sequencer, and appends the minted events to the history branch
_MINT_W = ("execution", "history", "queue_tasks", "shard_seq")

# retention-driven deletion (shared by the active + standby timer planes)
_RETENTION = _fp(
    reads=("execution",),
    writes=("execution", "current_run", "visibility", "history"),
)

# verification-only standby handler: reads replicated state, no writes
_VERIFY = _fp(reads=("execution",))

_NOOP = _fp()

# (plane, task type name) → declared footprint. Planes mirror the
# processor families: "transfer"/"timer" are the active pipelines,
# "*-standby" the per-cluster verification twins, "replication" the
# NDC apply path (pseudo task types — it is not task-type dispatched).
TASK_FOOTPRINTS: Dict[Tuple[str, str], Footprint] = {
    # -- transfer (active) ---------------------------------------------
    ("transfer", "DecisionTask"): _fp(
        reads=("execution",), writes=("task_store",)),
    ("transfer", "ActivityTask"): _fp(
        reads=("execution",), writes=("task_store",)),
    ("transfer", "CloseExecution"): _fp(
        # reads its own close batch; visibility+archival on itself; the
        # parent notify + parent-close-policy fan-out mint events on
        # OTHER workflows (the implied _MINT_W surfaces ride in writes
        # so the witness can attribute the fan-out's persistence calls)
        reads=("execution", "history"),
        writes=("visibility", "archival") + _MINT_W,
        cross=("xwf.record_child_close", "xwf.terminate",
               "xwf.request_cancel")),
    ("transfer", "CancelExecution"): _fp(
        reads=("execution",), writes=_MINT_W,
        cross=("xwf.request_cancel",)),
    ("transfer", "SignalExecution"): _fp(
        reads=("execution",), writes=_MINT_W,
        cross=("xwf.signal",)),
    ("transfer", "StartChildExecution"): _fp(
        # reads the initiated event; the child start creates execution +
        # current rows (on the child); started/failed recorded on self
        reads=("execution", "history"),
        writes=("current_run", "task_store", "visibility") + _MINT_W,
        cross=("xwf.start_child",)),
    ("transfer", "RecordWorkflowStarted"): _fp(
        reads=("execution",), writes=("visibility",)),
    ("transfer", "UpsertWorkflowSearchAttributes"): _fp(
        reads=("execution",), writes=("visibility",)),
    ("transfer", "ResetWorkflow"): _NOOP,
    # -- timer (active) ------------------------------------------------
    ("timer", "UserTimer"): _fp(reads=("execution",), writes=_MINT_W),
    ("timer", "ActivityTimeout"): _fp(
        reads=("execution",), writes=_MINT_W),
    ("timer", "DecisionTimeout"): _fp(
        reads=("execution",), writes=_MINT_W),
    ("timer", "WorkflowTimeout"): _fp(
        # cron/retry restart reads the first event for the relaunch
        reads=("execution", "history"), writes=_MINT_W),
    ("timer", "ActivityRetryTimer"): _fp(
        reads=("execution",), writes=("task_store",)),
    ("timer", "WorkflowBackoffTimer"): _fp(
        reads=("execution",), writes=_MINT_W),
    ("timer", "DeleteHistoryEvent"): _RETENTION,
    # -- transfer standby (verify-and-discharge) -----------------------
    ("transfer-standby", "DecisionTask"): _VERIFY,
    ("transfer-standby", "ActivityTask"): _VERIFY,
    ("transfer-standby", "CloseExecution"): _fp(
        reads=("execution",), writes=("visibility",)),
    ("transfer-standby", "CancelExecution"): _VERIFY,
    ("transfer-standby", "SignalExecution"): _VERIFY,
    ("transfer-standby", "StartChildExecution"): _VERIFY,
    ("transfer-standby", "RecordWorkflowStarted"): _fp(
        reads=("execution",), writes=("visibility",)),
    ("transfer-standby", "UpsertWorkflowSearchAttributes"): _fp(
        reads=("execution",), writes=("visibility",)),
    ("transfer-standby", "ResetWorkflow"): _NOOP,
    # -- timer standby -------------------------------------------------
    ("timer-standby", "UserTimer"): _VERIFY,
    ("timer-standby", "ActivityTimeout"): _VERIFY,
    ("timer-standby", "DecisionTimeout"): _VERIFY,
    ("timer-standby", "WorkflowTimeout"): _VERIFY,
    ("timer-standby", "ActivityRetryTimer"): _NOOP,   # active-only
    ("timer-standby", "WorkflowBackoffTimer"): _VERIFY,
    ("timer-standby", "DeleteHistoryEvent"): _RETENTION,
    # -- replication (NDC apply path; pseudo task types) ---------------
    ("replication", "HistoryReplication"): _fp(
        reads=("execution", "history", "current_run", "checkpoint"),
        writes=("execution", "current_run", "history", "queue_tasks",
                "shard_seq", "checkpoint")),
    ("replication", "SnapshotReplication"): _fp(
        reads=("execution", "history", "current_run", "checkpoint"),
        writes=("execution", "current_run", "history", "queue_tasks",
                "shard_seq", "checkpoint")),
    ("replication", "HistoryBackfill"): _fp(
        reads=("execution",), writes=("history", "shard_seq")),
}

for _f in TASK_FOOTPRINTS.values():
    _f.validate()

PLANES = ("transfer", "timer", "transfer-standby", "timer-standby",
          "replication")


def effective_footprint(plane: str, task_type: str) -> Optional[Footprint]:
    """Declared footprint + the plane-common prelude (domain-owner
    classification) — what the runtime witness checks recorded effects
    against; None for an undeclared (plane, type)."""
    base = TASK_FOOTPRINTS.get((plane, task_type))
    if base is None:
        return None
    return Footprint(
        base.reads | PLANE_COMMON_READS, base.writes, base.cross_workflow
    )


# --------------------------------------------------------------------------
# persistence-verb → surface mapping (shared by the witness and Pass 5)
# --------------------------------------------------------------------------

_READ_PREFIXES = ("get_", "list_", "read_", "count_", "describe_")


def verb_effects(manager: str, method: str) -> Tuple[Tuple[str, str], ...]:
    """((surface, "r"|"w"), ...) for one persistence-manager call —
    the canonical name of what a wrapped-bundle invocation touches.
    Unknown managers map to themselves so a new manager surfaces as an
    undeclared effect instead of vanishing."""
    kind = "r" if method.startswith(_READ_PREFIXES) else "w"
    if manager == "metadata":
        return (("metadata", kind),)
    if manager == "visibility":
        return (("visibility", kind),)
    if manager == "task":
        return (("task_store", kind),)
    if manager == "shard":
        return (("shard_seq", kind),)
    if manager == "checkpoint":
        return (("checkpoint", kind),)
    if manager == "history":
        return (("history", kind),)
    if manager == "execution":
        if "current" in method:
            return (("current_run", kind),)
        if ("transfer_task" in method or "timer_task" in method
                or "replication_task" in method or "cross_cluster" in method):
            return (("queue_tasks", kind),)
        if method == "create_workflow_execution":
            # a create writes the state row AND the current-run pointer,
            # plus any minted task rows riding the snapshot
            return (("execution", "w"), ("current_run", "w"),
                    ("queue_tasks", "w"))
        if method in ("update_workflow_execution",
                      "conflict_resolve_workflow_execution"):
            return (("execution", "w"), ("queue_tasks", "w"))
        if method.startswith("reshard_"):
            return (("execution", kind), ("queue_tasks", kind))
        return (("execution", kind),)
    return ((manager, kind),)


# --------------------------------------------------------------------------
# runtime witness hook: task attribution for recorded persistence calls
# --------------------------------------------------------------------------

_SCOPE = threading.local()
_recorder = None  # callable(plane, task_type, manager, method) | None


def set_recorder(cb) -> None:
    """Install (or clear, with None) the process-wide effect recorder.
    Testing-only plumbing: with no recorder, task_effect_scope and
    record_persistence_call are a single module-global check."""
    global _recorder
    _recorder = cb


def plane_of(queue_name: str) -> Optional[str]:
    """Map a processor name ("transfer-standby-west-3", "timer-0",
    "replication") to its footprint plane; None for non-queue scopes."""
    for plane in ("transfer-standby", "timer-standby", "transfer",
                  "timer", "replication"):
        if queue_name == plane or queue_name.startswith(plane + "-"):
            return plane
    return None


def task_type_name(plane: str, task_type) -> str:
    """Footprint key for a task's type: enum member name for the
    transfer/timer planes, the pseudo-type string for replication."""
    try:
        if plane in ("transfer", "transfer-standby"):
            return TransferTaskType(int(task_type)).name
        if plane in ("timer", "timer-standby"):
            return TimerTaskType(int(task_type)).name
    except (ValueError, TypeError):
        pass
    return str(task_type)


class _NoopScope:
    """Shared disabled scope: entering/exiting touches nothing — the
    per-task-attempt cost with no recorder installed is one module
    global check and no allocation (the queue hot path runs this for
    every task in the system)."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP_SCOPE = _NoopScope()


class _TaskScope:
    __slots__ = ("_queue_name", "_task_type", "_prev")

    def __init__(self, queue_name: str, task_type) -> None:
        self._queue_name = queue_name
        self._task_type = task_type

    def __enter__(self):
        self._prev = getattr(_SCOPE, "cur", None)
        _SCOPE.cur = (self._queue_name, self._task_type)
        return None

    def __exit__(self, *exc):
        _SCOPE.cur = self._prev
        return False


def task_effect_scope(queue_name: str, task_type):
    """Attribute persistence calls on this thread to one queue task.

    Entered around every queue-task attempt (runtime/queues/base.py
    ``run_task_attempts``) and the NDC apply entry points. Returns the
    shared no-op scope when no recorder is installed (the
    overwhelmingly common case)."""
    if _recorder is None:
        return _NOOP_SCOPE
    return _TaskScope(queue_name, task_type)


def record_persistence_call(manager: str, method: str) -> None:
    """Called by the effect-witness persistence decorator per call;
    drops calls made outside any task scope (pump machinery, ack
    checkpoints, test setup)."""
    cb = _recorder
    if cb is None:
        return
    cur = getattr(_SCOPE, "cur", None)
    if cur is None:
        return
    plane = plane_of(cur[0])
    if plane is None:
        return
    cb(plane, task_type_name(plane, cur[1]), manager, method)


# --------------------------------------------------------------------------
# commutativity matrix
# --------------------------------------------------------------------------

CONFLICT_MATRIX_SCHEMA = "queue_conflict_matrix"


def footprints_fingerprint() -> str:
    """Stable digest of the declared footprint table + surface scopes.

    Embedded in the emitted conflict matrix and re-derived by the
    parallel-queue executor at construction: a matrix artifact whose
    fingerprint does not match the LIVE table was built against a
    different footprint declaration and must not drive scheduling
    (the executor degrades to sequential and counts
    ``parqueue_matrix_stale``)."""
    doc = {
        "surfaces": dict(sorted(SURFACES.items())),
        "footprints": {
            f"{p}:{t}": {
                "reads": sorted(f.reads),
                "writes": sorted(f.writes),
                "cross_workflow": sorted(f.cross_workflow),
            }
            for (p, t), f in sorted(TASK_FOOTPRINTS.items())
        },
    }
    blob = json.dumps(doc, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]


def _conflicting_overlap(a: FrozenSet[str], b: FrozenSet[str]):
    """Shared surfaces whose scope does NOT make same-surface touches
    commute (counter increments and shared reads do)."""
    return sorted(
        s for s in a & b
        if SURFACES.get(s) not in ("counter", "read_shared")
    )


def _touches_workflow_state(f: Footprint) -> bool:
    return any(
        SURFACES.get(s) == "workflow" for s in f.reads | f.writes
    ) or bool(f.cross_workflow)


def pair_verdict(a: Footprint, b: Footprint) -> Dict[str, object]:
    """Commute/conflict verdicts for one task-type pair.

    ``same_workflow``: both tasks target the same workflow — they
    commute iff neither's writes intersect the other's reads∪writes on
    a non-commuting surface. ``distinct_workflows``: workflow-scoped
    surfaces are disjoint rows, so the pair commutes unless either side
    fans out across workflows (the fan-out may target the other task's
    workflow, defeating per-workflow conflict keying)."""
    reasons = []
    ww = _conflicting_overlap(a.writes, b.writes)
    rw = sorted(set(_conflicting_overlap(a.reads, b.writes))
                | set(_conflicting_overlap(b.reads, a.writes)))
    if ww:
        reasons.append(f"write/write overlap: {','.join(ww)}")
    if rw:
        reasons.append(f"read/write overlap: {','.join(rw)}")
    same = "conflict" if reasons else "commute"

    distinct_reasons = []
    if a.cross_workflow and _touches_workflow_state(b):
        distinct_reasons.append(
            f"a fans out cross-workflow ({','.join(sorted(a.cross_workflow))})"
        )
    if b.cross_workflow and _touches_workflow_state(a):
        distinct_reasons.append(
            f"b fans out cross-workflow ({','.join(sorted(b.cross_workflow))})"
        )
    distinct = "conflict" if distinct_reasons else "commute"
    return {
        "same_workflow": same,
        "distinct_workflows": distinct,
        "reasons": reasons + distinct_reasons,
    }


def build_conflict_matrix() -> Dict[str, object]:
    """The full task-type × task-type commutativity matrix as a
    JSON-ready document (wrapped with schema_version by the analysis
    artifact writer). Pairs are unordered; each appears once with
    a <= b in key order."""
    keys = sorted(TASK_FOOTPRINTS)
    labels = [f"{p}:{t}" for p, t in keys]
    fps = {
        f"{p}:{t}": {
            "reads": sorted(effective_footprint(p, t).reads),
            "writes": sorted(f.writes),
            "cross_workflow": sorted(f.cross_workflow),
        }
        for (p, t), f in TASK_FOOTPRINTS.items()
    }
    pairs = []
    for i, ka in enumerate(keys):
        for kb in keys[i:]:
            v = pair_verdict(TASK_FOOTPRINTS[ka], TASK_FOOTPRINTS[kb])
            pairs.append({
                "a": f"{ka[0]}:{ka[1]}",
                "b": f"{kb[0]}:{kb[1]}",
                **v,
            })
    return {
        "surfaces": dict(SURFACES),
        "task_types": labels,
        "footprints": fps,
        "pairs": pairs,
        "fingerprint": footprints_fingerprint(),
    }
