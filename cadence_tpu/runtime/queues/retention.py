"""Retention-driven workflow deletion, shared by the active and standby
timer pipelines (ref timerQueueProcessorBase.go deleteHistoryEvent —
retention runs on every cluster)."""

from __future__ import annotations


def delete_workflow_retention(shard, engine, task) -> None:
    """Remove visibility, mutable state, and the history branch of a
    retention-expired run; idempotent (a second call finds nothing)."""
    ex = shard.persistence.execution
    vis = shard.persistence.visibility
    hist = shard.persistence.history
    try:
        record = ex.get_workflow_execution(
            shard.shard_id, task.domain_id, task.workflow_id, task.run_id,
        )
    except Exception:
        return  # already gone
    if vis is not None:
        try:
            vis.delete_workflow_execution(
                task.domain_id, task.workflow_id, task.run_id
            )
        except Exception:
            pass
    branch = record.snapshot.get("execution_info", {}).get("branch_token", b"")
    ex.delete_current_workflow_execution(
        shard.shard_id, task.domain_id, task.workflow_id, task.run_id
    )
    ex.delete_workflow_execution(
        shard.shard_id, task.domain_id, task.workflow_id, task.run_id
    )
    if branch and hist is not None:
        from cadence_tpu.runtime.persistence.records import BranchToken
        from cadence_tpu.utils.log import get_logger

        if isinstance(branch, bytes):
            branch = branch.decode()
        try:
            hist.delete_history_branch(BranchToken.from_json(branch))
        except Exception:
            # the execution record is already gone, so this branch will
            # never be retried — make the leak visible instead of
            # silently recreating the swallowed-error bug
            get_logger("cadence_tpu.retention").exception(
                f"history branch delete failed for {task.workflow_id}/"
                f"{task.run_id}; branch leaked"
            )
    engine.cache.evict(task.domain_id, task.workflow_id, task.run_id)
    events_cache = getattr(engine, "events_cache", None)
    if events_cache is not None:
        events_cache.delete_workflow(
            task.domain_id, task.workflow_id, task.run_id
        )
