"""Dependency-aware parallel queue executor.

"Rethinking State-Machine Replication for Parallelism" (PAPERS.md)
executes non-conflicting SMR commands concurrently because they
commute; the queue planes earn the same right from the proven per-task
effect footprints (``effects.TASK_FOOTPRINTS``, gated bidirectionally
by analysis Pass 5 + the runtime effect witness). This module is the
executor that ROADMAP item — a shared wave scheduler replacing the
one-task-at-a-time drain of ``QueueProcessorBase`` behind the
``queues.parallelism`` config gate (sequential stays the default):

* **matrix gate** — the executor consumes the versioned commutativity
  matrix artifact (``analysis --emit-conflict-matrix`` →
  ``build/queue_conflict_matrix.json``) through
  ``analysis/artifact.load_artifact``, and validates the embedded
  footprint fingerprint against the live declaration at construction.
  A missing/stale/mismatched artifact degrades LOUDLY to sequential
  scheduling: ``parqueue_matrix_stale`` counts it, a warning names the
  regeneration command, and the ``parqueue_degraded`` gauge pins at 1
  so the state can't go unnoticed forever.
* **conflict-keyed waves** — each cycle gathers one generation-stamped
  batch from every registered queue (across shards: one executor
  drains all of a host's transfer/timer pipelines in a shared
  schedule), keys every task by its workflow conflict key(s), and
  union-finds conflict groups: two tasks that share a key conflict per
  the matrix's ``same_workflow`` verdict; disjoint-key tasks conflict
  only when one side declares an *untargeted* cross-workflow fan-out
  (``xwf.*`` whose victim is not named on the task row — a
  CloseExecution's parent-close-policy sweep). Targeted ``xwf``
  types (cancel/signal/start-child) take multi-workflow keys
  {self, target} instead of serializing the whole batch.
* **ordered groups, concurrent waves** — each conflict group runs its
  tasks in read order; distinct groups run concurrently on a bounded
  worker pool. Per-task execution is the exact sequential attempt
  loop (``run_task_attempts``): effect-scope attribution, fault-
  injection hooks, defer/park and retry semantics are shared, not
  forked.
* **generation fencing** — batches are collected under the ack
  manager's read generation; a rewind (failover handover, reshard
  fence) between collect and execution rejects the stale portion of a
  wave WHOLE (``parqueue_stale_skipped``), the same discipline the
  sequential pump applies per batch.
"""

from __future__ import annotations

import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor, wait
from typing import Dict, List, Optional, Tuple

from cadence_tpu.utils.locks import make_guarded, make_lock
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP

from .effects import (
    CONFLICT_MATRIX_SCHEMA,
    build_conflict_matrix,
    footprints_fingerprint,
    plane_of,
    task_type_name,
)

# xwf effects whose victim workflow is NAMED on the task row (the
# transfer task carries target_domain_id/target_workflow_id): the
# scheduler keys the task by {self, target} instead of serializing it
# against the whole batch. Every other xwf effect (parent-close-policy
# terminate/cancel sweeps, child-close notification) targets workflows
# the task row does not name — those stay sequential against anything
# that touches workflow state.
_TARGETED_XWF = frozenset({
    "xwf.request_cancel", "xwf.signal", "xwf.start_child",
})


def ensure_conflict_matrix(path: str) -> str:
    """(Re)generate the conflict-matrix artifact at ``path`` when it is
    missing, unreadable, or fingerprint-stale against the live footprint
    table — tier-1 consumers (bench arms, chaos boxes) call this so they
    never gate on an artifact an older checkout left behind. Returns
    ``path``. The full lint emit (``scripts/run_lint.sh``) remains the
    CI-blessed writer; this helper writes the same runtime-derived
    document minus the AST-extracted ``ms_columns`` annotation."""
    from cadence_tpu.analysis import artifact

    try:
        doc = artifact.load_artifact(path, kind=CONFLICT_MATRIX_SCHEMA)
        if doc.get("fingerprint") == footprints_fingerprint():
            return path
    except Exception:
        pass
    artifact.write_artifact(path, CONFLICT_MATRIX_SCHEMA,
                            build_conflict_matrix())
    return path


class ConflictMatrix:
    """Pairwise commute/conflict verdicts, validated against the live
    footprint table. Construct via :meth:`load` (artifact path) or
    :meth:`live` (in-process, trivially fresh)."""

    def __init__(self, doc: Dict) -> None:
        fp = doc.get("fingerprint")
        live = footprints_fingerprint()
        if fp != live:
            raise ValueError(
                f"conflict matrix fingerprint {fp!r} does not match the "
                f"live footprint table ({live!r}) — regenerate with "
                "scripts/run_lint.sh (--emit-conflict-matrix)"
            )
        surfaces: Dict[str, str] = doc["surfaces"]
        # label → (touches workflow state, has untargeted xwf,
        #          has targeted xwf)
        self._types: Dict[str, Tuple[bool, bool, bool]] = {}
        for label, f in doc["footprints"].items():
            xwf = set(f["cross_workflow"])
            touches = bool(xwf) or any(
                surfaces.get(s) == "workflow"
                for s in set(f["reads"]) | set(f["writes"])
            )
            self._types[label] = (
                touches,
                bool(xwf - _TARGETED_XWF),
                bool(xwf & _TARGETED_XWF),
            )
        # unordered pair → same-workflow verdict is "conflict"
        self._same_conflict: Dict[Tuple[str, str], bool] = {}
        for p in doc["pairs"]:
            key = (p["a"], p["b"]) if p["a"] <= p["b"] else (p["b"], p["a"])
            self._same_conflict[key] = p["same_workflow"] == "conflict"

    @classmethod
    def load(cls, path: str) -> "ConflictMatrix":
        from cadence_tpu.analysis import artifact

        return cls(artifact.load_artifact(path, kind=CONFLICT_MATRIX_SCHEMA))

    @classmethod
    def live(cls) -> "ConflictMatrix":
        return cls(build_conflict_matrix())

    def known(self, label: str) -> bool:
        return label in self._types

    def touches_workflow_state(self, label: str) -> bool:
        info = self._types.get(label)
        return True if info is None else info[0]

    def untargeted_xwf(self, label: str) -> bool:
        """Whether ``label`` fans out to workflows its task row does not
        name (unknown types count: they must serialize)."""
        info = self._types.get(label)
        return True if info is None else info[1]

    def targeted_xwf(self, label: str) -> bool:
        info = self._types.get(label)
        return False if info is None else info[2]

    def same_workflow_conflict(self, a: str, b: str) -> bool:
        """Conflict verdict for two tasks sharing a workflow conflict
        key; unknown pairs conflict (safe default)."""
        key = (a, b) if a <= b else (b, a)
        return self._same_conflict.get(key, True)


class _SchedTask:
    """One collected task with its scheduling attributes."""

    __slots__ = ("slot", "task", "key", "gen", "order", "label", "keys",
                 "untargeted", "touches")

    def __init__(self, slot, task, key, gen, order, matrix: ConflictMatrix):
        self.slot = slot
        self.task = task
        self.key = key
        self.gen = gen
        self.order = order  # (queue index, read position): group order
        plane = slot.plane
        label = f"{plane}:{task_type_name(plane, getattr(task, 'task_type', ''))}" \
            if plane is not None else f"?:{getattr(task, 'task_type', '')}"
        self.label = label
        self.untargeted = matrix.untargeted_xwf(label)
        self.touches = matrix.touches_workflow_state(label)
        wf = (getattr(task, "domain_id", None),
              getattr(task, "workflow_id", None))
        keys = {wf}
        if matrix.targeted_xwf(label):
            target_wf = getattr(task, "target_workflow_id", "")
            if target_wf:
                keys.add((
                    getattr(task, "target_domain_id", "") or wf[0],
                    target_wf,
                ))
            else:
                # a targeted xwf type whose row names no victim: fall
                # back to serializing (the fan-out could land anywhere)
                self.untargeted = True
        self.keys = keys


class _Slot:
    """One registered queue processor."""

    __slots__ = ("proc", "plane")

    def __init__(self, proc) -> None:
        self.proc = proc
        self.plane = plane_of(proc.name)


class ParallelQueueExecutor:
    """Shared conflict-keyed wave scheduler over many queue pumps.

    Queues register at ``start()`` (``QueueProcessorBase`` /
    ``TimerQueueProcessor`` with ``executor=`` set); one pump thread
    then drains every registered queue in shared cycles. Sequential
    semantics are preserved group-by-group: a conflict group's tasks
    run in read order, only provably-commuting groups overlap.
    """

    def __init__(
        self,
        parallelism: int = 4,
        batch_size: int = 64,
        poll_interval_s: float = 0.05,
        matrix_path: Optional[str] = None,
        matrix: Optional[ConflictMatrix] = None,
        metrics=None,
    ) -> None:
        self._log = get_logger("cadence_tpu.queue.parallel")
        self._metrics = (metrics or NOOP).tagged(
            service="history_queue", queue="parallel"
        )
        self._parallelism = max(1, int(parallelism))
        self._batch_size = batch_size
        self._poll_interval = poll_interval_s
        self._lock = make_lock("ParallelQueueExecutor._lock")
        self._slots: List[_Slot] = make_guarded(
            [], "ParallelQueueExecutor._slots", self._lock
        )
        self._notify = threading.Event()
        self._stopped = threading.Event()
        self._started = False
        self._pool: Optional[ThreadPoolExecutor] = None
        self._pump_thread: Optional[threading.Thread] = None
        # local counters mirrored to metrics (bench/tests read these
        # without a registry round-trip)
        self.cycles = 0
        self.tasks = 0
        self.waves = 0
        self.stale_skipped = 0

        self.matrix: Optional[ConflictMatrix] = None
        self.degraded_reason: Optional[str] = None
        try:
            if matrix is not None:
                self.matrix = matrix
            elif matrix_path is not None:
                self.matrix = ConflictMatrix.load(matrix_path)
            else:
                self.matrix = ConflictMatrix.live()
        except Exception as e:
            # LOUD degrade, not silent-forever: counted, gauged, and
            # logged with the regeneration command. Scheduling falls
            # back to one sequential group per cycle.
            self.degraded_reason = f"{type(e).__name__}: {e}"
            self._metrics.inc("parqueue_matrix_stale")
            self._log.warn(
                f"conflict matrix unusable ({self.degraded_reason}) — "
                "parallel queue executor DEGRADED to sequential "
                "scheduling; regenerate the artifact with "
                "scripts/run_lint.sh"
            )
        self._metrics.gauge(
            "parqueue_degraded", 1 if self.degraded else 0
        )

    @property
    def degraded(self) -> bool:
        return self.matrix is None

    # -- registration --------------------------------------------------

    def register(self, proc) -> None:
        with self._lock:
            if all(s.proc is not proc for s in self._slots):
                self._slots.append(_Slot(proc))
            n = len(self._slots)
        self._metrics.gauge("parqueue_queues", n)
        self._notify.set()

    def unregister(self, proc) -> None:
        with self._lock:
            # guarded containers track mutations, not identity filters:
            # rebuild in place
            keep = [s for s in self._slots if s.proc is not proc]
            del self._slots[:]
            self._slots.extend(keep)
            n = len(self._slots)
        self._metrics.gauge("parqueue_queues", n)

    # -- lifecycle -----------------------------------------------------

    def start(self) -> "ParallelQueueExecutor":
        if self._started:
            return self
        self._started = True
        self._pool = ThreadPoolExecutor(
            max_workers=self._parallelism,
            thread_name_prefix="parqueue-worker",
        )
        self._pump_thread = threading.Thread(
            target=self._pump, name="parqueue-pump", daemon=True
        )
        self._pump_thread.start()
        return self

    def notify(self) -> None:
        self._notify.set()

    def stop(self) -> None:
        if self._stopped.is_set():
            return
        self._stopped.set()
        self._notify.set()
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    # -- pump ----------------------------------------------------------

    def _pump(self) -> None:
        while not self._stopped.is_set():
            try:
                worked = self._cycle()
            except Exception:
                self._log.exception("parallel queue cycle failed")
                worked = False
            if self._stopped.is_set():
                return
            if not worked:
                self._notify.wait(timeout=self._poll_interval)
                self._notify.clear()

    def _cycle(self) -> bool:
        """One shared wave cycle over every registered queue. Returns
        True when any task was collected (the pump loops immediately:
        full batches mean more work is waiting)."""
        with self._lock:
            slots = list(self._slots)
        if not slots:
            return False
        t0 = _time.perf_counter()
        matrix = self.matrix
        sched: List[_SchedTask] = []
        collected_from = []
        for qi, slot in enumerate(slots):
            try:
                batch, gen = slot.proc.parallel_collect(self._batch_size)
            except Exception:
                self._log.exception(
                    f"queue {slot.proc.name} collect failed"
                )
                continue
            if not batch:
                continue
            collected_from.append(slot)
            if matrix is None:
                for pos, (task, key) in enumerate(batch):
                    sched.append(_DegradedTask(slot, task, key, gen,
                                               (qi, pos)))
            else:
                for pos, (task, key) in enumerate(batch):
                    sched.append(_SchedTask(slot, task, key, gen,
                                            (qi, pos), matrix))
        if not sched:
            return False

        groups = self._plan(sched) if matrix is not None else [sched]
        self._execute(groups)

        self.cycles += 1
        self.tasks += len(sched)
        self.waves += len(groups)
        self._metrics.inc("parqueue_cycles")
        self._metrics.inc("parqueue_tasks", len(sched))
        self._metrics.inc("parqueue_waves", len(groups))
        self._metrics.record("parqueue_wave_width", len(groups))
        self._metrics.record(
            "parqueue_conflict_frac",
            1.0 - (len(groups) / len(sched)) if sched else 0.0,
        )
        self._metrics.record(
            "parqueue_cycle_latency", _time.perf_counter() - t0
        )
        for slot in collected_from:
            proc = slot.proc
            from .base import sweep_ack

            sweep_ack(proc.ack, self._log, proc.name)
            scope = getattr(proc, "_metrics", None)
            if scope is not None:
                scope.gauge("task_outstanding", proc.ack.outstanding())
                scope.gauge("task_held", proc.ack.held())
        return True

    # -- scheduling ----------------------------------------------------

    def _plan(self, sched: List[_SchedTask]) -> List[List[_SchedTask]]:
        """Partition one cycle's tasks into conflict groups (union-find
        over the pairwise conflict relation). Group-internal order is
        read order; distinct groups provably commute."""
        n = len(sched)
        parent = list(range(n))

        def find(i: int) -> int:
            while parent[i] != i:
                parent[i] = parent[parent[i]]
                i = parent[i]
            return i

        def union(i: int, j: int) -> None:
            ri, rj = find(i), find(j)
            if ri != rj:
                parent[rj] = ri

        matrix = self.matrix
        # (1) shared conflict keys: full pairwise check inside each key
        # bucket — buckets are per-workflow, so they stay small; a
        # union-with-last shortcut can miss an edge when a commuting
        # predecessor pair both conflict a newcomer
        buckets: Dict[object, List[int]] = {}
        for i, t in enumerate(sched):
            for k in t.keys:
                buckets.setdefault(k, []).append(i)
        for members in buckets.values():
            for ai in range(len(members)):
                for bi in range(ai + 1, len(members)):
                    a, b = sched[members[ai]], sched[members[bi]]
                    if find(members[ai]) == find(members[bi]):
                        continue
                    if matrix.same_workflow_conflict(a.label, b.label):
                        union(members[ai], members[bi])
        # (2) untargeted cross-workflow fan-out serializes against every
        # task touching workflow state, keys notwithstanding
        fanout = [i for i, t in enumerate(sched) if t.untargeted]
        if fanout:
            for i in fanout:
                for j, t in enumerate(sched):
                    if i != j and (t.touches or t.untargeted):
                        union(i, j)
        groups: Dict[int, List[_SchedTask]] = {}
        for i, t in enumerate(sched):
            groups.setdefault(find(i), []).append(t)
        out = list(groups.values())
        for g in out:
            g.sort(key=lambda t: t.order)
        out.sort(key=lambda g: g[0].order)
        return out

    # -- execution -----------------------------------------------------

    def _execute(self, groups: List[List[_SchedTask]]) -> None:
        if len(groups) == 1 or self._pool is None:
            for g in groups:
                self._run_group(g)
            return
        futures = [
            self._pool.submit(self._run_group, g) for g in groups[1:]
        ]
        self._run_group(groups[0])
        wait(futures)

    def _run_group(self, group: List[_SchedTask]) -> None:
        """One conflict group, in read order. A queue whose ack
        generation moved since collect (rewind: failover handover,
        reshard fence) has this wave's tasks rejected WHOLE — executing
        them would race the span's re-read on the new cursor."""
        stale = {}
        for t in group:
            if self._stopped.is_set():
                return
            proc = t.slot.proc
            fresh = stale.get(id(proc))
            if fresh is None:
                fresh = proc.ack.generation() == t.gen
                stale[id(proc)] = fresh
            if not fresh:
                self.stale_skipped += 1
                self._metrics.inc("parqueue_stale_skipped")
                continue
            try:
                proc.parallel_run(t.task, t.key)
            except Exception:
                self._log.exception(
                    f"queue {proc.name} task {t.key} wave execution failed"
                )


class _DegradedTask:
    """Schedule entry for degraded (matrix-less) cycles: no conflict
    attributes, everything rides one sequential group."""

    __slots__ = ("slot", "task", "key", "gen", "order")

    def __init__(self, slot, task, key, gen, order) -> None:
        self.slot = slot
        self.task = task
        self.key = key
        self.gen = gen
        self.order = order
