"""Shard controller: acquire/release shard engines on membership change.

Reference: /root/reference/service/history/shardController.go:96,148-389 —
one engine per owned shard; a management pump re-evaluates ownership on
every membership ChangedEvent, acquiring newly-owned shards and
releasing stolen ones (the new owner's lease bump fences the old one).

Elastic resharding (runtime/resharding.py): routing is an
epoch-versioned ShardMap held by the history ServiceResolver, so the
set of shard ids is no longer frozen at construction — a committed
split/merge flips the map, the resolver listeners re-fire, and
``acquire_shards`` walks the NEW id set. During the brief dual-read
window after a flip, ``get_engine`` falls back to the previous epoch's
shard handle so reads racing the flip don't error needlessly.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from cadence_tpu.utils.clock import TimeSource
from cadence_tpu.utils.log import get_logger

from .domains import DomainCache
from .engine.engine import HistoryEngine
from .membership import Monitor, ServiceResolver
from .persistence.interfaces import PersistenceBundle
from .shard import ShardContext


class ShardOwnershipLostError(Exception):
    def __init__(self, shard_id: int, owner: str) -> None:
        super().__init__(f"shard {shard_id} owned by {owner}")
        self.shard_id = shard_id
        self.owner = owner


class _ShardHandle:
    """One owned shard: context + engine + queue processors."""

    def __init__(self, shard: ShardContext, engine: HistoryEngine,
                 processors: List[object]) -> None:
        self.shard = shard
        self.engine = engine
        self.processors = processors

    def stop(self) -> None:
        for p in self.processors:
            p.stop()


class ShardController:
    def __init__(
        self,
        num_shards: int,
        persistence: PersistenceBundle,
        domain_cache: DomainCache,
        monitor: Monitor,
        engine_factory: Optional[Callable[[ShardContext], _ShardHandle]] = None,
        time_source: Optional[TimeSource] = None,
    ) -> None:
        self.initial_num_shards = num_shards
        self.persistence = persistence
        self.domains = domain_cache
        self.monitor = monitor
        self.identity = monitor.self_identity
        self._time = time_source
        self._engine_factory = engine_factory or self._default_factory
        self._lock = threading.Lock()
        self._handles: Dict[int, _ShardHandle] = {}
        self._log = get_logger("cadence_tpu.shardController", host=self.identity)
        self._resolver: ServiceResolver = monitor.resolver("history")
        self._install_shard_map(num_shards)
        self._resolver.add_listener(
            f"shardController-{self.identity}", lambda ev: self.acquire_shards()
        )

    def _install_shard_map(self, num_shards: int) -> None:
        """Adopt the durable routing map: a committed reshard outlives
        every host restart, so the store's epoch wins over both the
        constructor arg and any stale resolver state."""
        from .resharding import ShardMap, load_reshard_state

        stored, _ = load_reshard_state(self.persistence.shard)
        current = self._resolver.shard_map()
        if stored is not None and (
            current is None or stored.epoch > current.epoch
        ):
            self._resolver.set_shard_map(stored)
        elif current is None:
            self._resolver.set_shard_map(ShardMap.initial(num_shards))

    # -- ownership -----------------------------------------------------

    @property
    def shard_map(self):
        return self._resolver.shard_map()

    @property
    def num_shards(self) -> int:
        """Live shard count under the current routing epoch."""
        m = self._resolver.shard_map()
        return m.num_shards if m is not None else self.initial_num_shards

    def shard_ids(self) -> List[int]:
        m = self._resolver.shard_map()
        return (
            m.shard_ids() if m is not None
            else list(range(self.initial_num_shards))
        )

    def _owned(self, shard_id: int) -> bool:
        return self._resolver.lookup(str(shard_id)).identity == self.identity

    def shard_for(self, workflow_id: str) -> int:
        return self.shard_map.shard_for(workflow_id)

    def acquire_shards(self) -> None:
        """Re-evaluate ownership for every shard (acquireShards :279-346).
        Walks the union of the current map's ids and anything still
        held, so a merged-away shard's engine is released too."""
        with self._lock:
            held = set(self._handles)
        # one consistent view of the id set for the whole sweep (a map
        # flip mid-loop re-fires the listener and re-evaluates anyway)
        ids = set(self.shard_ids())
        for shard_id in sorted(ids | held):
            try:
                owned = shard_id in ids and self._owned(shard_id)
            except RuntimeError:
                owned = False  # empty ring
            with self._lock:
                have = shard_id in self._handles
                if owned and not have:
                    try:
                        self._handles[shard_id] = self._engine_factory(
                            self._make_shard(shard_id)
                        )
                        self._log.info(f"acquired shard {shard_id}")
                    except Exception:
                        self._log.exception(f"failed to acquire shard {shard_id}")
                elif not owned and have:
                    self._handles.pop(shard_id).stop()
                    self._log.info(f"released shard {shard_id}")

    def _make_shard(self, shard_id: int) -> ShardContext:
        return ShardContext(
            shard_id, self.persistence, owner=self.identity,
            time_source=self._time,
        )

    def _default_factory(self, shard: ShardContext) -> _ShardHandle:
        engine = HistoryEngine(shard, self.domains)
        return _ShardHandle(shard, engine, [])

    # -- engine lookup -------------------------------------------------

    def get_engine(self, workflow_id: str) -> HistoryEngine:
        current, previous = self._resolver.shard_maps()
        shard_id = (
            current.shard_for(workflow_id) if current is not None else 0
        )
        try:
            return self.get_engine_for_shard(shard_id)
        except ShardOwnershipLostError:
            # dual-read window: a read racing a reshard flip may still
            # find the outgoing epoch's handle on this host
            if previous is not None:
                prev_id = previous.shard_for(workflow_id)
                if prev_id != shard_id:
                    with self._lock:
                        handle = self._handles.get(prev_id)
                    if handle is not None:
                        return handle.engine
            raise

    def get_engine_for_shard(self, shard_id: int) -> HistoryEngine:
        with self._lock:
            handle = self._handles.get(shard_id)
        if handle is None:
            try:
                owner = self._resolver.lookup(str(shard_id)).identity
            except RuntimeError:
                owner = "<no hosts>"
            raise ShardOwnershipLostError(shard_id, owner)
        return handle.engine

    def owned_shards(self) -> List[int]:
        with self._lock:
            return sorted(self._handles)

    def describe(self) -> dict:
        """DescribeHistoryHost (service/history/handler.go:662)."""
        m = self.shard_map
        with self._lock:
            return {
                "identity": self.identity,
                "shard_count": len(self._handles),
                "shard_ids": sorted(self._handles),
                "num_shards_total": self.num_shards,
                "reshard_epoch": m.epoch if m is not None else 0,
            }

    def stop(self) -> None:
        self._resolver.remove_listener(f"shardController-{self.identity}")
        with self._lock:
            for handle in self._handles.values():
                handle.stop()
            self._handles.clear()

    def release_shard(self, shard_id: int) -> None:
        """Force-release one owned shard (admin CloseShard — reference
        shardController.removeEngineForShard)."""
        with self._lock:
            handle = self._handles.pop(shard_id, None)
        if handle is not None:
            handle.stop()
