"""Shard controller: acquire/release shard engines on membership change.

Reference: /root/reference/service/history/shardController.go:96,148-389 —
one engine per owned shard; a management pump re-evaluates ownership on
every membership ChangedEvent, acquiring newly-owned shards and
releasing stolen ones (the new owner's lease bump fences the old one).
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from cadence_tpu.utils.clock import TimeSource
from cadence_tpu.utils.hashing import shard_for_workflow
from cadence_tpu.utils.log import get_logger

from .domains import DomainCache
from .engine.engine import HistoryEngine
from .membership import Monitor, ServiceResolver
from .persistence.interfaces import PersistenceBundle
from .shard import ShardContext


class ShardOwnershipLostError(Exception):
    def __init__(self, shard_id: int, owner: str) -> None:
        super().__init__(f"shard {shard_id} owned by {owner}")
        self.shard_id = shard_id
        self.owner = owner


class _ShardHandle:
    """One owned shard: context + engine + queue processors."""

    def __init__(self, shard: ShardContext, engine: HistoryEngine,
                 processors: List[object]) -> None:
        self.shard = shard
        self.engine = engine
        self.processors = processors

    def stop(self) -> None:
        for p in self.processors:
            p.stop()


class ShardController:
    def __init__(
        self,
        num_shards: int,
        persistence: PersistenceBundle,
        domain_cache: DomainCache,
        monitor: Monitor,
        engine_factory: Optional[Callable[[ShardContext], _ShardHandle]] = None,
        time_source: Optional[TimeSource] = None,
    ) -> None:
        self.num_shards = num_shards
        self.persistence = persistence
        self.domains = domain_cache
        self.monitor = monitor
        self.identity = monitor.self_identity
        self._time = time_source
        self._engine_factory = engine_factory or self._default_factory
        self._lock = threading.Lock()
        self._handles: Dict[int, _ShardHandle] = {}
        self._log = get_logger("cadence_tpu.shardController", host=self.identity)
        self._resolver: ServiceResolver = monitor.resolver("history")
        self._resolver.add_listener(
            f"shardController-{self.identity}", lambda ev: self.acquire_shards()
        )

    # -- ownership -----------------------------------------------------

    def _owned(self, shard_id: int) -> bool:
        return self._resolver.lookup(str(shard_id)).identity == self.identity

    def shard_for(self, workflow_id: str) -> int:
        return shard_for_workflow(workflow_id, self.num_shards)

    def acquire_shards(self) -> None:
        """Re-evaluate ownership for every shard (acquireShards :279-346)."""
        for shard_id in range(self.num_shards):
            try:
                owned = self._owned(shard_id)
            except RuntimeError:
                owned = False  # empty ring
            with self._lock:
                have = shard_id in self._handles
                if owned and not have:
                    try:
                        self._handles[shard_id] = self._engine_factory(
                            self._make_shard(shard_id)
                        )
                        self._log.info(f"acquired shard {shard_id}")
                    except Exception:
                        self._log.exception(f"failed to acquire shard {shard_id}")
                elif not owned and have:
                    self._handles.pop(shard_id).stop()
                    self._log.info(f"released shard {shard_id}")

    def _make_shard(self, shard_id: int) -> ShardContext:
        return ShardContext(
            shard_id, self.persistence, owner=self.identity,
            time_source=self._time,
        )

    def _default_factory(self, shard: ShardContext) -> _ShardHandle:
        engine = HistoryEngine(shard, self.domains)
        return _ShardHandle(shard, engine, [])

    # -- engine lookup -------------------------------------------------

    def get_engine(self, workflow_id: str) -> HistoryEngine:
        return self.get_engine_for_shard(self.shard_for(workflow_id))

    def get_engine_for_shard(self, shard_id: int) -> HistoryEngine:
        with self._lock:
            handle = self._handles.get(shard_id)
        if handle is None:
            try:
                owner = self._resolver.lookup(str(shard_id)).identity
            except RuntimeError:
                owner = "<no hosts>"
            raise ShardOwnershipLostError(shard_id, owner)
        return handle.engine

    def owned_shards(self) -> List[int]:
        with self._lock:
            return sorted(self._handles)

    def describe(self) -> dict:
        """DescribeHistoryHost (service/history/handler.go:662)."""
        with self._lock:
            return {
                "identity": self.identity,
                "shard_count": len(self._handles),
                "shard_ids": sorted(self._handles),
                "num_shards_total": self.num_shards,
            }

    def stop(self) -> None:
        self._resolver.remove_listener(f"shardController-{self.identity}")
        with self._lock:
            for handle in self._handles.values():
                handle.stop()
            self._handles.clear()

    def release_shard(self, shard_id: int) -> None:
        """Force-release one owned shard (admin CloseShard — reference
        shardController.removeEngineForShard)."""
        with self._lock:
            handle = self._handles.pop(shard_id, None)
        if handle is not None:
            handle.stop()
