"""Elastic resharding: online shard split/merge under live traffic.

Following "Reconfigurable State Machine Replication from
Non-Reconfigurable Building Blocks" (PAPERS.md), reconfiguration is
layered ON TOP of the static shard substrate instead of baked into it:
each reconfiguration epoch is a write-ahead ``ReshardPlan`` executed by
a coordinator against building blocks that individually know nothing
about elasticity — range-fenced shard leases, drainable queue pumps,
and the checkpoint store.

The routing function is an epoch-versioned :class:`ShardMap`: a
partition of the 32-bit workflow-hash space into residue classes
``hash % modulus == residue``, each owned by one shard id. The initial
map (``residue i mod N -> shard i``) routes byte-identically to the
legacy ``shard_for_workflow(wid, N)``; a **split** halves one shard's
classes (doubling their modulus), a **merge** repoints a shard's
classes at a sibling — both change only the affected shards' keyspace,
never the whole cluster's (no global rehash).

Handoff protocol per epoch (the coordinator, one reconfiguration at a
time):

1. persist the plan (``persistence.shard.set_reshard_state`` — the
   write-ahead record; it rides ``wrap_bundle(faults=...)`` so chaos
   rules can kill any step);
2. pause + drain the affected shards' queue pumps to a recorded ack
   watermark (``fence_drain``), then fence the shard contexts (lease
   bump + write refusal: a fenced shard can never mint regressing task
   IDs) and flush ``ReplayCheckpoint`` snapshots for every open
   workflow on a source shard;
3. move the affected workflows' execution/current rows and the queue
   tasks past the watermark to their target shards (checkpoints — not
   event histories — are what the new owner warms from; suffix-only
   replay rides the existing resume path);
4. commit the new map under an epoch LWT, flip every host's resolver
   (brief dual-read window), let controllers re-acquire, warm the new
   owners from the shipped checkpoints, and retire the old map.

A failure at any step rolls back: moved rows return to their source
shards, the plan is marked ABORTED (same epoch LWT), and controllers
re-acquire under the old map — the old epoch's fences were lease
bumps, so rollback never regresses a range_id.
"""

from __future__ import annotations

import dataclasses
import json
import threading
import time
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple

from cadence_tpu.utils.hashing import fnv1a32
from cadence_tpu.utils.locks import make_lock
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP

from .persistence.errors import ConditionFailedError, EntityNotExistsError
from .shard import ShardContext


class ReshardError(RuntimeError):
    """A reconfiguration step failed; the coordinator rolled back."""


# --------------------------------------------------------------------------
# ShardMap — epoch-versioned routing
# --------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ShardMap:
    """A partition of the workflow-hash space into residue classes.

    ``entries``: tuples ``(residue, modulus, shard_id)`` — workflow w
    routes to the entry with ``fnv1a32(w) % modulus == residue``.
    Entries always partition the space (``validate``), so lookup is
    total and unambiguous.
    """

    epoch: int
    entries: Tuple[Tuple[int, int, int], ...]

    @classmethod
    def initial(cls, num_shards: int) -> "ShardMap":
        """Epoch-0 map routing identically to the legacy
        ``shard_for_workflow(wid, num_shards)``."""
        if num_shards < 1:
            raise ValueError("num_shards must be >= 1")
        return cls(
            epoch=0,
            entries=tuple((i, num_shards, i) for i in range(num_shards)),
        )

    # -- lookup --------------------------------------------------------

    def shard_for(self, workflow_id: str) -> int:
        return self.shard_for_hash(fnv1a32(workflow_id))

    def shard_for_hash(self, h: int) -> int:
        for residue, modulus, shard_id in self.entries:
            if h % modulus == residue:
                return shard_id
        raise RuntimeError(f"shard map does not cover hash {h}")  # validate()d away

    def shard_ids(self) -> List[int]:
        return sorted({s for _, _, s in self.entries})

    @property
    def num_shards(self) -> int:
        return len({s for _, _, s in self.entries})

    # -- reconfiguration -----------------------------------------------

    def split(self, shard_id: int,
              new_id: Optional[int] = None) -> Tuple["ShardMap", int]:
        """Halve ``shard_id``'s keyspace into (itself, a fresh shard id).
        Returns ``(new_map, new_shard_id)``. ``new_id`` lets the
        coordinator mint ids that were never used before (even by an
        aborted plan), so stale rows from a failed cleanup can never be
        resurrected by id reuse."""
        owned = [e for e in self.entries if e[2] == shard_id]
        if not owned:
            raise ValueError(f"shard {shard_id} not in map")
        if new_id is None:
            new_id = max(self.shard_ids()) + 1
        elif new_id in self.shard_ids():
            raise ValueError(f"shard id {new_id} already in map")
        entries = [e for e in self.entries if e[2] != shard_id]
        for residue, modulus, _ in owned:
            entries.append((residue, 2 * modulus, shard_id))
            entries.append((residue + modulus, 2 * modulus, new_id))
        m = ShardMap(epoch=self.epoch + 1, entries=tuple(sorted(entries)))
        m.validate()
        return m, new_id

    def merge(self, source_id: int, target_id: int) -> "ShardMap":
        """Repoint every class of ``source_id`` at ``target_id``; the
        source shard id leaves the map."""
        if source_id == target_id:
            raise ValueError("merge source == target")
        if not any(e[2] == source_id for e in self.entries):
            raise ValueError(f"shard {source_id} not in map")
        if not any(e[2] == target_id for e in self.entries):
            raise ValueError(f"shard {target_id} not in map")
        entries = tuple(sorted(
            (r, m, target_id if s == source_id else s)
            for r, m, s in self.entries
        ))
        m = ShardMap(epoch=self.epoch + 1, entries=entries)
        m.validate()
        return m

    def validate(self) -> None:
        """The entries must partition the hash space: total coverage
        (measures sum to 1) and pairwise disjoint residue classes."""
        if not self.entries:
            raise ValueError("empty shard map")
        total = sum(Fraction(1, m) for _, m, _ in self.entries)
        if total != 1:
            raise ValueError(f"shard map covers {total} of the hash space")
        import math

        es = self.entries
        for i in range(len(es)):
            r1, m1, _ = es[i]
            if not 0 <= r1 < m1:
                raise ValueError(f"residue {r1} out of range for mod {m1}")
            for j in range(i + 1, len(es)):
                r2, m2, _ = es[j]
                if (r1 - r2) % math.gcd(m1, m2) == 0:
                    raise ValueError(
                        f"overlapping classes ({r1},{m1}) and ({r2},{m2})"
                    )

    # -- serde ---------------------------------------------------------

    def to_dict(self) -> dict:
        return {"epoch": self.epoch,
                "entries": [list(e) for e in self.entries]}

    @classmethod
    def from_dict(cls, d: dict) -> "ShardMap":
        return cls(
            epoch=int(d["epoch"]),
            entries=tuple(tuple(int(x) for x in e) for e in d["entries"]),
        )


# --------------------------------------------------------------------------
# ReshardPlan — the write-ahead record
# --------------------------------------------------------------------------

PLAN_PREPARED = "PREPARED"
PLAN_FENCED = "FENCED"
PLAN_COMMITTED = "COMMITTED"
PLAN_ABORTED = "ABORTED"


@dataclasses.dataclass
class ReshardPlan:
    """One reconfiguration epoch's durable record (old map -> new map).

    Persisted through ``ShardManager.set_reshard_state`` before any
    state moves; every later step updates ``state`` in place under the
    same epoch LWT, so a crashed coordinator's successor (``recover``)
    knows exactly how far the handoff got — and anything short of
    COMMITTED rolls back to ``epoch_from``.
    """

    kind: str                      # "split" | "merge"
    epoch_from: int
    epoch_to: int
    map_from: dict                 # ShardMap.to_dict()
    map_to: dict
    sources: List[int]             # shards losing workflows
    targets: List[int]             # shards gaining workflows
    state: str = PLAN_PREPARED
    watermarks: Dict[str, dict] = dataclasses.field(default_factory=dict)
    moved_workflows: int = 0
    moved_tasks: int = 0
    checkpoints_shipped: int = 0
    suffix_events_replayed: int = 0
    handoff_ms: float = 0.0
    # the write-unavailability window: fence-drain start → engines
    # re-acquired under the new epoch (handoff_ms minus the pre-fence
    # checkpoint flush, which runs under live traffic)
    pause_ms: float = 0.0
    error: str = ""

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "ReshardPlan":
        return cls(**d)


def _state_blob(shard_map: ShardMap, plan: Optional[ReshardPlan],
                max_shard_id: int = 0) -> str:
    return json.dumps({
        "map": shard_map.to_dict(),
        "plan": plan.to_dict() if plan is not None else None,
        # monotone high-water mark over every shard id EVER minted —
        # including by aborted plans whose target cleanup failed; ids
        # are never reused, so stale rows can never be resurrected
        "max_shard_id": max_shard_id,
    }, sort_keys=True)


def load_reshard_state(shard_manager):
    """(ShardMap, in-flight ReshardPlan) from the store, or (None, None)
    when no reconfiguration was ever committed. Never raises — a broken
    store reads as 'no state' (the epoch-0 default map)."""
    try:
        row = shard_manager.get_reshard_state()
    except Exception:
        return None, None
    if row is None:
        return None, None
    epoch, blob = row
    try:
        d = json.loads(blob)
        shard_map = ShardMap.from_dict(d["map"])
        plan = (
            ReshardPlan.from_dict(d["plan"])
            if d.get("plan") is not None else None
        )
        return shard_map, plan
    except Exception:
        return None, None


# --------------------------------------------------------------------------
# Coordinator
# --------------------------------------------------------------------------


class ReshardCoordinator:
    """Executes shard split/merge + host rebalancing across the given
    in-process controllers (one per history host). One reconfiguration
    at a time; the plan row is the write-ahead record.

    ``controllers``: every host's ShardController. The coordinator
    pauses/drains the affected shards wherever they live, moves the
    rows, flips each host's resolver, and triggers re-acquisition.
    Cross-process deployments drive the same steps through each host's
    admin endpoint (see README "Elastic resharding").
    """

    def __init__(
        self,
        persistence,
        controllers: Sequence,
        metrics=None,
        drain_timeout_s: float = 10.0,
        checkpoint_flush: bool = True,
        time_source=None,
        on_step=None,
    ) -> None:
        self.persistence = persistence
        self.controllers = list(controllers)
        self.drain_timeout_s = drain_timeout_s
        self.checkpoint_flush = checkpoint_flush
        self._time = time_source
        # chaos hook: called with the protocol step name just completed
        # ("prepared" / "flushed" / "fenced" / "moved" / "committed") —
        # the reshard chaos family kills hosts between exact steps
        self._on_step = on_step or (lambda step: None)
        self.metrics = (metrics if metrics is not None else NOOP).tagged(
            layer="resharding"
        )
        self._lock = make_lock("ReshardCoordinator._lock")
        # in-process cache of the durable shard-id high-water mark
        self._max_shard_id = 0
        self._log = get_logger("cadence_tpu.resharding")

    # -- public API ----------------------------------------------------

    def current_map(self) -> ShardMap:
        stored, _ = load_reshard_state(self.persistence.shard)
        if stored is not None:
            return stored
        return self._resolver_map()

    def split(self, shard_id: int) -> ReshardPlan:
        """Split ``shard_id`` 1→2 online; returns the committed plan."""
        with self._lock:
            old_map = self.current_map()
            new_map, new_id = old_map.split(
                shard_id, new_id=self._fresh_shard_id(old_map)
            )
            plan = ReshardPlan(
                kind="split",
                epoch_from=old_map.epoch, epoch_to=new_map.epoch,
                map_from=old_map.to_dict(), map_to=new_map.to_dict(),
                sources=[shard_id], targets=[new_id],
            )
            return self._execute(old_map, new_map, plan)

    def merge(self, source_id: int, target_id: int) -> ReshardPlan:
        """Merge ``source_id`` into ``target_id`` 2→1 online."""
        with self._lock:
            old_map = self.current_map()
            new_map = old_map.merge(source_id, target_id)
            plan = ReshardPlan(
                kind="merge",
                epoch_from=old_map.epoch, epoch_to=new_map.epoch,
                map_from=old_map.to_dict(), map_to=new_map.to_dict(),
                sources=[source_id], targets=[target_id],
            )
            return self._execute(old_map, new_map, plan)

    def _fresh_shard_id(self, shard_map: ShardMap) -> int:
        """A shard id never used before — by the current map OR by any
        plan ever recorded (including an ABORTED one whose target-side
        cleanup may have failed, leaving stale rows under the old id;
        reusing it could resurrect them over live state). The durable
        ``max_shard_id`` high-water mark makes this monotone across
        plans and restarts."""
        used = set(shard_map.shard_ids())
        _, plan = load_reshard_state(self.persistence.shard)
        if plan is not None:
            used.update(plan.sources)
            used.update(plan.targets)
            used.update(ShardMap.from_dict(plan.map_to).shard_ids())
        return max(max(used), self._stored_max_shard_id()) + 1

    def _stored_max_shard_id(self) -> int:
        best = self._max_shard_id
        try:
            row = self.persistence.shard.get_reshard_state()
            if row is not None:
                best = max(
                    best, int(json.loads(row[1]).get("max_shard_id", 0))
                )
        except Exception:
            pass
        self._max_shard_id = best
        return best

    def rebalance(self) -> None:
        """Host add/remove: re-evaluate ring ownership everywhere (the
        ring listeners normally do this; explicit for orchestrators)."""
        for c in self.controllers:
            c.acquire_shards()

    def recover(self) -> Optional[ReshardPlan]:
        """Roll back an in-flight plan left by a crashed coordinator
        (the write-ahead contract: anything short of COMMITTED aborts).
        Returns the aborted plan, or None when the store is clean."""
        with self._lock:
            stored_map, plan = load_reshard_state(self.persistence.shard)
            if plan is None or plan.state in (PLAN_COMMITTED, PLAN_ABORTED):
                return None
            old_map = ShardMap.from_dict(plan.map_from)
            new_map = ShardMap.from_dict(plan.map_to)
            self._rollback_moves(old_map, new_map, plan)
            plan.state = PLAN_ABORTED
            plan.error = plan.error or "coordinator crashed mid-handoff"
            self._persist(old_map, plan)
            for c in self.controllers:
                self._set_resolver_map(c, old_map, previous=None)
                c.acquire_shards()
            self.metrics.inc("reshard_rollbacks")
            return plan

    def status(self) -> dict:
        shard_map = self.current_map()
        _, plan = load_reshard_state(self.persistence.shard)
        return {
            "epoch": shard_map.epoch,
            "shard_ids": shard_map.shard_ids(),
            "entries": shard_map.to_dict()["entries"],
            "last_plan": plan.to_dict() if plan is not None else None,
        }

    # -- resolver plumbing ---------------------------------------------

    def _resolver_map(self) -> ShardMap:
        for c in self.controllers:
            m = c.shard_map
            if m is not None:
                return m
        raise ReshardError("no controllers with a shard map")

    @staticmethod
    def _set_resolver_map(controller, shard_map, previous) -> None:
        controller._resolver.set_shard_map(shard_map, previous=previous)

    # -- protocol steps ------------------------------------------------

    def _persist(self, shard_map: ShardMap, plan: Optional[ReshardPlan],
                 previous_epoch: Optional[int] = None) -> None:
        """Write the plan/map row, surviving torn writes: a write whose
        ack was lost LANDED — re-reading the row and finding exactly
        our payload is success (including the ConditionFailed a retry
        of a landed epoch bump produces)."""
        epoch = shard_map.epoch
        self._max_shard_id = max(
            [self._max_shard_id] + shard_map.shard_ids()
            + (plan.sources + plan.targets if plan is not None else [])
        )
        blob = _state_blob(shard_map, plan, self._max_shard_id)
        prev = epoch if previous_epoch is None else previous_epoch
        last_exc = None
        for _ in range(3):
            try:
                self.persistence.shard.set_reshard_state(
                    epoch, blob, previous_epoch=prev
                )
                return
            except Exception as e:
                last_exc = e
                try:
                    if self.persistence.shard.get_reshard_state() == (
                        epoch, blob
                    ):
                        return  # our torn write landed
                except Exception:
                    pass
                if isinstance(e, ConditionFailedError):
                    raise  # a competing coordinator really won
        raise last_exc

    def _owning_controller(self, shard_id: int):
        for c in self.controllers:
            if shard_id in c.owned_shards():
                return c
        return None

    def _affected_handles(self, plan: ReshardPlan):
        """(controller, handle) per affected live shard. A shard nobody
        owns (its host died) has nothing to pause — the fence at move
        time still protects it via the lease bump."""
        out = []
        for shard_id in sorted(set(plan.sources + plan.targets)):
            c = self._owning_controller(shard_id)
            if c is None:
                continue
            with c._lock:
                handle = c._handles.get(shard_id)
            if handle is not None:
                out.append((c, shard_id, handle))
        return out

    def _drain_and_fence(self, plan: ReshardPlan, handles) -> None:
        deadline = time.monotonic() + self.drain_timeout_s
        for _, shard_id, handle in handles:
            marks = {}
            for p in handle.processors:
                if not hasattr(p, "fence_drain"):
                    continue
                mark = p.fence_drain(deadline)
                marks[getattr(p, "name", type(p).__name__)] = (
                    list(mark) if isinstance(mark, tuple) else mark
                )
            plan.watermarks[str(shard_id)] = marks
        for _, _, handle in handles:
            handle.shard.fence()

    # -- checkpoint shipping -------------------------------------------

    def _checkpoint_manager(self):
        store = getattr(self.persistence, "checkpoint", None)
        if store is None or not self.checkpoint_flush:
            return None
        from cadence_tpu.checkpoint import CheckpointManager, CheckpointPolicy

        # every_events=1: the handoff must snapshot every workflow at
        # its tip, whatever the serving-path cadence is
        return CheckpointManager(
            store, CheckpointPolicy(every_events=1, keep_last=2)
        )

    @staticmethod
    def _is_open(snap: dict) -> bool:
        ex = snap.get("execution_info") or snap.get("exec") or {}
        return int(ex.get("state", 0)) != 2  # WorkflowState.Completed

    def _rebuild_requests(self, shard_id: int, workflow_ids) -> list:
        """RebuildRequests for the current run of every OPEN workflow
        given, on ``shard_id`` (branch token + version-history items
        straight from the execution snapshot). Closed runs are skipped:
        they move with the shard but nobody replays them on the hot
        path, so flushing/warming them would stretch the handoff for
        nothing."""
        from .replication.rebuilder import RebuildRequest

        execution = self.persistence.execution
        reqs = []
        for domain_id, wf_id, run_id in workflow_ids:
            try:
                resp = execution.get_workflow_execution(
                    shard_id, domain_id, wf_id, run_id
                )
            except EntityNotExistsError:
                continue
            snap = resp.snapshot or {}
            if not self._is_open(snap):
                continue
            raw = snap.get("execution_info", {}).get("branch_token", "")
            if isinstance(raw, str):
                raw = raw.encode()
            if not raw:
                continue
            vh = snap.get("version_histories") or {}
            histories = vh.get("histories", [])
            items = None
            if histories:
                cur = histories[vh.get("current_index", 0)]
                items = [tuple(i) for i in cur.get("items", [])]
            reqs.append(RebuildRequest(
                domain_id=domain_id, workflow_id=wf_id, run_id=run_id,
                branch_token=raw, version_history_items=items,
            ))
        return reqs

    def _flush_checkpoints(self, plan: ReshardPlan, moved) -> None:
        """Snapshot every moving workflow at its tip so the new owner
        rehydrates from checkpoints, never from full event streams."""
        mgr = self._checkpoint_manager()
        if mgr is None:
            return
        from .replication.rebuilder import StateRebuilder

        rb = StateRebuilder(
            self.persistence.history, checkpoints=mgr, metrics=NOOP
        )
        for shard_id, rows in moved.items():
            reqs = self._rebuild_requests(shard_id, rows)
            if not reqs:
                continue
            rb.rebuild_many(reqs)
            plan.checkpoints_shipped += len(reqs)
        self.metrics.inc("checkpoints_shipped", plan.checkpoints_shipped)

    def _warm_new_owners(self, plan: ReshardPlan, moved_by_target) -> None:
        """Rehydrate moved workflows on their target shards from the
        shipped checkpoints + suffix-only replay; counts the events the
        checkpoints saved vs the suffix events actually replayed."""
        mgr = self._checkpoint_manager()
        if mgr is None:
            return
        from cadence_tpu.utils.metrics import Scope

        from .replication.rebuilder import StateRebuilder

        warm_scope = Scope()
        rb = StateRebuilder(
            self.persistence.history, checkpoints=mgr, metrics=warm_scope
        )
        total_events = 0
        for target, rows in sorted(moved_by_target.items()):
            reqs = self._rebuild_requests(target, rows)
            if not reqs:
                continue
            for ms, _, _ in rb.rebuild_many(reqs):
                total_events += max(0, int(ms.next_event_id) - 1)
        saved = int(
            warm_scope.registry.counter_value("events_replayed_saved") or 0
        )
        # everything a shipped checkpoint covered was NOT re-read; the
        # remainder is the suffix the resume path actually replayed —
        # the "no full-history shipping" proof the chaos suite asserts
        plan.suffix_events_replayed = max(0, total_events - saved)
        self.metrics.inc("suffix_events_replayed", plan.suffix_events_replayed)
        self.metrics.inc("events_replayed_saved", saved)

    # -- row movement --------------------------------------------------

    def _moving_rows(self, old_map: ShardMap, new_map: ShardMap,
                     source: int):
        """(domain, wf, run) rows leaving ``source``, grouped by their
        target shard under ``new_map``."""
        by_target: Dict[int, list] = {}
        for domain_id, wf_id, run_id in (
            self.persistence.execution.list_concrete_executions(source)
        ):
            target = new_map.shard_for(wf_id)
            if target != source:
                by_target.setdefault(target, []).append(
                    (domain_id, wf_id, run_id)
                )
        return by_target

    def _temp_context(self, shard_id: int) -> ShardContext:
        """Coordinator-owned lease on a target shard (creates the shard
        row for a brand-new split target; the bump fences any stale
        writer until the real owner re-acquires)."""
        return ShardContext(
            shard_id, self.persistence, owner="reshard-coordinator",
            time_source=self._time,
        )

    def _move(self, old_map: ShardMap, new_map: ShardMap,
              plan: ReshardPlan, journal: list) -> Dict[int, list]:
        """Move every affected row (copy → install → purge: the source
        keeps its rows until the target copy durably landed, so a crash
        in ANY window leaves a recoverable state — at worst a duplicate
        copy the rollback sweep deletes). Returns target -> moved rows;
        appends ``[source, target, extracted, purged]`` journal entries
        so a failure can undo exactly what moved."""
        execution = self.persistence.execution
        moved_by_target: Dict[int, list] = {}
        for source in plan.sources:
            marks = plan.watermarks.get(str(source), {})
            transfer_mark, timer_mark = _queue_watermarks(source, marks)
            for target, rows in sorted(
                self._moving_rows(old_map, new_map, source).items()
            ):
                ctx = self._temp_context(target)
                wids = sorted({w for _, w, _ in rows})
                extracted = execution.reshard_extract(
                    source, wids,
                    transfer_watermark=transfer_mark,
                    timer_watermark=timer_mark,
                )
                entry = [source, target, extracted, False]
                journal.append(entry)
                execution.reshard_install(
                    target, ctx.range_id, extracted, ctx.next_task_id
                )
                execution.reshard_purge(source, extracted)
                entry[3] = True
                self._rewind_target_acks(ctx, extracted)
                plan.moved_workflows += len(extracted["executions"])
                plan.moved_tasks += (
                    len(extracted["transfer"]) + len(extracted["timers"])
                    + len(extracted["replication"])
                )
                moved_by_target.setdefault(target, []).extend(rows)
        return moved_by_target

    @staticmethod
    def _rewind_target_acks(ctx: ShardContext, extracted) -> None:
        """Moved timers keep their firing time: the target's timer
        cursors must sit at/below the earliest moved deadline or the
        pump would never read it."""
        timers = extracted.get("timers") or []
        if not timers:
            return
        min_ts = min(t.visibility_timestamp for t in timers)
        if ctx.get_timer_ack_level() > min_ts:
            ctx.update_timer_ack_level(min_ts)
        for cluster in list(ctx._info.cluster_timer_ack_level):
            if ctx.get_cluster_timer_ack_level(cluster) > min_ts:
                ctx.update_cluster_timer_ack_level(cluster, min_ts)

    def _rollback_moves(self, old_map: ShardMap, new_map: ShardMap,
                        plan: ReshardPlan, journal=None) -> None:
        """Undo the copy-then-purge moves. With a journal (in-process
        failure): delete the target copies, and reinstall on the source
        only the entries whose purge already ran (otherwise the source
        never lost its rows — reinstalling would duplicate queue
        tasks). Without one (crash recovery): sweep the new map's
        targets for rows that belong elsewhere under the OLD map,
        delete duplicates, move back orphans."""
        execution = self.persistence.execution
        if journal:
            for source, target, extracted, purged in reversed(journal):
                wids = sorted({
                    s["workflow_id"] for s in extracted["executions"]
                })
                back = {"executions": []}
                for attempt in range(2):
                    try:
                        # remove whatever landed on the target (empty
                        # when the install never happened — idempotent)
                        back = execution.reshard_extract(
                            target, wids,
                            transfer_watermark=0, timer_watermark=(0, 0),
                            delete=True,
                        )
                        break
                    except Exception:
                        if attempt:
                            # stale copies may remain on the target;
                            # harmless while its id stays out of the
                            # map, and _fresh_shard_id never re-mints
                            # it (resurrection-proof)
                            self._log.exception(
                                f"rollback cleanup of shard {target} "
                                "failed; stale copies may remain"
                            )
                if purged:
                    ctx = self._temp_context(source)
                    restore = back if back["executions"] else extracted
                    execution.reshard_install(
                        source, ctx.range_id, restore, ctx.next_task_id
                    )
            return
        # crash recovery: no journal — sweep targets for misplaced rows
        for target in set(ShardMap.from_dict(plan.map_to).shard_ids()):
            rows = []
            try:
                rows = execution.list_concrete_executions(target)
            except Exception:
                continue
            misplaced: Dict[int, list] = {}
            for domain_id, wf_id, run_id in rows:
                want = old_map.shard_for(wf_id)
                if want != target:
                    misplaced.setdefault(want, []).append(
                        (domain_id, wf_id, run_id)
                    )
            for source, rows3 in sorted(misplaced.items()):
                ctx = self._temp_context(source)
                extracted = execution.reshard_extract(
                    target, sorted({w for _, w, _ in rows3}),
                    transfer_watermark=0, timer_watermark=(0, 0),
                    delete=True,
                )
                # a crash between install and purge leaves the row on
                # BOTH shards: the source copy wins, the target copy
                # (just deleted) is discarded; orphans move back
                orphans = {
                    k: list(v) if isinstance(v, list) else v
                    for k, v in extracted.items()
                }
                keep = []
                for e in extracted["executions"]:
                    try:
                        execution.get_workflow_execution(
                            source, e["domain_id"], e["workflow_id"],
                            e["run_id"],
                        )
                    except EntityNotExistsError:
                        keep.append(e)
                if not keep:
                    continue
                kept_wids = {e["workflow_id"] for e in keep}
                orphans["executions"] = keep
                orphans["currents"] = [
                    c for c in extracted["currents"]
                    if c["workflow_id"] in kept_wids
                ]
                for q in ("transfer", "timers", "replication"):
                    orphans[q] = [
                        t for t in extracted[q]
                        if t.workflow_id in kept_wids
                    ]
                execution.reshard_install(
                    source, ctx.range_id, orphans, ctx.next_task_id
                )

    # -- the protocol --------------------------------------------------

    def _execute(self, old_map: ShardMap, new_map: ShardMap,
                 plan: ReshardPlan) -> ReshardPlan:
        t0 = time.perf_counter()
        journal: list = []
        handles = []
        moved_by_target: Dict[int, list] = {}
        try:
            # 1. write-ahead plan row (LWT on the OLD epoch)
            self._persist(old_map, plan, previous_epoch=old_map.epoch)
            self._on_step("prepared")

            # 2a. snapshot moving workflows while traffic still flows —
            #     suffix-only replay covers anything written after the
            #     snapshot, so flushing pre-fence keeps the JIT/compile
            #     cost OUT of the write-unavailability window
            moving = {
                s: [r for rows in
                    self._moving_rows(old_map, new_map, s).values()
                    for r in rows]
                for s in plan.sources
            }
            self._flush_checkpoints(plan, moving)
            self._on_step("flushed")

            # 2b. quiesce: pause intake, drain in-flight work to the
            #     ack watermark, fence the leases (the pause starts HERE)
            t_fence = time.perf_counter()
            handles = self._affected_handles(plan)
            self._drain_and_fence(plan, handles)
            plan.state = PLAN_FENCED
            self._persist(old_map, plan, previous_epoch=old_map.epoch)
            self._on_step("fenced")

            # 3. stop the affected shards' engines, move the rows
            for c, shard_id, _ in handles:
                c.release_shard(shard_id)
            moved_by_target = self._move(old_map, new_map, plan, journal)
            self._on_step("moved")

            # 4. commit: epoch LWT flips the durable routing truth
            plan.state = PLAN_COMMITTED
            plan.handoff_ms = (time.perf_counter() - t0) * 1e3
            self._persist(new_map, plan, previous_epoch=old_map.epoch)
            self._on_step("committed")
        except Exception as e:
            self._log.exception(
                f"reshard {plan.kind} epoch {plan.epoch_to} failed; "
                "rolling back"
            )
            plan.state = PLAN_ABORTED
            plan.error = f"{type(e).__name__}: {e}"
            try:
                self._rollback_moves(old_map, new_map, plan, journal)
            finally:
                # a fence is permanent on its context (the flag never
                # clears), so every affected handle must be RELEASED —
                # re-acquisition below builds fresh, unfenced contexts
                # under new leases; merely unpausing a fenced handle
                # would brick its shard until host restart
                for c, shard_id, _ in handles:
                    try:
                        c.release_shard(shard_id)
                    except Exception:
                        self._log.exception(
                            f"release of shard {shard_id} failed in "
                            "rollback"
                        )
                for c in self.controllers:
                    self._set_resolver_map(c, old_map, previous=None)
                    c.acquire_shards()
            try:
                self._persist(old_map, plan, previous_epoch=old_map.epoch)
            except Exception:
                self._log.exception("reshard abort record write failed")
            self.metrics.inc("reshard_rollbacks")
            raise ReshardError(plan.error) from e

        # 5. flip every host's resolver (brief dual-read window), let
        #    controllers re-acquire under the new epoch, warm the new
        #    owners from the shipped checkpoints, retire the old map
        for c in self.controllers:
            self._set_resolver_map(c, new_map, previous=old_map)
        for c in self.controllers:
            c.acquire_shards()
        plan.pause_ms = (time.perf_counter() - t_fence) * 1e3
        try:
            # warm is an optimization: a failing checkpoint plane must
            # not wedge a COMMITTED reconfiguration (cold reads work)
            self._warm_new_owners(plan, moved_by_target)
        except Exception:
            self._log.exception("post-commit checkpoint warm failed")
        for c in self.controllers:
            c._resolver.retire_previous_shard_map()
        plan.handoff_ms = (time.perf_counter() - t0) * 1e3
        try:
            self._persist(new_map, plan, previous_epoch=new_map.epoch)
        except Exception:
            pass  # commit already durable; the update is bookkeeping
        self.metrics.gauge("reshard_epoch", new_map.epoch)
        self.metrics.record("handoff_ms", plan.handoff_ms)
        self.metrics.record("reshard_pause_ms", plan.pause_ms)
        self.metrics.inc("reshard_commits")
        self._log.info(
            f"reshard {plan.kind} committed: epoch "
            f"{plan.epoch_from}->{plan.epoch_to}, "
            f"{plan.moved_workflows} workflows / {plan.moved_tasks} tasks "
            f"moved in {plan.handoff_ms:.1f}ms"
        )
        return plan


def _queue_watermarks(source: int, marks: dict):
    """(transfer watermark, timer watermark) for one drained source
    shard; missing pumps (unowned shard) read as 'move everything'.
    The MINIMUM across active + standby pumps wins: a standby cursor
    behind the active one means those tasks are not yet standby-
    verified — they move with the shard and re-verify on the target
    (idempotent handlers), rather than being stranded behind a
    watermark only the active plane crossed."""
    transfer_marks = [
        mark for name, mark in marks.items()
        if name.startswith("transfer-") and isinstance(mark, int)
    ]
    timer_marks = [
        tuple(mark) for name, mark in marks.items()
        if name.startswith("timer-") and isinstance(mark, (list, tuple))
    ]
    return (
        min(transfer_marks) if transfer_marks else 0,
        min(timer_marks) if timer_marks else (0, 0),
    )
