"""Membership: host ring with consistent-hash lookup + change listeners.

Reference: /root/reference/common/membership/interfaces.go:49-79
(Monitor / ServiceResolver) over ringpop SWIM gossip
(rpMonitor.go:44, rpServiceResolver.go:45). In this build the gossip
plane is replaced by an explicitly-driven host set (the onebox test
strategy, /root/reference/host/simpleMonitor.go): hosts join/leave via
API calls, listeners fire on change, and Lookup hashes keys onto a
replicated consistent-hash ring. Multi-host deployments drive the same
API from their orchestrator (k8s endpoints watch, etc.).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Callable, Dict, List, Optional

_VNODES = 100  # virtual nodes per host for ring smoothness


def _ring_hash(s: str) -> int:
    """Ring position hash. NOT fnv1a32: FNV-1a over strings that differ
    only in a trailing counter ("host#0", "host#1", ...) yields hashes
    in arithmetic progression (stride = the FNV prime), so every host's
    vnodes form a band and a two-host ring degenerates — measured ~45%
    of adjacent-port host pairs put ALL 16 shard keys on one host. MD5
    avalanches properly; ring rebuilds are rare, lookups hash one short
    key."""
    # usedforsecurity=False: this is a placement hash; FIPS-mode
    # OpenSSL otherwise refuses md5 entirely
    digest = hashlib.md5(s.encode(), usedforsecurity=False).digest()
    return int.from_bytes(digest[:4], "big")


class HostInfo:
    def __init__(self, identity: str) -> None:
        self.identity = identity

    def __repr__(self) -> str:
        return f"HostInfo({self.identity!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, HostInfo) and other.identity == self.identity

    def __hash__(self) -> int:
        return hash(self.identity)


class ChangedEvent:
    def __init__(self, added: List[str], removed: List[str]) -> None:
        self.hosts_added = added
        self.hosts_removed = removed


class ServiceResolver:
    """Consistent-hash ring for one service (rpServiceResolver.go)."""

    def __init__(self, service: str) -> None:
        self.service = service
        self._lock = threading.Lock()
        self._hosts: List[str] = []
        self._ring: List[int] = []  # sorted vnode hashes
        self._ring_hosts: Dict[int, str] = {}
        self._listeners: Dict[str, Callable[[ChangedEvent], None]] = {}

    def _rebuild(self) -> None:
        self._ring = []
        self._ring_hosts = {}
        for host in self._hosts:
            for v in range(_VNODES):
                h = _ring_hash(f"{host}#{v}")
                # first writer wins on (astronomically unlikely) collision
                if h not in self._ring_hosts:
                    self._ring_hosts[h] = host
        self._ring = sorted(self._ring_hosts)

    def set_hosts(self, hosts: List[str]) -> None:
        with self._lock:
            old = set(self._hosts)
            new = set(hosts)
            self._hosts = sorted(new)
            self._rebuild()
            listeners = list(self._listeners.values())
        event = ChangedEvent(sorted(new - old), sorted(old - new))
        if event.hosts_added or event.hosts_removed:
            for cb in listeners:
                cb(event)

    def members(self) -> List[HostInfo]:
        with self._lock:
            return [HostInfo(h) for h in self._hosts]

    def member_count(self) -> int:
        with self._lock:
            return len(self._hosts)

    def lookup(self, key: str) -> HostInfo:
        """key → owning host (Lookup, interfaces.go:74)."""
        with self._lock:
            if not self._ring:
                raise RuntimeError(
                    f"no hosts in service ring {self.service!r}"
                )
            h = _ring_hash(key)
            idx = bisect.bisect_left(self._ring, h)
            if idx == len(self._ring):
                idx = 0
            return HostInfo(self._ring_hosts[self._ring[idx]])

    def add_listener(
        self, name: str, cb: Callable[[ChangedEvent], None]
    ) -> None:
        with self._lock:
            self._listeners[name] = cb

    def remove_listener(self, name: str) -> None:
        with self._lock:
            self._listeners.pop(name, None)


class Monitor:
    """Per-service rings + this host's identity (membership.Monitor)."""

    SERVICES = ("frontend", "history", "matching", "worker")

    def __init__(self, self_identity: str = "self") -> None:
        self.self_identity = self_identity
        self._resolvers: Dict[str, ServiceResolver] = {
            s: ServiceResolver(s) for s in self.SERVICES
        }

    def resolver(self, service: str) -> ServiceResolver:
        r = self._resolvers.get(service)
        if r is None:
            r = self._resolvers[service] = ServiceResolver(service)
        return r

    def whoami(self) -> HostInfo:
        return HostInfo(self.self_identity)

    def join(self, service: str, identity: Optional[str] = None) -> None:
        identity = identity or self.self_identity
        r = self.resolver(service)
        hosts = [h.identity for h in r.members()]
        if identity not in hosts:
            r.set_hosts(hosts + [identity])

    def leave(self, service: str, identity: Optional[str] = None) -> None:
        identity = identity or self.self_identity
        r = self.resolver(service)
        r.set_hosts([h.identity for h in r.members() if h.identity != identity])


def single_host_monitor(identity: str = "onebox") -> Monitor:
    """A monitor where this host owns every service (onebox topology)."""
    m = Monitor(identity)
    for s in Monitor.SERVICES:
        m.join(s)
    return m
