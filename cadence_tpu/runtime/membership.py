"""Membership: host ring with consistent-hash lookup + change listeners.

Reference: /root/reference/common/membership/interfaces.go:49-79
(Monitor / ServiceResolver) over ringpop SWIM gossip
(rpMonitor.go:44, rpServiceResolver.go:45). In this build the gossip
plane is replaced by an explicitly-driven host set (the onebox test
strategy, /root/reference/host/simpleMonitor.go): hosts join/leave via
API calls, listeners fire on change, and Lookup hashes keys onto a
replicated consistent-hash ring. Multi-host deployments drive the same
API from their orchestrator (k8s endpoints watch, etc.).
"""

from __future__ import annotations

import bisect
import hashlib
import threading
from typing import Callable, Dict, List, Optional

_VNODES = 100  # virtual nodes per host for ring smoothness


def _ring_hash(s: str) -> int:
    """Ring position hash. NOT fnv1a32: FNV-1a over strings that differ
    only in a trailing counter ("host#0", "host#1", ...) yields hashes
    in arithmetic progression (stride = the FNV prime), so every host's
    vnodes form a band and a two-host ring degenerates — measured ~45%
    of adjacent-port host pairs put ALL 16 shard keys on one host. MD5
    avalanches properly; ring rebuilds are rare, lookups hash one short
    key."""
    # usedforsecurity=False: this is a placement hash; FIPS-mode
    # OpenSSL otherwise refuses md5 entirely
    digest = hashlib.md5(s.encode(), usedforsecurity=False).digest()
    return int.from_bytes(digest[:4], "big")


class HostInfo:
    def __init__(self, identity: str) -> None:
        self.identity = identity

    def __repr__(self) -> str:
        return f"HostInfo({self.identity!r})"

    def __eq__(self, other) -> bool:
        return isinstance(other, HostInfo) and other.identity == self.identity

    def __hash__(self) -> int:
        return hash(self.identity)


class ChangedEvent:
    def __init__(self, added: List[str], removed: List[str]) -> None:
        self.hosts_added = added
        self.hosts_removed = removed


class ServiceResolver:
    """Consistent-hash ring for one service (rpServiceResolver.go)."""

    def __init__(self, service: str) -> None:
        self.service = service
        self._lock = threading.Lock()
        self._hosts: List[str] = []
        self._ring: List[int] = []  # sorted vnode hashes
        self._ring_hosts: Dict[int, str] = {}
        self._listeners: Dict[str, Callable[[ChangedEvent], None]] = {}
        # epoch-versioned shard routing (runtime/resharding.ShardMap):
        # the reshard coordinator flips the current map atomically and
        # keeps the outgoing one for a brief dual-read window so reads
        # racing the flip can still find the old owner's handle
        self._shard_map = None
        self._prev_shard_map = None

    # -- shard map (elastic resharding) --------------------------------

    def set_shard_map(self, shard_map, previous=None) -> None:
        """Atomically flip the routing epoch. ``previous`` keeps the
        outgoing map readable (dual-read window) until
        ``retire_previous_shard_map``."""
        with self._lock:
            if (
                self._shard_map is not None
                and shard_map.epoch < self._shard_map.epoch
            ):
                return  # a newer epoch already landed; never regress
            self._prev_shard_map = previous
            self._shard_map = shard_map

    def shard_map(self):
        with self._lock:
            return self._shard_map

    def shard_maps(self):
        """(current, previous-or-None) under one lock acquisition."""
        with self._lock:
            return self._shard_map, self._prev_shard_map

    def retire_previous_shard_map(self) -> None:
        with self._lock:
            self._prev_shard_map = None

    def _rebuild(self) -> None:
        self._ring = []
        self._ring_hosts = {}
        for host in self._hosts:
            for v in range(_VNODES):
                h = _ring_hash(f"{host}#{v}")
                # first writer wins on (astronomically unlikely) collision
                if h not in self._ring_hosts:
                    self._ring_hosts[h] = host
        self._ring = sorted(self._ring_hosts)

    def set_hosts(self, hosts: List[str]) -> None:
        with self._lock:
            old = set(self._hosts)
            new = set(hosts)
            self._hosts = sorted(new)
            self._rebuild()
            listeners = list(self._listeners.values())
        event = ChangedEvent(sorted(new - old), sorted(old - new))
        if event.hosts_added or event.hosts_removed:
            for cb in listeners:
                cb(event)

    def members(self) -> List[HostInfo]:
        with self._lock:
            return [HostInfo(h) for h in self._hosts]

    def member_count(self) -> int:
        with self._lock:
            return len(self._hosts)

    def lookup(self, key: str) -> HostInfo:
        """key → owning host (Lookup, interfaces.go:74)."""
        with self._lock:
            if not self._ring:
                raise RuntimeError(
                    f"no hosts in service ring {self.service!r}"
                )
            h = _ring_hash(key)
            idx = bisect.bisect_left(self._ring, h)
            if idx == len(self._ring):
                idx = 0
            return HostInfo(self._ring_hosts[self._ring[idx]])

    def add_listener(
        self, name: str, cb: Callable[[ChangedEvent], None]
    ) -> None:
        with self._lock:
            self._listeners[name] = cb

    def remove_listener(self, name: str) -> None:
        with self._lock:
            self._listeners.pop(name, None)


class Monitor:
    """Per-service rings + this host's identity (membership.Monitor)."""

    SERVICES = ("frontend", "history", "matching", "worker")

    def __init__(self, self_identity: str = "self") -> None:
        self.self_identity = self_identity
        self._resolvers: Dict[str, ServiceResolver] = {
            s: ServiceResolver(s) for s in self.SERVICES
        }

    def resolver(self, service: str) -> ServiceResolver:
        r = self._resolvers.get(service)
        if r is None:
            r = self._resolvers[service] = ServiceResolver(service)
        return r

    def whoami(self) -> HostInfo:
        return HostInfo(self.self_identity)

    def join(self, service: str, identity: Optional[str] = None) -> None:
        identity = identity or self.self_identity
        r = self.resolver(service)
        hosts = [h.identity for h in r.members()]
        if identity not in hosts:
            r.set_hosts(hosts + [identity])

    def leave(self, service: str, identity: Optional[str] = None) -> None:
        identity = identity or self.self_identity
        r = self.resolver(service)
        r.set_hosts([h.identity for h in r.members() if h.identity != identity])


def single_host_monitor(identity: str = "onebox") -> Monitor:
    """A monitor where this host owns every service (onebox topology)."""
    m = Monitor(identity)
    for s in Monitor.SERVICES:
        m.join(s)
    return m


class FailureDetector:
    """Direct-probe liveness monitor: the SWIM stand-in.

    Reference: ringpop gossip drives membership so a dead host's shards
    are reacquired automatically (/root/reference/common/membership/
    rpMonitor.go:44). Here each host probes its rings' peers directly
    (``probe(service, address) -> bool``, transport injected — the rpc
    plane provides grpc_ping); ``failure_threshold`` consecutive misses
    evict the peer from THIS host's rings via Monitor.leave, firing
    resolver listeners so the shard controller rebalances and reacquires
    the dead host's shards under rangeID fencing. Hosts detect
    independently, so rings may diverge for ~a probe interval — the
    same transient SWIM suspicion allows. Recovery (a restarted host
    rejoining) is driven by that host's own bootstrap join, as before.
    """

    def __init__(
        self,
        monitor: Monitor,
        probe: Callable[[str, str], bool],
        own_identities: Optional[set] = None,
        services: Optional[List[str]] = None,
        probe_interval_s: float = 1.0,
        failure_threshold: int = 3,
    ) -> None:
        self.monitor = monitor
        self.probe = probe
        self.own = set(own_identities or {monitor.self_identity})
        self.services = list(services or Monitor.SERVICES)
        self.probe_interval_s = probe_interval_s
        self.failure_threshold = failure_threshold
        self._misses: Dict[tuple, int] = {}
        # evicted peers stay on the probe list: a restarted host that
        # answers again is re-admitted (monitor.join) — without this,
        # eviction would be permanent on every SURVIVING host and a
        # returning peer would split the rings (it sees {A,B}, the
        # survivor sees {A}), double-acquiring shards forever
        self._evicted: set = set()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool = None  # lazy: probe rounds reuse one executor

    def start(self) -> "FailureDetector":
        self._thread = threading.Thread(
            target=self._run, name="failureDetector", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)

    def _run(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # detector must outlive transient faults
                pass

    def probe_once(self) -> None:
        """One probe round over every ring peer + every evicted peer
        (test-callable). Probes run concurrently so one blackholed host
        cannot stretch the round by its full timeout per peer; ring
        mutations happen after the round, on this thread."""
        targets = []  # (service, identity, currently_evicted)
        for service in self.services:
            for host in self.monitor.resolver(service).members():
                if host.identity not in self.own:
                    targets.append((service, host.identity, False))
        targets.extend((s, i, True) for (s, i) in self._evicted)
        if not targets:
            return
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="fd-probe"
            )
        alive = list(self._pool.map(
            lambda t: self.probe(t[0], t[1]), targets
        ))
        for (service, ident, evicted), ok in zip(targets, alive):
            key = (service, ident)
            if ok:
                self._misses.pop(key, None)
                if evicted:
                    self._evicted.discard(key)
                    self.monitor.join(service, ident)
                continue
            if evicted:
                continue
            n = self._misses.get(key, 0) + 1
            self._misses[key] = n
            if n >= self.failure_threshold:
                self._misses.pop(key, None)
                self._evicted.add(key)
                self.monitor.leave(service, ident)
