"""History re-replication: fill event gaps from the remote cluster.

Reference: common/xdc/historyRereplicator.go:113-420 — when the passive
side raises a retry error (missing earlier events), read the missing
range [start_event_id+1, end_event_id) from the remote cluster's raw
history API and apply it batch-by-batch through the same replicator,
then let the caller retry the original task.
"""

from __future__ import annotations

from typing import List

from cadence_tpu.core.events import HistoryEvent

from .messages import HistoryTaskV2, RetryTaskV2Error


class HistoryRereplicator:
    def __init__(self, remote_client, replicator) -> None:
        """``remote_client`` must expose get_workflow_history_raw(...)
        → (batches, version_history_items); ``replicator`` is the local
        NDCHistoryReplicator."""
        self.remote = remote_client
        self.replicator = replicator

    def rereplicate(self, err: RetryTaskV2Error) -> int:
        """Fetch + apply the missing range; returns batches applied."""
        start = err.start_event_id + 1 if err.start_event_id else 1
        end = err.end_event_id or (1 << 60)
        batches, items = self.remote.get_workflow_history_raw(
            err.domain_id, err.workflow_id, err.run_id, start, end
        )
        applied = 0
        for batch in batches:
            if not batch:
                continue
            task = HistoryTaskV2(
                task_id=0,
                domain_id=err.domain_id,
                workflow_id=err.workflow_id,
                run_id=err.run_id,
                version_history_items=_items_up_to(items, batch),
                events=list(batch),
            )
            self.replicator.apply_events(task)
            applied += 1
        return applied


def _items_up_to(
    items: List[dict], batch: List[HistoryEvent]
) -> List[dict]:
    """Trim the remote's version-history items to this batch's end —
    each re-replicated batch must present the history as it was at that
    point, or LCA math would see "future" items."""
    end_id = batch[-1].event_id
    end_version = batch[-1].version
    out: List[dict] = []
    for it in items:
        if it["event_id"] < end_id:
            out.append(dict(it))
        else:
            break
    out.append({"event_id": end_id, "version": end_version})
    # drop any stale prefix item with the same version as the boundary
    dedup: List[dict] = []
    for it in out:
        if dedup and dedup[-1]["version"] == it["version"]:
            dedup[-1] = it
        else:
            dedup.append(it)
    return dedup
