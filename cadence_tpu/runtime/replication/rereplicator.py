"""History re-replication: fill event gaps from the remote cluster.

Reference: common/xdc/historyRereplicator.go:113-420 — when the passive
side raises a retry error (missing earlier events), read the missing
range [start_event_id+1, end_event_id) from the remote cluster's raw
history API and apply it batch-by-batch through the same replicator,
then let the caller retry the original task.

Bandwidth-adaptive twist (transport.py): with an ``AdaptiveTransport``
attached, every gap first consults the mode controller. A deep gap on a
constrained link recovers by **snapshot shipping** — fetch the source's
delta-compressed ``ReplayCheckpoint``, install it through the suffix-only
resume path (``NDCHistoryReplicator.apply_state_snapshot``), and owe a
history backfill for the covered range — instead of re-shipping and
re-replaying the whole event backlog. Any snapshot-path failure (torn
transfer, stale fingerprint, divergent local branch) falls back to the
event path below, which remains the correctness baseline.
"""

from __future__ import annotations

import time
from typing import List, Optional

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.utils import tracing
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP

from .messages import HistoryTaskV2, RetryTaskV2Error
from .transport import MODE_SNAPSHOT

logger = get_logger("cadence_tpu.replication")


class HistoryRereplicator:
    def __init__(self, remote_client, replicator, transport=None,
                 metrics=None) -> None:
        """``remote_client`` must expose get_workflow_history_raw(...)
        → (batches, version_history_items); ``replicator`` is the local
        NDCHistoryReplicator. ``transport`` (AdaptiveTransport) enables
        the snapshot recovery mode; None keeps pure event shipping."""
        self.remote = remote_client
        self.replicator = replicator
        self.transport = transport
        self._metrics = (metrics or NOOP).tagged(layer="replication")
        # the consumer's deferred-backfill hook: when set, a snapshot
        # recovery enqueues its history backfill there (state catches
        # up now, bytes follow); unset, the backfill runs inline so a
        # standalone rereplicator still converges byte-identical
        self.backfill_sink = None

    def rereplicate(self, err: RetryTaskV2Error) -> int:
        """Fetch + apply the missing range; returns batches applied
        (0 when a snapshot recovery covered the gap instead)."""
        gap = max(0, (err.end_event_id or 0) - (err.start_event_id or 0))
        if (
            self.transport is not None
            and self.transport.controller.decide(gap) == MODE_SNAPSHOT
        ):
            recovered = None
            try:
                recovered = self._snapshot_recover(err)
                if recovered is None:
                    self._metrics.inc("replication_snapshot_fallbacks")
                    tracing.annotate(
                        f"snapshot_fallback wf={err.workflow_id}"
                    )
            except Exception:
                # torn snapshot transfer / partitioned link mid-blob:
                # the event path below re-fetches through the same
                # (possibly still degraded) link and stays correct
                self._metrics.inc("replication_snapshot_fallbacks")
                tracing.annotate(
                    f"snapshot_fallback wf={err.workflow_id} (torn)"
                )
                logger.exception(
                    "snapshot recovery failed; falling back to event "
                    "shipping",
                    workflow=err.workflow_id, run=err.run_id,
                )
            if recovered is not None:
                # the chain-successor heal runs OUTSIDE the fallback
                # guard above: a failure here must propagate so the
                # caller holds its cursor and retries — falling back to
                # the event path for the PREDECESSOR run would read as
                # healed while the successor's first batch stays lost
                if recovered.get("continued_as_new"):
                    self._heal_chain_successor(
                        err.domain_id, err.workflow_id, err.run_id,
                        tip_event_id=recovered["covered_through"],
                    )
                return 0
        start = err.start_event_id + 1 if err.start_event_id else 1
        end = err.end_event_id or (1 << 60)
        if self.transport is not None:
            batches, items = self.transport.fetch_raw_history(
                err.domain_id, err.workflow_id, err.run_id, start, end
            )
        else:
            batches, items = self.remote.get_workflow_history_raw(
                err.domain_id, err.workflow_id, err.run_id, start, end
            )
        applied = apply_raw_history(
            self.replicator, err.domain_id, err.workflow_id, err.run_id,
            batches, items,
        )
        # the raw event heal has the same chain blind spot as snapshot
        # shipping: synthetic tasks carry no new_run_events, so a
        # healed run that closed ContinuedAsNew leaves its successor's
        # first batch unapplied — walk the chain explicitly
        succ = _chain_successor_of(batches)
        if succ:
            self._heal_chain_successor(
                err.domain_id, err.workflow_id, err.run_id,
                successor_run_id=succ,
            )
        return applied

    # -- snapshot recovery --------------------------------------------

    def _snapshot_recover(self, err: RetryTaskV2Error):
        """Returns apply_state_snapshot's result record on success, or
        None when the gap must heal through the event path."""
        got = self.transport.fetch_snapshot(
            err.domain_id, err.workflow_id, err.run_id
        )
        if got is None:
            return None
        ckpt, nbytes = got
        t0 = time.monotonic()
        res = self.replicator.apply_state_snapshot(
            err.domain_id, err.workflow_id, err.run_id, ckpt
        )
        if res is None:
            return None
        self.transport.estimator.observe_snapshot(
            nbytes, time.monotonic() - t0
        )
        self._metrics.inc("replication_snapshots_shipped")
        if self.backfill_sink is not None:
            self.backfill_sink(
                err.domain_id, err.workflow_id, err.run_id,
                res["backfill_from"], res["covered_through"],
            )
        else:
            self.backfill(
                err.domain_id, err.workflow_id, err.run_id,
                res["backfill_from"], res["covered_through"],
            )
        return res

    # -- continue-as-new chain walk -----------------------------------

    _CHAIN_HEAL_MAX = 16

    def _fetch_raw(self, domain_id: str, workflow_id: str, run_id: str,
                   start: int, end: int):
        if self.transport is not None:
            return self.transport.fetch_raw_history(
                domain_id, workflow_id, run_id, start, end
            )
        return self.remote.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start, end
        )

    def _fetch_tip_event(self, domain_id: str, workflow_id: str,
                         run_id: str, tip: int):
        """The run's final event via node-aligned raw reads: history
        nodes key on their batch's FIRST event id, so a [tip, tip+1)
        read misses a tail that sits inside a wider batch — widen the
        window geometrically until the tip lands (bounded by the full
        history)."""
        lo = tip
        while True:
            batches, _ = self._fetch_raw(
                domain_id, workflow_id, run_id, lo, tip + 1
            )
            for b in batches:
                for e in b:
                    if e.event_id == tip:
                        return e
            if lo <= 1:
                return None
            lo = max(1, lo - 16 * max(1, tip + 1 - lo))

    def _heal_chain_successor(
        self, domain_id: str, workflow_id: str, run_id: str,
        tip_event_id: int = 0, successor_run_id: str = "",
    ) -> int:
        """Walk a continue-as-new chain forward from a healed run and
        materialize every successor the fast-forward bypassed.

        A chain run's FIRST batch rides its predecessor's replication
        task as ``new_run_events`` — a catch-up that heals the
        predecessor by snapshot (or raw-history fetch) and fast-forwards
        the cursor past those tasks loses the successor entirely: it has
        no replication tasks of its own until a second batch exists, so
        no later cycle will ever surface it. When the successor id is
        unknown (snapshot path: the covered events are backfill debt,
        not yet local) the predecessor's tip event is fetched remotely
        — one event — to read ``new_execution_run_id``. Each successor
        heals snapshot-first when the transport prefers it, else by raw
        history from event 1; the walk continues while the healed run
        itself continued-as-new (bounded, loudly, at 16 hops). Failures
        raise: the caller must hold its cursor and retry rather than
        mark the span healed with a chain run missing."""
        healed = 0
        cur_run, cur_tip, next_run = run_id, tip_event_id, successor_run_id
        seen = {run_id}
        for _ in range(self._CHAIN_HEAL_MAX):
            if not next_run:
                # read the predecessor's final event for the successor id
                tail = self._fetch_tip_event(
                    domain_id, workflow_id, cur_run, cur_tip
                )
                if tail is None:
                    break
                next_run = tail.attributes.get(
                    "new_execution_run_id", ""
                )
            if not next_run or next_run in seen:
                break
            seen.add(next_run)
            res = None
            if self.transport is not None:
                # unknown gap for a run we may not have at all: let the
                # controller's current mode decide, exactly like the
                # predecessor's heal did
                try:
                    res = self._snapshot_recover(RetryTaskV2Error(
                        "chain successor heal",
                        domain_id=domain_id, workflow_id=workflow_id,
                        run_id=next_run, start_event_id=0, end_event_id=0,
                    )) if self.transport.controller.mode == MODE_SNAPSHOT \
                        else None
                except Exception:
                    res = None  # raw-history heal below stays correct
            if res is not None:
                healed += 1
                self._metrics.inc("replication_chain_heals")
                cur_run, cur_tip = next_run, res["covered_through"]
                next_run = ""
                if res.get("continued_as_new"):
                    continue
                break
            batches, items = self._fetch_raw(
                domain_id, workflow_id, next_run, 1, 1 << 60
            )
            applied = apply_raw_history(
                self.replicator, domain_id, workflow_id, next_run,
                batches, items,
            )
            if applied == 0 and not any(batches):
                break  # source knows no such run: chain ends here
            healed += 1
            self._metrics.inc("replication_chain_heals")
            succ = _chain_successor_of(batches)
            if not succ:
                break
            cur_run, next_run = next_run, succ
        else:
            # raising (not warning) keeps the caller's cursor held, so
            # a chain deeper than the hop bound converges through the
            # regular event stream instead — the held cursor re-fetches
            # the original tasks, whose new_run_events create each
            # successor page by page. Silent truncation here would lose
            # every run past the bound forever (they have no
            # replication tasks of their own to ever surface again).
            raise RuntimeError(
                f"continue-as-new chain for {workflow_id!r} exceeds "
                f"{self._CHAIN_HEAL_MAX} hops; holding the cursor so "
                "the event stream heals the remainder"
            )
        return healed

    def backfill(self, domain_id: str, workflow_id: str, run_id: str,
                 from_event_id: int, through_event_id: int) -> int:
        """Fetch + append the raw history range a snapshot covered —
        the byte-identity half of snapshot shipping. Returns events
        appended."""
        if from_event_id > through_event_id:
            return 0
        if self.transport is not None:
            batches, _ = self.transport.fetch_raw_history(
                domain_id, workflow_id, run_id,
                from_event_id, through_event_id + 1,
            )
        else:
            batches, _ = self.remote.get_workflow_history_raw(
                domain_id, workflow_id, run_id,
                from_event_id, through_event_id + 1,
            )
        applied = self.replicator.backfill_history(
            domain_id, workflow_id, run_id, batches
        )
        if applied:
            self._metrics.inc("replication_backfill_events", applied)
        return applied


def _chain_successor_of(batches) -> str:
    """The continue-as-new successor run id a healed history names in
    its final event, or "" when the run didn't continue."""
    from cadence_tpu.core.enums import EventType

    tail = None
    for b in batches:
        if b:
            tail = b[-1]
    if tail is None:
        return ""
    if tail.event_type != EventType.WorkflowExecutionContinuedAsNew:
        return ""
    return tail.attributes.get("new_execution_run_id", "")


def apply_raw_history(
    replicator, domain_id: str, workflow_id: str, run_id: str,
    batches, items: Optional[List[dict]],
) -> int:
    """Apply raw remote batches through the NDC replicator, one
    synthetic HistoryTaskV2 per batch — the event-shipping heal shared
    by the rereplicator and the adaptive catch-up cycle."""
    applied = 0
    for batch in batches:
        if not batch:
            continue
        task = HistoryTaskV2(
            task_id=0,
            domain_id=domain_id,
            workflow_id=workflow_id,
            run_id=run_id,
            version_history_items=_items_up_to(items or [], batch),
            events=list(batch),
        )
        replicator.apply_events(task)
        applied += 1
    return applied


def _items_up_to(
    items: List[dict], batch: List[HistoryEvent]
) -> List[dict]:
    """Trim the remote's version-history items to this batch's end —
    each re-replicated batch must present the history as it was at that
    point, or LCA math would see "future" items."""
    end_id = batch[-1].event_id
    end_version = batch[-1].version
    out: List[dict] = []
    for it in items:
        if it["event_id"] < end_id:
            out.append(dict(it))
        else:
            break
    out.append({"event_id": end_id, "version": end_version})
    # drop any stale prefix item with the same version as the boundary
    dedup: List[dict] = []
    for it in out:
        if dedup and dedup[-1]["version"] == it["version"]:
            dedup[-1] = it
        else:
            dedup.append(it)
    return dedup
