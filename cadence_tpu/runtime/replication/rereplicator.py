"""History re-replication: fill event gaps from the remote cluster.

Reference: common/xdc/historyRereplicator.go:113-420 — when the passive
side raises a retry error (missing earlier events), read the missing
range [start_event_id+1, end_event_id) from the remote cluster's raw
history API and apply it batch-by-batch through the same replicator,
then let the caller retry the original task.

Bandwidth-adaptive twist (transport.py): with an ``AdaptiveTransport``
attached, every gap first consults the mode controller. A deep gap on a
constrained link recovers by **snapshot shipping** — fetch the source's
delta-compressed ``ReplayCheckpoint``, install it through the suffix-only
resume path (``NDCHistoryReplicator.apply_state_snapshot``), and owe a
history backfill for the covered range — instead of re-shipping and
re-replaying the whole event backlog. Any snapshot-path failure (torn
transfer, stale fingerprint, divergent local branch) falls back to the
event path below, which remains the correctness baseline.
"""

from __future__ import annotations

import time
from typing import List, Optional

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.utils import tracing
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP

from .messages import HistoryTaskV2, RetryTaskV2Error
from .transport import MODE_SNAPSHOT

logger = get_logger("cadence_tpu.replication")


class HistoryRereplicator:
    def __init__(self, remote_client, replicator, transport=None,
                 metrics=None) -> None:
        """``remote_client`` must expose get_workflow_history_raw(...)
        → (batches, version_history_items); ``replicator`` is the local
        NDCHistoryReplicator. ``transport`` (AdaptiveTransport) enables
        the snapshot recovery mode; None keeps pure event shipping."""
        self.remote = remote_client
        self.replicator = replicator
        self.transport = transport
        self._metrics = (metrics or NOOP).tagged(layer="replication")
        # the consumer's deferred-backfill hook: when set, a snapshot
        # recovery enqueues its history backfill there (state catches
        # up now, bytes follow); unset, the backfill runs inline so a
        # standalone rereplicator still converges byte-identical
        self.backfill_sink = None

    def rereplicate(self, err: RetryTaskV2Error) -> int:
        """Fetch + apply the missing range; returns batches applied
        (0 when a snapshot recovery covered the gap instead)."""
        gap = max(0, (err.end_event_id or 0) - (err.start_event_id or 0))
        if (
            self.transport is not None
            and self.transport.controller.decide(gap) == MODE_SNAPSHOT
        ):
            try:
                if self._snapshot_recover(err):
                    return 0
                self._metrics.inc("replication_snapshot_fallbacks")
                tracing.annotate(
                    f"snapshot_fallback wf={err.workflow_id}"
                )
            except Exception:
                # torn snapshot transfer / partitioned link mid-blob:
                # the event path below re-fetches through the same
                # (possibly still degraded) link and stays correct
                self._metrics.inc("replication_snapshot_fallbacks")
                tracing.annotate(
                    f"snapshot_fallback wf={err.workflow_id} (torn)"
                )
                logger.exception(
                    "snapshot recovery failed; falling back to event "
                    "shipping",
                    workflow=err.workflow_id, run=err.run_id,
                )
        start = err.start_event_id + 1 if err.start_event_id else 1
        end = err.end_event_id or (1 << 60)
        if self.transport is not None:
            batches, items = self.transport.fetch_raw_history(
                err.domain_id, err.workflow_id, err.run_id, start, end
            )
        else:
            batches, items = self.remote.get_workflow_history_raw(
                err.domain_id, err.workflow_id, err.run_id, start, end
            )
        return apply_raw_history(
            self.replicator, err.domain_id, err.workflow_id, err.run_id,
            batches, items,
        )

    # -- snapshot recovery --------------------------------------------

    def _snapshot_recover(self, err: RetryTaskV2Error) -> bool:
        got = self.transport.fetch_snapshot(
            err.domain_id, err.workflow_id, err.run_id
        )
        if got is None:
            return False
        ckpt, nbytes = got
        t0 = time.monotonic()
        res = self.replicator.apply_state_snapshot(
            err.domain_id, err.workflow_id, err.run_id, ckpt
        )
        if res is None:
            return False
        self.transport.estimator.observe_snapshot(
            nbytes, time.monotonic() - t0
        )
        self._metrics.inc("replication_snapshots_shipped")
        if self.backfill_sink is not None:
            self.backfill_sink(
                err.domain_id, err.workflow_id, err.run_id,
                res["backfill_from"], res["covered_through"],
            )
        else:
            self.backfill(
                err.domain_id, err.workflow_id, err.run_id,
                res["backfill_from"], res["covered_through"],
            )
        return True

    def backfill(self, domain_id: str, workflow_id: str, run_id: str,
                 from_event_id: int, through_event_id: int) -> int:
        """Fetch + append the raw history range a snapshot covered —
        the byte-identity half of snapshot shipping. Returns events
        appended."""
        if from_event_id > through_event_id:
            return 0
        if self.transport is not None:
            batches, _ = self.transport.fetch_raw_history(
                domain_id, workflow_id, run_id,
                from_event_id, through_event_id + 1,
            )
        else:
            batches, _ = self.remote.get_workflow_history_raw(
                domain_id, workflow_id, run_id,
                from_event_id, through_event_id + 1,
            )
        applied = self.replicator.backfill_history(
            domain_id, workflow_id, run_id, batches
        )
        if applied:
            self._metrics.inc("replication_backfill_events", applied)
        return applied


def apply_raw_history(
    replicator, domain_id: str, workflow_id: str, run_id: str,
    batches, items: Optional[List[dict]],
) -> int:
    """Apply raw remote batches through the NDC replicator, one
    synthetic HistoryTaskV2 per batch — the event-shipping heal shared
    by the rereplicator and the adaptive catch-up cycle."""
    applied = 0
    for batch in batches:
        if not batch:
            continue
        task = HistoryTaskV2(
            task_id=0,
            domain_id=domain_id,
            workflow_id=workflow_id,
            run_id=run_id,
            version_history_items=_items_up_to(items or [], batch),
            events=list(batch),
        )
        replicator.apply_events(task)
        applied += 1
    return applied


def _items_up_to(
    items: List[dict], batch: List[HistoryEvent]
) -> List[dict]:
    """Trim the remote's version-history items to this batch's end —
    each re-replicated batch must present the history as it was at that
    point, or LCA math would see "future" items."""
    end_id = batch[-1].event_id
    end_version = batch[-1].version
    out: List[dict] = []
    for it in items:
        if it["event_id"] < end_id:
            out.append(dict(it))
        else:
            break
    out.append({"event_id": end_id, "version": end_version})
    # drop any stale prefix item with the same version as the boundary
    dedup: List[dict] = []
    for it in out:
        if dedup and dedup[-1]["version"] == it["version"]:
            dedup[-1] = it
        else:
            dedup.append(it)
    return dedup
