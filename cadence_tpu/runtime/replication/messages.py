"""Replication wire types.

Reference: the ReplicationTask / HistoryTaskV2Attributes thrift shapes
(idl replicator.thrift) carried by GetReplicationMessages
(service/history/replicatorQueueProcessor.go getHistoryTaskV2) and the
RetryTaskV2Error the passive side raises when events arrive out of order
(common/persistence serviceerrors → xdc rereplication).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from cadence_tpu.core.events import HistoryEvent


@dataclasses.dataclass
class HistoryTaskV2:
    """One replicated transaction batch for one workflow run."""

    task_id: int
    domain_id: str
    workflow_id: str
    run_id: str
    version_history_items: List[Dict[str, int]]  # [{"event_id", "version"}]
    events: List[HistoryEvent]
    new_run_events: List[HistoryEvent] = dataclasses.field(default_factory=list)
    new_run_id: str = ""

    @property
    def first_event_id(self) -> int:
        return self.events[0].event_id if self.events else 0

    @property
    def next_event_id(self) -> int:
        return self.events[-1].event_id + 1 if self.events else 0

    @property
    def version(self) -> int:
        return self.events[0].version if self.events else 0


@dataclasses.dataclass
class ReplicationMessages:
    """One pull response: tasks after ``last_retrieved_id`` plus whether
    the emitter has more backlog."""

    tasks: List[HistoryTaskV2]
    last_retrieved_id: int
    has_more: bool = False
    # emitter's clock at serve time — advances the consumer's view of the
    # remote cluster (ref syncShardStatus / shardContext.SetCurrentTime),
    # which gates standby timer processing
    source_time_ns: int = 0


class RetryTaskV2Error(Exception):
    """Passive side is missing earlier events — the caller must
    re-replicate [start_event_id, end_event_id) first and retry."""

    def __init__(
        self,
        msg: str,
        domain_id: str = "",
        workflow_id: str = "",
        run_id: str = "",
        start_event_id: int = 0,
        start_event_version: int = 0,
        end_event_id: int = 0,
        end_event_version: int = 0,
    ) -> None:
        super().__init__(msg)
        self.domain_id = domain_id
        self.workflow_id = workflow_id
        self.run_id = run_id
        self.start_event_id = start_event_id
        self.start_event_version = start_event_version
        self.end_event_id = end_event_id
        self.end_event_version = end_event_version
