"""Domain failover coordinator: managed handover, forced region-loss
promotion, and failback over the two-cluster xdc topology.

Reference: the failover-version design of common/cluster/metadata.go +
common/domain/handler.go UpdateDomain (PAPER.md §cluster metadata): a
global domain's ownership is a ``failover_version`` that moves in
increments whose residue identifies the owning cluster. This module
composes that static arithmetic into a *reconfigurable* whole — the
"Reconfigurable State Machine Replication from Non-Reconfigurable
Building Blocks" move (PAPERS.md): each drill is a sequence of
already-proven static steps (queue drain, replication drain, guarded
domain-record merge, cursor rewind via the standby handover listeners)
whose composition is what the failover drills validate.

Three drill shapes (the scenario zoo in tests/test_failover_drills.py
and the ``failover_drill`` bench config drive all three):

* **managed handover** — the graceful path: drain the old active
  side's queue pipelines (in-flight decisions settle), drain the
  target's replication pull plane to state-current, bump
  ``failover_version`` through ``ClusterMetadata.next_failover_version``
  and flip ``active_cluster_name`` on every reachable cluster (old
  active FIRST, so it stops minting before anyone else starts), then
  wait for the new active's domain cache to observe its own ownership —
  that observation fires the ``_on_domain_failover`` listeners that
  rewind the active queue cursors over the standby-held span, so no
  passive-side task is ever lost;
* **forced failover** — region loss: the old active is unreachable, so
  nothing drains; the domain record is flipped on the reachable
  clusters only, with divergent branches knowingly outstanding. The
  report carries the replication lag *known at promote time* (the
  estimator's last view of the dead link — exactly what an operator
  sees) and the NDC conflict-resolution storm that follows the heal is
  measured via the ``replication_conflicts_resolved`` counter;
* **failback** — after the lost region recovers: re-sync its domain
  record (guarded merge, same rules as the domain-replication topic),
  drain both directions to convergence (the conflict storm resolves
  here), then run a managed handover back.

Every drill emits the ``FAILOVER_METRICS`` family through the PR 9
histogram plane: ``failover_handover_ms`` (end-to-end drill wall time),
``failover_unavailability_ms`` (flip start → new active observes
ownership: the window where neither side safely mints),
``failover_replication_lag_at_promote`` and
``failover_conflicts_resolved`` (registry delta across the drill), plus
a ``domain_failovers`` counter tagged ``kind=managed|forced|failback``.
"""

from __future__ import annotations

import contextlib
import copy
import dataclasses
import time
from typing import Dict, List, Optional, Sequence

from cadence_tpu.utils.locks import make_lock
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP

logger = get_logger("cadence_tpu.replication.failover")

# one counter name, one definition: what "a resolved conflict" means to
# both the NDC replicator (emit side) and the drill reports (read side)
CONFLICTS_RESOLVED = "replication_conflicts_resolved"


@dataclasses.dataclass
class ClusterHandle:
    """What the coordinator needs from ONE cluster of the topology.

    ``processors`` are the cluster's replication consumers (its pull
    planes FROM the peers — ``ReplicationTaskProcessor``); draining
    them makes this cluster state-current. ``transport`` is the
    cluster's inbound ``AdaptiveTransport`` when wired (the lag view at
    promote time); ``registry`` a ``utils.metrics.Registry`` whose
    ``replication_conflicts_resolved`` counter the drill reports read.
    ``history`` (a ``HistoryService``) is optional — without it the
    graceful drain skips the queue pipelines of that cluster."""

    name: str
    metadata: object                 # persistence MetadataManager
    domains: object                  # runtime.domains.DomainCache
    history: object = None           # runtime.service.HistoryService
    processors: Sequence = ()        # inbound ReplicationTaskProcessors
    transport: object = None         # inbound AdaptiveTransport
    registry: object = None          # utils.metrics.Registry


@dataclasses.dataclass
class FailoverReport:
    """One drill's outcome — the assertion surface of the scenario zoo
    and the rows of the ``failover_drill`` bench record."""

    kind: str                        # managed | forced | failback
    domain: str
    from_cluster: str
    to_cluster: str
    failover_version: int
    handover_ms: float = 0.0         # end-to-end drill wall time
    unavailability_ms: float = 0.0   # flip start -> ownership observed
    replication_lag_at_promote: int = 0   # events known outstanding
    conflicts_resolved: int = 0      # registry delta across the drill
    drained_tasks: int = 0           # replication tasks applied in-drill
    unreachable: List[str] = dataclasses.field(default_factory=list)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class FailoverDrillError(RuntimeError):
    """A drill step failed in a way that leaves ownership ambiguous —
    the drill harness must treat the topology as poisoned."""


class DomainFailoverCoordinator:
    """Drives domain ownership changes across an in-process (or test /
    bench) multi-cluster topology.

    The coordinator is an *operator*, not a service: it owns no
    background threads and mutates nothing outside the domain records
    and the drains it is asked to run. One drill at a time (guarded) —
    overlapping ownership changes for the same domain are exactly the
    split-brain the failover-version arithmetic exists to prevent."""

    def __init__(
        self,
        cluster_metadata,
        handles: Sequence[ClusterHandle],
        metrics=None,
        drain_timeout_s: float = 30.0,
    ) -> None:
        if not handles:
            raise ValueError("failover coordinator needs cluster handles")
        self.cluster_metadata = cluster_metadata
        self.handles: Dict[str, ClusterHandle] = {}
        for h in handles:
            if h.name in self.handles:
                raise ValueError(f"duplicate cluster handle {h.name!r}")
            self.handles[h.name] = h
        self.drain_timeout_s = drain_timeout_s
        self._metrics = (metrics or NOOP).tagged(layer="failover")
        self._lock = make_lock("DomainFailoverCoordinator._lock")

    @contextlib.contextmanager
    def _one_drill(self):
        """Non-blocking exclusivity: a second concurrent drill fails
        loudly instead of queueing behind the first — overlapping
        ownership changes are the split-brain the failover-version
        arithmetic exists to prevent. Try-lock, never held across a
        wait the caller didn't ask for."""
        if not self._lock.acquire(blocking=False):
            raise FailoverDrillError(
                "another failover drill is already in progress"
            )
        try:
            yield
        finally:
            self._lock.release()

    # -- domain record plumbing ---------------------------------------

    def _newest_record(self, domain: str, reachable: Sequence[str]):
        """The authoritative record: max failover_version among the
        reachable clusters (ties keep the first handle's copy)."""
        best = None
        for name in reachable:
            try:
                rec = self.handles[name].metadata.get_domain(name=domain)
            except Exception:
                continue
            if best is None or rec.failover_version > best.failover_version:
                best = rec
        if best is None:
            raise FailoverDrillError(
                f"domain {domain!r} not found on any reachable cluster"
            )
        return best

    def _apply_record(self, handle: ClusterHandle, rec) -> None:
        """Guarded merge of ``rec`` into one cluster's metadata — the
        same failover-version monotonicity rule the domain-replication
        topic applies (domain_handler.apply_replication_record): a
        stale flip can never regress ownership."""
        fresh = copy.deepcopy(rec)
        try:
            existing = handle.metadata.get_domain(name=rec.info.name)
        except Exception:
            handle.metadata.create_domain(fresh)
            return
        if fresh.failover_version <= existing.failover_version and (
            existing.replication_config.active_cluster_name
            == fresh.replication_config.active_cluster_name
        ):
            return  # already at/past this ownership state
        if fresh.failover_version < existing.failover_version:
            return  # stale: never regress
        handle.metadata.update_domain(fresh)

    def _poke_cache(self, handle: ClusterHandle, domain: str) -> None:
        """Force the cluster's domain cache to observe the new record
        NOW (a lookup triggers the staleness refresh, which fires the
        failover listeners that rewind the active queue cursors)."""
        try:
            handle.domains.get_by_name(domain)
        except Exception:
            pass

    def propagate_domain(
        self, domain: str, reachable: Optional[Sequence[str]] = None
    ) -> None:
        """Push the newest record to every reachable cluster and poke
        their caches — what the domain-replication topic does in a real
        deployment; here the drill step that re-syncs a recovered
        region before failback."""
        names = list(reachable if reachable is not None else self.handles)
        rec = self._newest_record(domain, names)
        for name in names:
            self._apply_record(self.handles[name], rec)
            self._poke_cache(self.handles[name], domain)

    # -- drains --------------------------------------------------------

    def _drain_replication(
        self, handle: ClusterHandle, timeout_s: Optional[float] = None,
        swallow: tuple = (),
    ) -> int:
        """Pull this cluster's inbound replication planes until one full
        round applies nothing; returns tasks applied. ``swallow`` lets a
        drill keep draining through transfer-indexed partition windows
        (the link heals by index, not wall time)."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.drain_timeout_s
        )
        total = 0
        while time.monotonic() < deadline:
            round_applied = 0
            faulted = False
            for proc in handle.processors:
                try:
                    round_applied += proc.process_once()
                except swallow:
                    # a swallowed fault (partition window, injected
                    # write error) means the cycle held its cursor —
                    # the round is a retry, never quiescence
                    faulted = True
            total += round_applied
            if round_applied == 0 and not faulted:
                return total
        raise FailoverDrillError(
            f"replication into {handle.name!r} never drained "
            f"within {timeout_s or self.drain_timeout_s}s"
        )

    def _drain_queues(self, handle: ClusterHandle,
                      timeout_s: float = 10.0) -> None:
        if handle.history is None:
            return
        if not handle.history.drain_queues(timeout_s):
            raise FailoverDrillError(
                f"queue pipelines on {handle.name!r} did not quiesce"
            )

    def _lag_at_promote(self, handle: ClusterHandle) -> int:
        t = handle.transport
        if t is None:
            return 0
        return int(t.estimator.lag_events)

    def _conflicts(self, names: Sequence[str]) -> int:
        total = 0
        for name in names:
            reg = self.handles[name].registry
            if reg is None:
                continue
            try:
                total += int(reg.counter_value(CONFLICTS_RESOLVED))
            except Exception:
                pass
        return total

    # -- the flip ------------------------------------------------------

    def _flip(
        self, domain: str, to_cluster: str, reachable: Sequence[str],
        observe_timeout_s: float = 10.0,
    ) -> tuple:
        """Bump the failover version, write the flipped record to every
        reachable cluster (old active first — it must stop minting
        before anyone else starts), and wait for the TARGET cluster's
        domain cache to observe its own ownership. Returns
        (new_failover_version, unavailability_ms)."""
        rec = self._newest_record(domain, reachable)
        if not rec.is_global:
            raise FailoverDrillError(
                f"domain {domain!r} is not global; nothing to fail over"
            )
        if to_cluster not in rec.replication_config.clusters:
            raise FailoverDrillError(
                f"target {to_cluster!r} not in domain clusters"
            )
        old_active = rec.replication_config.active_cluster_name
        new_version = self.cluster_metadata.next_failover_version(
            to_cluster, rec.failover_version + 1
        )
        flipped = copy.deepcopy(rec)
        flipped.replication_config.active_cluster_name = to_cluster
        flipped.failover_version = new_version
        flipped.failover_notification_version = rec.notification_version

        t_flip = time.monotonic()
        ordered = [n for n in reachable if n == old_active] + [
            n for n in reachable if n != old_active
        ]
        for name in ordered:
            self._apply_record(self.handles[name], flipped)
            self._poke_cache(self.handles[name], domain)
        # unavailability ends when the new active OBSERVES ownership:
        # from that moment its frontends accept and its queue cursors
        # have been rewound over the standby-held span
        target = self.handles[to_cluster]
        deadline = time.monotonic() + observe_timeout_s
        while time.monotonic() < deadline:
            try:
                cur = target.domains.get_by_name(domain)
                if (
                    cur.replication_config.active_cluster_name == to_cluster
                    and cur.failover_version >= new_version
                ):
                    break
            except Exception:
                pass
            time.sleep(0.005)
        else:
            raise FailoverDrillError(
                f"{to_cluster!r} never observed ownership of {domain!r}"
            )
        return new_version, (time.monotonic() - t_flip) * 1000.0

    # -- drills --------------------------------------------------------

    def managed_handover(
        self, domain: str, to_cluster: str, kind: str = "managed",
        swallow: tuple = (), _emit: bool = True,
    ) -> FailoverReport:
        """The graceful path: drain, flip, observe — zero lost progress
        by construction (everything in flight settled before the flip;
        the handover listeners rewind over anything the standby held)."""
        with self._one_drill():
            t0 = time.monotonic()
            reachable = list(self.handles)
            conflicts0 = self._conflicts(reachable)
            rec = self._newest_record(domain, reachable)
            old_active = rec.replication_config.active_cluster_name
            if to_cluster == old_active:
                raise FailoverDrillError(
                    f"domain {domain!r} already active in {to_cluster!r}"
                )
            target = self.handles[to_cluster]
            # 1. in-flight decisions/timers on the old active settle
            if old_active in self.handles:
                self._drain_queues(self.handles[old_active])
            # 2. the target catches up to state-current
            drained = self._drain_replication(target, swallow=swallow)
            lag = self._lag_at_promote(target)
            # 3. flip + observe
            version, unavail_ms = self._flip(
                domain, to_cluster, reachable
            )
            # 4. residual drain: anything minted between 2 and the flip
            drained += self._drain_replication(target, swallow=swallow)
            report = FailoverReport(
                kind=kind, domain=domain, from_cluster=old_active,
                to_cluster=to_cluster, failover_version=version,
                handover_ms=(time.monotonic() - t0) * 1000.0,
                unavailability_ms=unavail_ms,
                replication_lag_at_promote=lag,
                conflicts_resolved=(
                    self._conflicts(reachable) - conflicts0
                ),
                drained_tasks=drained,
            )
        if _emit:
            self._emit(report)
        return report

    def forced_failover(
        self, domain: str, to_cluster: str,
        lost_clusters: Sequence[str] = (),
    ) -> FailoverReport:
        """Region loss: promote ``to_cluster`` with the lost clusters
        unreachable and divergent branches knowingly outstanding. No
        drain of the lost side is possible; the target's inbound lag
        view at promote time is reported as-is."""
        with self._one_drill():
            t0 = time.monotonic()
            lost = set(lost_clusters)
            reachable = [n for n in self.handles if n not in lost]
            if to_cluster not in reachable:
                raise FailoverDrillError(
                    f"cannot promote unreachable cluster {to_cluster!r}"
                )
            conflicts0 = self._conflicts(reachable)
            rec = self._newest_record(domain, reachable)
            old_active = rec.replication_config.active_cluster_name
            lag = self._lag_at_promote(self.handles[to_cluster])
            version, unavail_ms = self._flip(
                domain, to_cluster, reachable
            )
            report = FailoverReport(
                kind="forced", domain=domain, from_cluster=old_active,
                to_cluster=to_cluster, failover_version=version,
                handover_ms=(time.monotonic() - t0) * 1000.0,
                unavailability_ms=unavail_ms,
                replication_lag_at_promote=lag,
                conflicts_resolved=(
                    self._conflicts(reachable) - conflicts0
                ),
                unreachable=sorted(lost),
            )
        self._emit(report)
        return report

    def await_convergence(
        self, domain: str, timeout_s: Optional[float] = None,
        swallow: tuple = (),
    ) -> int:
        """Drain every cluster's inbound replication, round-robin, until
        one full round applies nothing anywhere — the conflict storm
        after a healed partition resolves inside this loop (divergent
        branches fork, higher-version branches win, reapplied signals
        replicate back). Returns total tasks applied."""
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None else self.drain_timeout_s
        )
        self.propagate_domain(domain)
        total = 0
        while time.monotonic() < deadline:
            round_applied = 0
            faulted = False
            for handle in self.handles.values():
                for proc in handle.processors:
                    try:
                        round_applied += proc.process_once()
                    except swallow:
                        faulted = True
            total += round_applied
            if round_applied == 0 and not faulted:
                return total
        raise FailoverDrillError(
            f"replication never converged within "
            f"{timeout_s or self.drain_timeout_s}s"
        )

    def failback(
        self, domain: str, to_cluster: str, swallow: tuple = (),
    ) -> FailoverReport:
        """Return ownership to a recovered region: re-sync its domain
        record, converge both directions (the conflict-resolution storm
        drains here), then a managed handover back. The report's
        conflict count covers the whole failback, convergence
        included."""
        reachable = list(self.handles)
        conflicts0 = self._conflicts(reachable)
        t0 = time.monotonic()
        drained = self.await_convergence(domain, swallow=swallow)
        # the inner handover must not emit: its window excludes the
        # convergence phase, so its handover_ms/conflicts would land in
        # the histogram plane as a fraction of the real drill — the
        # final report below is emitted once, convergence included
        report = self.managed_handover(
            domain, to_cluster, kind="failback", swallow=swallow,
            _emit=False,
        )
        report.handover_ms = (time.monotonic() - t0) * 1000.0
        report.drained_tasks += drained
        report.conflicts_resolved = self._conflicts(reachable) - conflicts0
        self._emit(report)
        return report

    # -- metrics -------------------------------------------------------

    def _emit(self, report: FailoverReport) -> None:
        scope = self._metrics.tagged(
            kind=report.kind, domain=report.domain
        )
        scope.inc("domain_failovers")
        scope.record("failover_handover_ms", report.handover_ms)
        scope.record(
            "failover_unavailability_ms", report.unavailability_ms
        )
        scope.gauge(
            "failover_replication_lag_at_promote",
            report.replication_lag_at_promote,
        )
        if report.conflicts_resolved > 0:
            scope.inc(
                "failover_conflicts_resolved", report.conflicts_resolved
            )
        logger.info(
            f"failover drill {report.kind}: {report.domain} "
            f"{report.from_cluster}->{report.to_cluster} "
            f"v{report.failover_version} "
            f"handover={report.handover_ms:.1f}ms "
            f"unavail={report.unavailability_ms:.1f}ms "
            f"lag@promote={report.replication_lag_at_promote} "
            f"conflicts={report.conflicts_resolved}"
        )
