"""Replicator queue: hydrate persisted replication tasks into messages.

Reference: service/history/replicatorQueueProcessor.go — reads the
shard's replication task queue, loads the event batch each task covers
from its history branch (getHistoryTaskV2 → ReadHistoryBranchByBatch),
attaches the version-history items, and serves them to remote pollers
via GetReplicationMessages (pull model). Acking completes tasks up to
the remote's last-processed ID.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.tasks import ReplicationTask

from ..persistence.errors import EntityNotExistsError
from ..persistence.records import BranchToken
from ..shard import ShardContext
from .messages import HistoryTaskV2, ReplicationMessages


class ReplicatorQueueProcessor:
    """Per-shard emit side of replication."""

    def __init__(
        self,
        shard: ShardContext,
        batch_size: int = 100,
        remote_clusters: Optional[List[str]] = None,
        metrics=None,
        faults=None,
        checkpoints=None,
    ) -> None:
        from cadence_tpu.utils.metrics import NOOP

        self.shard = shard
        self.batch_size = batch_size
        # chaos hook: fired per remote fetch BEFORE the ack/read, so an
        # injected fault leaves the cluster ack level untouched and the
        # remote's next poll simply retries (pull model is stateless)
        from ..queues.base import make_fault_hook

        self._fault_hook = make_fault_hook(
            faults, "replication.replicator_queue", shard_id=shard.shard_id
        )
        self._lock = threading.Lock()
        # last task id each remote cluster has confirmed processing —
        # pre-seeded with every configured remote so one cluster's ack
        # can't delete tasks another has yet to fetch
        self._cluster_ack: Dict[str, int] = {
            c: 0 for c in (remote_clusters or [])
        }
        self._metrics = (metrics or NOOP).tagged(
            service="history_replication", shard=str(shard.shard_id)
        )
        self._max_served = 0
        self._completed_through = 0  # highest min-ack already swept
        # snapshot-shipping serving plane: the engine-wired checkpoint
        # store when present (shipped rows persist and double as warm
        # rebuild seeds), else a lazily built transient store
        self._checkpoints = checkpoints
        self._snapshot_server = None

    # -- hydration ----------------------------------------------------

    def _read_batch(
        self, branch_token: bytes, first_event_id: int, next_event_id: int
    ) -> List[HistoryEvent]:
        if not branch_token:
            return []
        branch = BranchToken.from_json(branch_token.decode())
        batches, _ = self.shard.persistence.history.read_history_branch(
            branch, first_event_id, next_event_id
        )
        return [e for batch in batches for e in batch]

    def _version_history_items(
        self, task: ReplicationTask, events: List[HistoryEvent]
    ) -> List[Dict[str, int]]:
        """The version-history item list the passive side needs for LCA
        computation. Derived from the run's stored mutable state when
        available; falls back to the batch's own end item."""
        end_id = events[-1].event_id
        end_version = events[-1].version
        try:
            resp = self.shard.persistence.execution.get_workflow_execution(
                self.shard.shard_id, task.domain_id, task.workflow_id,
                task.run_id,
            )
            vh = (resp.snapshot or {}).get("version_histories")
            # VersionHistory.to_dict stores items as [event_id, version]
            # pairs (cadence_tpu/core/version_history.py to_dict).
            # Prefer the history whose branch_token matches the TASK's
            # branch — after a resolved conflict the workflow carries
            # several histories and picking by mere end-id coverage can
            # ship another branch's items, making the passive side see
            # "no common ancestor" and force a full resync
            want_branch = (
                task.branch_token.decode("latin-1")
                if isinstance(task.branch_token, bytes)
                else (task.branch_token or "")
            )
            histories = (vh or {}).get("histories", [])
            ranked = sorted(
                histories,
                key=lambda h: h.get("branch_token", "") != want_branch,
            ) if want_branch else histories
            for h in ranked:
                items = [
                    {"event_id": e, "version": v}
                    for e, v in h.get("items", [])
                ]
                if items and items[-1]["event_id"] >= end_id:
                    trimmed = [
                        dict(j) for j in items if j["event_id"] < end_id
                    ]
                    trimmed.append(
                        {"event_id": end_id, "version": end_version}
                    )
                    return trimmed
        except EntityNotExistsError:
            pass
        return [{"event_id": end_id, "version": end_version}]

    def hydrate(self, task: ReplicationTask) -> Optional[HistoryTaskV2]:
        events = self._read_batch(
            task.branch_token, task.first_event_id, task.next_event_id
        )
        if not events:
            return None
        new_run_events: List[HistoryEvent] = []
        new_run_id = ""
        if task.new_run_branch_token:
            # the continued run's FULL first transaction batch (Started +
            # DecisionTaskScheduled — active_transaction new-run close)
            branch = BranchToken.from_json(
                task.new_run_branch_token.decode()
            )
            # page_size=1 bounds the read to the first batch node — the
            # continued run may have grown arbitrarily since
            batches, _ = self.shard.persistence.history.read_history_branch(
                branch, 1, 1 << 60, page_size=1
            )
            new_run_events = list(batches[0]) if batches else []
            if new_run_events:
                new_run_id = new_run_events[0].attributes.get("run_id", "")
                if not new_run_id:
                    nb = BranchToken.from_json(
                        task.new_run_branch_token.decode()
                    )
                    new_run_id = nb.tree_id
        return HistoryTaskV2(
            task_id=task.task_id,
            domain_id=task.domain_id,
            workflow_id=task.workflow_id,
            run_id=task.run_id,
            version_history_items=self._version_history_items(task, events),
            events=events,
            new_run_events=new_run_events,
            new_run_id=new_run_id,
        )

    # -- pull API ------------------------------------------------------

    def get_replication_messages(
        self, cluster: str, last_retrieved_id: int,
        max_tasks: Optional[int] = None,
    ) -> ReplicationMessages:
        """Serve tasks after ``last_retrieved_id``; completing everything
        the remote has already confirmed (replicatorQueueProcessor.go
        getTasks: ack then read). ``max_tasks`` lets a bandwidth-aware
        consumer shrink the page below the static ``batch_size`` — a
        throttled link pulls pages its budget can afford instead of
        timing out on one giant hydrated transfer."""
        if self._fault_hook is not None:
            self._fault_hook("get_replication_messages", self.shard.shard_id)
        self.ack(cluster, last_retrieved_id)
        page = self.batch_size
        if max_tasks is not None:
            page = max(1, min(page, int(max_tasks)))
        tasks = self.shard.persistence.execution.get_replication_tasks(
            self.shard.shard_id, last_retrieved_id, page + 1
        )
        has_more = len(tasks) > page
        tasks = tasks[:page]
        out: List[HistoryTaskV2] = []
        last_id = last_retrieved_id
        for t in tasks:
            msg = self.hydrate(t)
            if msg is not None:
                out.append(msg)
            last_id = max(last_id, t.task_id)
        with self._lock:
            self._max_served = max(self._max_served, last_id)
            # how far this consumer trails the newest task this queue
            # has served (reference defs.go replication lag gauges)
            lag = self._max_served - self._cluster_ack.get(cluster, 0)
        self._metrics.tagged(cluster=cluster).gauge(
            "replication_ack_lag", max(0, lag)
        )
        return ReplicationMessages(
            tasks=out, last_retrieved_id=last_id, has_more=has_more,
            source_time_ns=self.shard.now(),
        )

    def get_replication_backlog(self, last_retrieved_id: int) -> dict:
        """Per-run backlog spans past the cursor WITHOUT event payloads
        — the adaptive consumer's cheap "how far behind am I" probe
        (transport.py). A few hundred bytes describe a backlog whose
        hydrated events could be megabytes, which is the whole point on
        a constrained link."""
        if self._fault_hook is not None:
            self._fault_hook("get_replication_backlog", self.shard.shard_id)
        runs: Dict[tuple, dict] = {}
        read_from = last_retrieved_id
        max_id = last_retrieved_id
        while True:
            tasks = self.shard.persistence.execution.get_replication_tasks(
                self.shard.shard_id, read_from, self.batch_size
            )
            if not tasks:
                break
            for t in tasks:
                max_id = max(max_id, t.task_id)
                key = (t.domain_id, t.workflow_id, t.run_id)
                rec = runs.get(key)
                if rec is None:
                    runs[key] = rec = {
                        "domain_id": t.domain_id,
                        "workflow_id": t.workflow_id,
                        "run_id": t.run_id,
                        "first_event_id": t.first_event_id,
                        "next_event_id": t.next_event_id,
                        "tasks": 0,
                    }
                rec["first_event_id"] = min(
                    rec["first_event_id"], t.first_event_id
                )
                rec["next_event_id"] = max(
                    rec["next_event_id"], t.next_event_id
                )
                rec["tasks"] += 1
            read_from = tasks[-1].task_id
        return {
            "runs": list(runs.values()),
            "max_task_id": max_id,
            "source_time_ns": self.shard.now(),
        }

    # -- snapshot shipping (bandwidth-adaptive state transfer) ---------

    def _snapshot_serving(self):
        """(StateRebuilder, CheckpointManager) used to SERVE snapshot
        requests. ``every_events=1`` so a serve-time rebuild always
        leaves a branch-tip snapshot in the store (the wired policy's
        cadence is a write-amplification knob for the rebuild path, not
        a serving constraint)."""
        if self._snapshot_server is None:
            from cadence_tpu.checkpoint import (
                CheckpointManager,
                CheckpointPolicy,
                MemoryCheckpointStore,
            )

            from .rebuilder import StateRebuilder

            store = (
                self._checkpoints.store
                if self._checkpoints is not None
                else MemoryCheckpointStore()
            )
            mgr = CheckpointManager(
                store, CheckpointPolicy(every_events=1, keep_last=2)
            )
            self._snapshot_server = (
                StateRebuilder(
                    self.shard.persistence.history,
                    checkpoints=mgr,
                ),
                mgr,
            )
        return self._snapshot_server

    def get_replication_checkpoint(
        self, domain_id: str, workflow_id: str, run_id: str
    ) -> bytes:
        """The run's branch-tip ``ReplayCheckpoint``, delta-compressed
        for the wire (transport.encode_checkpoint_wire), or ``b""``
        when no shippable snapshot exists (unknown run, capacity
        overflow, device plane unavailable) — the consumer then falls
        back to event shipping."""
        from ..persistence.records import current_version_history
        from .rebuilder import RebuildRequest
        from .transport import encode_checkpoint_wire

        if self._fault_hook is not None:
            self._fault_hook(
                "get_replication_checkpoint", self.shard.shard_id
            )
        try:
            resp = self.shard.persistence.execution.get_workflow_execution(
                self.shard.shard_id, domain_id, workflow_id, run_id
            )
        except EntityNotExistsError:
            return b""
        token, items = current_version_history(resp.snapshot)
        if not token or not items:
            return b""
        tip = items[-1][0]
        rb, mgr = self._snapshot_serving()
        ckpt, _ = mgr.lookup(token, version_history_items=items)
        if ckpt is None or ckpt.event_id < tip:
            # no tip snapshot on file: rebuild once (suffix-only when an
            # older snapshot exists) and pick up the row it wrote
            try:
                rb.rebuild_many([RebuildRequest(
                    domain_id=domain_id, workflow_id=workflow_id,
                    run_id=run_id, branch_token=token.encode(),
                    version_history_items=items,
                )])
            except Exception:
                return b""
            ckpt, _ = mgr.lookup(token, version_history_items=items)
            if ckpt is None or ckpt.event_id < tip:
                return b""
        try:
            return encode_checkpoint_wire(ckpt)
        except Exception:
            return b""

    def ack(self, cluster: str, level: int) -> None:
        """Complete tasks every remote cluster has retrieved."""
        with self._lock:
            prev = self._cluster_ack.get(cluster, 0)
            if level <= prev and prev != 0:
                return
            self._cluster_ack[cluster] = level
            min_ack = min(self._cluster_ack.values())
            # skip the store scan when the MIN cursor hasn't moved —
            # every fetch calls ack(), and an unconditional scan from 0
            # is a wasted queue read per poll per cluster per shard
            if min_ack <= self._completed_through:
                return
            self._completed_through = min_ack
        if min_ack <= 0:
            return
        # scan the whole completed prefix, not just one batch
        read_from = 0
        while True:
            done = self.shard.persistence.execution.get_replication_tasks(
                self.shard.shard_id, read_from, self.batch_size
            )
            if not done:
                return
            for t in done:
                if t.task_id <= min_ack:
                    self.shard.persistence.execution.complete_replication_task(
                        self.shard.shard_id, t.task_id
                    )
            read_from = done[-1].task_id
            if read_from > min_ack:
                return
