"""State rebuilder: (history branch) → fresh MutableState + tasks.

Reference: service/history/nDCStateRebuilder.go:92-160 — page through
ReadHistoryBranchByBatch, replay every batch through a fresh
stateBuilder, close as snapshot, refresh tasks.

TPU-native twist: ``rebuild_many`` is the batched path — it packs N
runs' histories into the dense ``[B, T, E]`` tensor and rebuilds all of
them in ONE replay_scan on device (the north-star replication-storm /
conflict-resolution-storm configuration), falling back per-workflow to
the host oracle when a history exceeds device capacities.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.core.state_builder import StateBuilder
from cadence_tpu.core.task_refresher import refresh_tasks
from cadence_tpu.core.version_history import VersionHistories

from ..persistence.interfaces import HistoryManager
from ..persistence.records import BranchToken


class RebuildRequest:
    """One run to rebuild."""

    def __init__(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        branch_token: bytes,
        next_event_id: int = 0,
        request_id: str = "rebuild",
    ) -> None:
        self.domain_id = domain_id
        self.workflow_id = workflow_id
        self.run_id = run_id
        self.branch_token = branch_token
        self.next_event_id = next_event_id
        self.request_id = request_id


class StateRebuilder:
    def __init__(self, history: HistoryManager,
                 domain_resolver=lambda name: name,
                 chunk_size=0, lane_len: int = 1024) -> None:
        self.history = history
        self.domain_resolver = domain_resolver
        # device-dispatch chunk for rebuild_many: an int, or a callable
        # re-read every resolve (dynamicconfig history.rebuildChunkSize
        # via bootstrap stays live-tunable); 0 = backend default
        self.chunk_size = chunk_size
        # lane capacity (events) for ragged lane packing in
        # rebuild_many: shallow histories pack back-to-back into lanes
        # of this length instead of each padding a lane to max(depth)
        self.lane_len = lane_len
        self._backend_chunk = 0

    def _resolve_chunk(self) -> int:
        configured = (
            self.chunk_size() if callable(self.chunk_size)
            else self.chunk_size
        )
        if configured and configured > 0:
            return int(configured)
        if self._backend_chunk:
            return self._backend_chunk
        # Dispatch overhead is per-call (probe r4: ~21ms fixed vs
        # ~1.4ms per 8k-row tile through the tunnel), so the device
        # chunk should be as large as the chip comfortably holds —
        # measured-optimal >=32k rows on TPU. CPU test meshes keep the
        # small chunk (compile time scales with B there).
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
        self._backend_chunk = 32768 if backend == "tpu" else 4096
        return self._backend_chunk

    # -- history paging ------------------------------------------------

    def _read_batches(self, req: RebuildRequest) -> List[List[HistoryEvent]]:
        branch = BranchToken.from_json(req.branch_token.decode())
        out: List[List[HistoryEvent]] = []
        token = 0
        while True:
            batches, token = self.history.read_history_branch(
                branch, 1, req.next_event_id or 1 << 60,
                page_size=256, next_token=token,
            )
            out.extend(batches)
            if not token:
                return out

    # -- single rebuild (host oracle) ----------------------------------

    def rebuild(self, req: RebuildRequest) -> Tuple[MutableState, list, list]:
        """Replay one run from scratch; returns (ms, transfer, timer)."""
        batches = self._read_batches(req)
        if not batches:
            raise ValueError(
                f"rebuild: empty history for {req.workflow_id}/{req.run_id}"
            )
        ms = MutableState(domain_id=req.domain_id)
        ms.version_histories = VersionHistories.new_empty()
        sb = StateBuilder(ms, domain_resolver=self.domain_resolver)
        sb.apply_batches(
            req.domain_id, req.request_id, req.workflow_id, req.run_id,
            batches,
        )
        ms.execution_info.branch_token = req.branch_token
        transfer, timer = refresh_tasks(ms)
        return ms, transfer, timer

    # -- batched rebuild (device) --------------------------------------

    def rebuild_many(
        self, reqs: Sequence[RebuildRequest], use_device: bool = True,
    ) -> List[Tuple[MutableState, list, list]]:
        """Rebuild N runs at once. The device path packs all histories
        into one [B, T, E] tensor, replays them in a single vmapped scan,
        and rehydrates MutableState per row; any run the packer cannot
        express (capacity overflow, payload-dependent transition) falls
        back to the host oracle."""
        if not use_device or len(reqs) == 0:
            return [self.rebuild(r) for r in reqs]

        histories = []
        for r in reqs:
            histories.append((r.workflow_id, r.run_id, self._read_batches(r)))

        try:
            import jax  # noqa: F401 — device path needs a usable jax

            from cadence_tpu.ops.dispatch import (
                DeviceDispatcher,
                DispatchError,
                depth_buckets,
            )
            from cadence_tpu.ops.unpack import state_row_to_mutable_state
        except Exception:  # jax unavailable — host path
            return [self.rebuild(r) for r in reqs]

        # storm drain: depth-bucket the stream (a few deep stragglers
        # must not stretch every lane), lane-pack each bucket (several
        # whole histories per scan lane), and pump the chunks through
        # the double-buffered host→device dispatcher (ops/dispatch.py)
        # so packing batch k+1 overlaps replaying batch k; each failed
        # chunk (capacity overflow etc.) falls back per-workflow to the
        # host oracle
        chunk = self._resolve_chunk()
        out: List[Optional[Tuple[MutableState, list, list]]] = (
            [None] * len(reqs)
        )
        d = DeviceDispatcher(
            domain_resolver=self.domain_resolver, lane_pack=True,
            lane_len=self.lane_len,
        )
        for idxs, hs in depth_buckets(histories):
            for j in range(0, len(hs), chunk):
                d.submit(idxs[j : j + chunk], hs[j : j + chunk])
        d.finish()
        for item in d.results(strict=False):
            if isinstance(item, DispatchError):
                for gi in item.batch_id:
                    out[gi] = self.rebuild(reqs[gi])
                continue
            idxs, packed, final = item
            for j, gi in enumerate(idxs):
                r = reqs[gi]
                ms = state_row_to_mutable_state(
                    final, j, packed.side[j],
                    domain_id=r.domain_id, epoch_s=packed.epoch_s,
                )
                ms.execution_info.branch_token = r.branch_token
                transfer, timer = refresh_tasks(ms)
                out[gi] = (ms, transfer, timer)
        return out
