"""State rebuilder: (history branch) → fresh MutableState + tasks.

Reference: service/history/nDCStateRebuilder.go:92-160 — page through
ReadHistoryBranchByBatch, replay every batch through a fresh
stateBuilder, close as snapshot, refresh tasks.

TPU-native twist: ``rebuild_many`` is the batched path — it packs N
runs' histories into the dense ``[B, T, E]`` tensor and rebuilds all of
them in ONE replay_scan on device (the north-star replication-storm /
conflict-resolution-storm configuration), falling back per-workflow to
the host oracle when a history exceeds device capacities.

Checkpointed incremental replay (cadence_tpu/checkpoint/): with a
``CheckpointManager`` attached, ``rebuild_many`` consults the store per
request, fetches only the event SUFFIX past the newest valid snapshot,
seeds the packed scan's per-segment carry from the snapshot row, and
writes fresh checkpoints from the rebuilt state — repeat rebuilds cost
O(new events) instead of O(depth). A checkpoint at the branch tip skips
the device entirely (rehydrate + task refresh). Any checkpoint-plane
failure degrades that request to a full replay.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.core.state_builder import StateBuilder
from cadence_tpu.core.task_refresher import refresh_tasks
from cadence_tpu.core.version_history import VersionHistories
from cadence_tpu.utils.metrics import NOOP

from ..persistence.interfaces import HistoryManager
from ..persistence.records import BranchToken


class RebuildRequest:
    """One run to rebuild.

    ``version_history_items``: the target branch's (event_id, version)
    items when the caller knows them (the NDC conflict path does) —
    the checkpoint manager's divergence guard, and the key that lets a
    forked branch resume from a sibling's snapshot below the LCA.
    """

    def __init__(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        branch_token: bytes,
        next_event_id: int = 0,
        request_id: str = "rebuild",
        version_history_items: Optional[Sequence[Tuple[int, int]]] = None,
    ) -> None:
        self.domain_id = domain_id
        self.workflow_id = workflow_id
        self.run_id = run_id
        self.branch_token = branch_token
        self.next_event_id = next_event_id
        self.request_id = request_id
        self.version_history_items = version_history_items


class StateRebuilder:
    def __init__(self, history: HistoryManager,
                 domain_resolver=lambda name: name,
                 chunk_size=0, lane_len: int = 1024,
                 checkpoints=None, metrics=None, serving=None) -> None:
        self.history = history
        self.domain_resolver = domain_resolver
        # device-dispatch chunk for rebuild_many: an int, or a callable
        # re-read every resolve (dynamicconfig history.rebuildChunkSize
        # via bootstrap stays live-tunable); 0 = backend default
        self.chunk_size = chunk_size
        # lane capacity (events) for ragged lane packing in
        # rebuild_many: shallow histories pack back-to-back into lanes
        # of this length instead of each padding a lane to max(depth)
        self.lane_len = lane_len
        # checkpoint.CheckpointManager (or None: every rebuild is cold)
        self.checkpoints = checkpoints
        # serving.ResidentEngine (config `serving:` section, or None):
        # a rebuild whose target tip + branch + version histories match
        # a resident lane rehydrates from the row — no history read, no
        # replay (counted as serving_resident_hits)
        self.serving = serving
        # checkpoint_hit/miss/invalidated + events_replayed_saved land
        # here (utils/metrics_defs.py CHECKPOINT_METRICS); the raw scope
        # also feeds the dispatcher's device-step telemetry
        # (DEVICE_METRICS) — None disables both planes together
        self._raw_metrics = metrics
        self._metrics = (metrics if metrics is not None else NOOP).tagged(
            layer="checkpoint"
        )
        self._backend_chunk = 0

    def _resolve_chunk(self) -> int:
        configured = (
            self.chunk_size() if callable(self.chunk_size)
            else self.chunk_size
        )
        if configured and configured > 0:
            return int(configured)
        if self._backend_chunk:
            return self._backend_chunk
        # Dispatch overhead is per-call (probe r4: ~21ms fixed vs
        # ~1.4ms per 8k-row tile through the tunnel), so the device
        # chunk should be as large as the chip comfortably holds —
        # measured-optimal >=32k rows on TPU. CPU test meshes keep the
        # small chunk (compile time scales with B there).
        try:
            import jax

            backend = jax.default_backend()
        except Exception:
            backend = "cpu"
        self._backend_chunk = 32768 if backend == "tpu" else 4096
        return self._backend_chunk

    # -- history paging ------------------------------------------------

    def _read_batches(
        self, req: RebuildRequest, min_event_id: int = 1,
    ) -> List[List[HistoryEvent]]:
        branch = BranchToken.from_json(req.branch_token.decode())
        out: List[List[HistoryEvent]] = []
        token = 0
        while True:
            batches, token = self.history.read_history_branch(
                branch, min_event_id, req.next_event_id or 1 << 60,
                page_size=256, next_token=token,
            )
            out.extend(batches)
            if not token:
                return out

    # -- single rebuild (host oracle) ----------------------------------

    def rebuild(self, req: RebuildRequest) -> Tuple[MutableState, list, list]:
        """Replay one run from scratch; returns (ms, transfer, timer)."""
        batches = self._read_batches(req)
        if not batches:
            raise ValueError(
                f"rebuild: empty history for {req.workflow_id}/{req.run_id}"
            )
        ms = MutableState(domain_id=req.domain_id)
        ms.version_histories = VersionHistories.new_empty()
        sb = StateBuilder(ms, domain_resolver=self.domain_resolver)
        sb.apply_batches(
            req.domain_id, req.request_id, req.workflow_id, req.run_id,
            batches,
        )
        ms.execution_info.branch_token = req.branch_token
        transfer, timer = refresh_tasks(ms)
        return ms, transfer, timer

    # -- batched rebuild (device) --------------------------------------

    # -- checkpoint consult --------------------------------------------

    def _consult_serving(self, req: RebuildRequest):
        """Rehydrate one rebuild from a resident serving lane, or None.

        Sound only under exact-match guards: the caller pinned the
        target tip (``next_event_id``) and the lane is at it, the lane
        was seated from the SAME branch, and — when the caller supplied
        them (the NDC path) — the lane's version-history items equal
        the target's. Anything else (including a dirty lane the engine
        fails to compose) falls through to the checkpoint/cold path.
        Never raises."""
        if self.serving is None or not req.next_event_id:
            return None
        try:
            from cadence_tpu.ops import schema as S

            got = self.serving.resident_row(
                req.workflow_id, req.run_id, domain_id=req.domain_id
            )
            if got is None:
                return None
            if got.branch_token and got.branch_token != req.branch_token:
                return None
            row = got.state_row
            tip = int(row["exec_info"][S.X_NEXT_EVENT_ID])
            if tip != req.next_event_id:
                return None
            if req.version_history_items is not None:
                n = int(row["vh_len"])
                items = [
                    (int(e), int(v)) for e, v in row["vh_items"][:n]
                ]
                want = [
                    (int(e), int(v))
                    for e, v in req.version_history_items
                ]
                if items != want:
                    return None
            ms = got.mutable_state()
        except Exception:
            return None
        ms.execution_info.branch_token = req.branch_token
        transfer, timer = refresh_tasks(ms)
        (self._raw_metrics if self._raw_metrics is not None else NOOP
         ).tagged(layer="serving").inc("serving_resident_hits")
        return ms, transfer, timer

    def _consult_checkpoint(self, req: RebuildRequest, caps):
        """The resumable checkpoint for one request, or None; never
        raises. Misses/invalidations count here (they are final); a HIT
        counts only once the resume actually sticks
        (``_commit_hit``/``_degrade_hit``) so a degraded resume reports
        as the full replay it became, not as savings."""
        from cadence_tpu.checkpoint.manager import HIT

        if self.checkpoints is None:
            return None
        try:
            ckpt, status = self.checkpoints.lookup(
                req.branch_token, caps=caps,
                version_history_items=req.version_history_items,
                max_event_id=(
                    req.next_event_id - 1 if req.next_event_id else None
                ),
            )
        except Exception:
            self._metrics.inc("checkpoint_miss")
            return None
        if status == HIT and ckpt is not None:
            return ckpt
        self._metrics.inc(f"checkpoint_{status}")
        return None

    def _commit_hit(self, ckpt) -> None:
        self._metrics.inc("checkpoint_hit")
        # events before the snapshot are never read or replayed
        self._metrics.inc("events_replayed_saved", ckpt.event_id)

    def _degrade_hit(self) -> None:
        self._metrics.inc("checkpoint_miss")

    def _record_checkpoint(self, req, packed, final, row) -> None:
        if self.checkpoints is None:
            return
        self.checkpoints.maybe_record(
            req.branch_token, final, row, packed.side[row],
            epoch_s=packed.epoch_s, caps=packed.caps,
            domain_id=req.domain_id, workflow_id=req.workflow_id,
            run_id=req.run_id,
        )

    def rebuild_many(
        self, reqs: Sequence[RebuildRequest], use_device: bool = True,
    ) -> List[Tuple[MutableState, list, list]]:
        """Rebuild N runs at once. The device path packs all histories
        into one [B, T, E] tensor, replays them in a single vmapped scan,
        and rehydrates MutableState per row; any run the packer cannot
        express (capacity overflow, payload-dependent transition) falls
        back to the host oracle.

        With a checkpoint manager attached each request first looks up
        its newest valid snapshot: hits read + replay only the event
        suffix (the snapshot row seeds the segment carry), tip hits skip
        the device entirely, and the rebuilt tips are written back as
        fresh checkpoints per the manager's policy."""
        if not use_device or len(reqs) == 0:
            return [self.rebuild(r) for r in reqs]

        try:
            import jax  # noqa: F401 — device path needs a usable jax

            from cadence_tpu.ops.dispatch import (
                DeviceDispatcher,
                DispatchError,
                depth_buckets,
            )
            from cadence_tpu.ops.unpack import state_row_to_mutable_state
        except Exception:  # jax unavailable — host path
            return [self.rebuild(r) for r in reqs]

        from cadence_tpu.ops import schema as S
        from cadence_tpu.ops.grid import staging_depth

        out: List[Optional[Tuple[MutableState, list, list]]] = (
            [None] * len(reqs)
        )
        caps = S.Capacities()

        # consult checkpoints, read only what must be replayed
        histories = []           # pending (wf, run, suffix batches)
        resumes = []             # aligned Optional[ResumeState]
        pend_req: List[int] = []  # pending index -> request index
        for gi, r in enumerate(reqs):
            hit = self._consult_serving(r)
            if hit is not None:
                out[gi] = hit
                continue
            ckpt = self._consult_checkpoint(r, caps)
            if ckpt is None:
                batches = self._read_batches(r)
                resume = None
            else:
                try:
                    batches = self._read_batches(
                        r, min_event_id=ckpt.event_id + 1
                    )
                    resume = self.checkpoints.resume_state(ckpt)
                except Exception:  # degraded store/decode: full replay
                    batches, resume = self._read_batches(r), None
                    self._degrade_hit()
                if resume is not None and not batches:
                    # tip hit: nothing to replay — rehydrate directly
                    try:
                        ms = self.checkpoints.rehydrate(
                            ckpt, domain_id=r.domain_id
                        )
                        ms.execution_info.branch_token = r.branch_token
                        transfer, timer = refresh_tasks(ms)
                        out[gi] = (ms, transfer, timer)
                        self._commit_hit(ckpt)
                        continue
                    except Exception:
                        batches, resume = self._read_batches(r), None
                        self._degrade_hit()
                if resume is not None:
                    self._commit_hit(ckpt)
            histories.append((r.workflow_id, r.run_id, batches))
            resumes.append(resume)
            pend_req.append(gi)

        # storm drain: depth-bucket the stream (a few deep stragglers
        # must not stretch every lane; a resumed run buckets by its
        # SUFFIX depth), lane-pack each bucket (several whole histories
        # per scan lane), and pump the chunks through the
        # double-buffered host→device dispatcher (ops/dispatch.py) so
        # packing batch k+1 overlaps replaying batch k; each failed
        # chunk (capacity overflow etc.) falls back per-workflow to the
        # host oracle
        chunk = self._resolve_chunk()
        plan = []
        for idxs, hs in depth_buckets(histories):
            for j in range(0, len(hs), chunk):
                plan.append((idxs[j : j + chunk], hs[j : j + chunk]))
        if not plan:
            return out
        # the dispatcher is built only once the chunk plan exists, so
        # its staging buffer is sized per batch (staging_depth) — the
        # one-chunk serving/small-rebuild shape gets a one-slot queue
        d = DeviceDispatcher(
            caps=caps, depth=staging_depth(len(plan)),
            domain_resolver=self.domain_resolver, lane_pack=True,
            lane_len=self.lane_len, metrics=self._raw_metrics,
        )
        for sub, hs in plan:
            d.submit(
                tuple(pend_req[i] for i in sub),
                hs,
                resume=[resumes[i] for i in sub],
            )
        d.finish()
        for item in d.results(strict=False):
            if isinstance(item, DispatchError):
                for gi in item.batch_id:
                    out[gi] = self.rebuild(reqs[gi])
                continue
            idxs, packed, final = item
            for j, gi in enumerate(idxs):
                r = reqs[gi]
                ms = state_row_to_mutable_state(
                    final, j, packed.side[j],
                    domain_id=r.domain_id, epoch_s=packed.epoch_s,
                )
                ms.execution_info.branch_token = r.branch_token
                transfer, timer = refresh_tasks(ms)
                out[gi] = (ms, transfer, timer)
                self._record_checkpoint(r, packed, final, j)
        return out
