"""Pull-model replication consumers.

Reference: service/history/replicationTaskFetcher.go:65-247 (per remote
cluster, batched GetReplicationMessages RPCs) and
replicationTaskProcessor.go:85-434 (applies fetched tasks to the local
engine, converts RetryTaskV2 errors into re-replication, acks progress
back to the source on the next fetch).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.task_processor import KeyedSequentialProcessor

from ..shard import ShardContext
from .messages import HistoryTaskV2, ReplicationMessages, RetryTaskV2Error
from .ndc import NDCHistoryReplicator
from .rereplicator import HistoryRereplicator

logger = get_logger("cadence_tpu.replication")


class RemoteClusterClient:
    """What a fetcher needs from a remote cluster (implemented by the
    remote cluster's history service / admin handler in-process, or a
    gRPC stub across hosts)."""

    def get_replication_messages(
        self, shard_id: int, last_retrieved_id: int
    ) -> ReplicationMessages:
        raise NotImplementedError

    def get_workflow_history_raw(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        start_event_id: int,
        end_event_id: int,
    ):
        raise NotImplementedError


class ReplicationTaskFetcher:
    """Per-remote-cluster fetch plane; one instance serves all local
    shards (the reference aggregates per-shard requests into one RPC —
    here the aggregation is a shared client + per-shard cursor)."""

    def __init__(
        self, cluster: str, client: RemoteClusterClient,
    ) -> None:
        self.cluster = cluster
        self.client = client
        self._cursor: Dict[int, int] = {}
        self._lock = threading.Lock()

    def last_retrieved(self, shard_id: int) -> int:
        with self._lock:
            return self._cursor.get(shard_id, 0)

    def fetch(self, shard_id: int) -> ReplicationMessages:
        """Read past the committed cursor WITHOUT advancing it — the
        processor commits only after tasks apply, so a failed apply is
        re-fetched (at-least-once, matching the reference's
        lastProcessedMessageId ack)."""
        return self.client.get_replication_messages(
            shard_id, self.last_retrieved(shard_id)
        )

    def commit(self, shard_id: int, applied_through: int) -> None:
        with self._lock:
            if applied_through > self._cursor.get(shard_id, 0):
                self._cursor[shard_id] = applied_through


class ReplicationTaskProcessor:
    """Applies one remote cluster's replication stream to one shard."""

    def __init__(
        self,
        shard: ShardContext,
        replicator: NDCHistoryReplicator,
        fetcher: ReplicationTaskFetcher,
        rereplicator: Optional[HistoryRereplicator] = None,
        max_retry: int = 3,
        metrics=None,
    ) -> None:
        from cadence_tpu.utils.metrics import NOOP

        self.shard = shard
        self.replicator = replicator
        self.fetcher = fetcher
        self.rereplicator = rereplicator
        self.max_retry = max_retry
        self._metrics = (metrics or NOOP).tagged(
            service="history_replication", shard=str(shard.shard_id),
            cluster=fetcher.cluster,
        )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-workflow-sequential, cross-workflow-parallel fallback
        # apply plane; created on first use, recreated after stop() so
        # a stop/start cycle (or a post-stop synchronous drain) works
        self._seq: Optional[KeyedSequentialProcessor] = None

    def _get_seq(self) -> KeyedSequentialProcessor:
        if self._seq is None or self._seq.is_shutdown:
            self._seq = KeyedSequentialProcessor(
                worker_count=4, name=f"repl-{self.shard.shard_id}"
            )
        return self._seq

    # -- synchronous drain (tests + backlog catch-up) ------------------

    # flush budget for the keyed fallback; drain() shrinks it to
    # fit its own deadline so a failover drain isn't held hostage
    # by one slow apply
    APPLY_FLUSH_TIMEOUT_S = 120.0

    def process_once(self) -> int:
        """One fetch + apply cycle; returns number of tasks applied.

        The whole fetched cycle drains through the replicator's batched
        path first (conflict rebuilds across the cycle collapse into one
        device scan — the replication-storm configuration); the cursor
        then commits through the cycle. On any batch failure it falls
        back to the sequential per-task path, which commits per task and
        converts RetryTaskV2 errors into re-replication — a re-fetched
        duplicate is detected and skipped by version-history bookkeeping
        (at-least-once, matching the reference's lastProcessedMessageId
        ack)."""
        import time as _time

        t0 = _time.perf_counter()
        applied = self._process_cycle()
        if applied:
            self._metrics.inc("replication_tasks_applied", applied)
            self._metrics.record(
                "replication_apply_latency", _time.perf_counter() - t0
            )
        return applied

    def _process_cycle(self) -> int:
        msgs = self.fetcher.fetch(self.shard.shard_id)
        if msgs.source_time_ns:
            # the stream carries the source cluster's clock; standby
            # timer processing fires against it (ref syncShardStatus)
            self.shard.set_remote_cluster_current_time(
                self.fetcher.cluster, msgs.source_time_ns
            )
        if not msgs.tasks:
            # nothing to apply in the range: safe to move past it
            self.fetcher.commit(self.shard.shard_id, msgs.last_retrieved_id)
            return 0
        if len(msgs.tasks) > 1:
            try:
                self.replicator.apply_events_batch(msgs.tasks)
                self.fetcher.commit(
                    self.shard.shard_id, msgs.tasks[-1].task_id
                )
                return len(msgs.tasks)
            except Exception:
                # sequential fallback below re-applies idempotently; a
                # persistent failure here means every cycle pays double
                # work, so make it visible
                logger.exception(
                    "batched replication drain failed; falling back to "
                    "sequential apply", shard=self.shard.shard_id,
                )
        return self._apply_keyed(msgs.tasks)

    def _apply_keyed(self, tasks) -> int:
        """Per-task fallback: runs sequentially PER WORKFLOW (a
        continue-as-new chain's runs must apply in order — the batched
        path barriers on the same key), concurrently across workflows
        (reference: replication tasks feed a keyed sequential task
        processor, common/task/sequentialTaskProcessor.go). The cursor
        commits through the longest finished-and-successful prefix, so
        a failed or still-running task re-fetches while already-applied
        peers dedup via version-history bookkeeping."""
        failures: List[tuple] = []  # (task_id, exception)
        flock = threading.Lock()

        def run(t: HistoryTaskV2) -> None:
            try:
                self._process_task(t)
            except Exception as e:
                with flock:
                    failures.append((t.task_id, e))
                logger.exception(
                    "replication task apply failed",
                    shard=self.shard.shard_id, task_id=t.task_id,
                    workflow=t.workflow_id,
                )

        seq = self._get_seq()
        for task in tasks:
            seq.submit(
                (task.domain_id, task.workflow_id),
                lambda t=task: run(t),
            )
        if not seq.flush(timeout_s=self.APPLY_FLUSH_TIMEOUT_S):
            # tasks still in flight: committing past them could lose
            # them forever (the cursor only moves forward). Raise —
            # returning 0 would read as "stream quiescent" to a
            # failover drain while work is still outstanding
            raise TimeoutError(
                f"shard {self.shard.shard_id}: keyed replication apply "
                "timed out with work in flight"
            )
        cutoff = min(tid for tid, _ in failures) if failures else None
        applied = 0
        last_ok = None
        for task in tasks:
            if cutoff is not None and task.task_id >= cutoff:
                break
            last_ok = task.task_id
            applied += 1
        if last_ok is not None:
            # the cursor is a monotonic watermark: one commit covers
            # the whole successful prefix
            self.fetcher.commit(self.shard.shard_id, last_ok)
        if applied == 0 and failures:
            # no progress at all: surface the failure to the caller
            # (drain()/pump) exactly like the old sequential loop did
            raise failures[0][1]
        return applied

    def drain_tasks(self, max_rounds: int = 100) -> int:
        """Pull+apply until a fetch comes back empty; returns the task
        count (test/assembly harness surface)."""
        total = 0
        for _ in range(max_rounds):
            n = self.process_once()
            total += n
            if n == 0:
                return total
        return total

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Queue-processor drain contract (HistoryService.drain_queues):
        True when the remote stream is quiescent within the budget. The
        keyed-apply flush budget shrinks to the caller's deadline for
        the duration — a single hung apply must not turn a 5s drain
        into a 120s stall."""
        deadline = time.monotonic() + timeout_s
        saved = self.APPLY_FLUSH_TIMEOUT_S
        self.APPLY_FLUSH_TIMEOUT_S = max(0.5, timeout_s)
        try:
            while time.monotonic() < deadline:
                if self.process_once() == 0:
                    return True
        finally:
            self.APPLY_FLUSH_TIMEOUT_S = saved
        return False

    def _process_task(self, task: HistoryTaskV2) -> None:
        for attempt in range(self.max_retry):
            try:
                self.replicator.apply_events(task)
                return
            except RetryTaskV2Error as e:
                if self.rereplicator is None or attempt == self.max_retry - 1:
                    raise
                self.rereplicator.rereplicate(e)

    # -- background pump -----------------------------------------------

    def start(self, interval_s: float = 0.05) -> None:
        if self._thread is not None:
            return

        def pump() -> None:
            while not self._stop.is_set():
                try:
                    if self.process_once() == 0:
                        self._stop.wait(interval_s)
                except Exception:
                    logger.exception(
                        "replication pump cycle failed",
                        shard=self.shard.shard_id,
                        cluster=self.fetcher.cluster,
                    )
                    self._stop.wait(interval_s)

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._seq is not None:
            # wait=False: a hung apply must not turn the bounded stop()
            # into an indefinite block (the pool threads are abandoned;
            # the interpreter reaps them at exit)
            self._seq.shutdown(wait=False)
