"""Pull-model replication consumers.

Reference: service/history/replicationTaskFetcher.go:65-247 (per remote
cluster, batched GetReplicationMessages RPCs) and
replicationTaskProcessor.go:85-434 (applies fetched tasks to the local
engine, converts RetryTaskV2 errors into re-replication, acks progress
back to the source on the next fetch).
"""

from __future__ import annotations

import collections
import json
import random
import threading
import time
from typing import Callable, Dict, List, Optional

from cadence_tpu.utils.backoff import BackoffLadder
from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.task_processor import KeyedSequentialProcessor

from ..persistence.errors import ConditionFailedError
from ..shard import ShardContext
from .messages import HistoryTaskV2, ReplicationMessages, RetryTaskV2Error
from .ndc import NDCHistoryReplicator
from .rereplicator import HistoryRereplicator

logger = get_logger("cadence_tpu.replication")


class RemoteClusterClient:
    """What a fetcher needs from a remote cluster (implemented by the
    remote cluster's history service / admin handler in-process, or a
    gRPC stub across hosts)."""

    def get_replication_messages(
        self, shard_id: int, last_retrieved_id: int,
        max_tasks: Optional[int] = None,
    ) -> ReplicationMessages:
        raise NotImplementedError

    def get_workflow_history_raw(
        self,
        domain_id: str,
        workflow_id: str,
        run_id: str,
        start_event_id: int,
        end_event_id: int,
    ):
        raise NotImplementedError


class ReplicationTaskFetcher:
    """Per-remote-cluster fetch plane; one instance serves all local
    shards (the reference aggregates per-shard requests into one RPC —
    here the aggregation is a shared client + per-shard cursor)."""

    def __init__(
        self, cluster: str, client: RemoteClusterClient,
    ) -> None:
        self.cluster = cluster
        self.client = client
        self._cursor: Dict[int, int] = {}
        self._lock = threading.Lock()

    def last_retrieved(self, shard_id: int) -> int:
        with self._lock:
            return self._cursor.get(shard_id, 0)

    def fetch(self, shard_id: int,
              max_tasks: Optional[int] = None) -> ReplicationMessages:
        """Read past the committed cursor WITHOUT advancing it — the
        processor commits only after tasks apply, so a failed apply is
        re-fetched (at-least-once, matching the reference's
        lastProcessedMessageId ack). ``max_tasks`` caps the emit page
        (the adaptive transport's per-link paging); None keeps the
        emit side's static default."""
        if max_tasks is None:
            return self.client.get_replication_messages(
                shard_id, self.last_retrieved(shard_id)
            )
        return self.client.get_replication_messages(
            shard_id, self.last_retrieved(shard_id), max_tasks=max_tasks
        )

    def commit(self, shard_id: int, applied_through: int) -> None:
        with self._lock:
            if applied_through > self._cursor.get(shard_id, 0):
                self._cursor[shard_id] = applied_through


class ReplicationTaskProcessor:
    """Applies one remote cluster's replication stream to one shard."""

    def __init__(
        self,
        shard: ShardContext,
        replicator: NDCHistoryReplicator,
        fetcher: ReplicationTaskFetcher,
        rereplicator: Optional[HistoryRereplicator] = None,
        max_retry: int = 3,
        metrics=None,
        transport=None,
        backoff_max_s: float = 5.0,
    ) -> None:
        from cadence_tpu.utils.metrics import NOOP

        self.shard = shard
        self.replicator = replicator
        self.fetcher = fetcher
        self.rereplicator = rereplicator
        self.max_retry = max_retry
        self._metrics = (metrics or NOOP).tagged(
            service="history_replication", shard=str(shard.shard_id),
            cluster=fetcher.cluster,
        )
        # bandwidth-adaptive transport (transport.AdaptiveTransport),
        # shared per remote cluster like the fetcher; None = the
        # pre-adaptive pure event-stream consumer, byte-for-byte
        self.transport = transport
        # a failed cycle's retry delay doubles up to this cap (jittered)
        # and resets on the first successful cycle
        self.backoff_max_s = backoff_max_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._backoff_rng = random.Random(shard.shard_id)
        # deferred history backfill owed by snapshot-shipped catch-ups:
        # (domain_id, workflow_id, run_id, from_event_id, through_id)
        self._backfill = collections.deque()
        # how many fetch attempts one owed range gets before it is
        # abandoned (loudly): a source that GC'd the range can never
        # serve it, and one poison item must not wedge the whole plane
        self.backfill_max_attempts = 8
        self._backfill_attempts: Dict[tuple, int] = {}
        if self.rereplicator is not None and transport is not None:
            # the processor owns the deferred-backfill sink; the
            # transport is only filled in when the caller didn't wire
            # one (never clobber an explicit choice)
            if self.rereplicator.transport is None:
                self.rereplicator.transport = transport
            self.rereplicator.backfill_sink = self._enqueue_backfill
        # durable replication progress (cursor + mode), keyed
        # (shard, cluster); absent on pre-v5 stores → in-memory only
        self._progress_supported = hasattr(
            shard.persistence.shard, "get_replication_progress"
        )
        self._persisted_cursor = 0
        self._persisted_debt: tuple = ()
        self._restore_progress()
        # per-workflow-sequential, cross-workflow-parallel fallback
        # apply plane; created on first use, recreated after stop() so
        # a stop/start cycle (or a post-stop synchronous drain) works
        self._seq: Optional[KeyedSequentialProcessor] = None

    def _get_seq(self) -> KeyedSequentialProcessor:
        if self._seq is None or self._seq.is_shutdown:
            self._seq = KeyedSequentialProcessor(
                worker_count=4, name=f"repl-{self.shard.shard_id}"
            )
        return self._seq

    # -- synchronous drain (tests + backlog catch-up) ------------------

    # flush budget for the keyed fallback; drain() shrinks it to
    # fit its own deadline so a failover drain isn't held hostage
    # by one slow apply
    APPLY_FLUSH_TIMEOUT_S = 120.0

    def process_once(self) -> int:
        """One fetch + apply cycle; returns number of tasks applied.

        The whole fetched cycle drains through the replicator's batched
        path first (conflict rebuilds across the cycle collapse into one
        device scan — the replication-storm configuration); the cursor
        then commits through the cycle. On any batch failure it falls
        back to the sequential per-task path, which commits per task and
        converts RetryTaskV2 errors into re-replication — a re-fetched
        duplicate is detected and skipped by version-history bookkeeping
        (at-least-once, matching the reference's lastProcessedMessageId
        ack)."""
        import time as _time

        t0 = _time.perf_counter()
        applied = self._process_cycle()
        if applied:
            self._metrics.inc("replication_tasks_applied", applied)
            self._metrics.record(
                "replication_apply_latency", _time.perf_counter() - t0
            )
        return applied

    def _process_cycle(self) -> int:
        t0 = time.monotonic()
        # per-link dynamic paging: a throttled link fetches pages sized
        # to its measured budget instead of the emit side's static page
        page_hint = (
            self.transport.page_size()
            if self.transport is not None else None
        )
        msgs = self.fetcher.fetch(self.shard.shard_id, max_tasks=page_hint)
        if self.transport is not None:
            # the fetch IS the link probe: bytes + wall time feed the
            # bandwidth/bytes-per-event EWMAs the mode controller reads
            self.transport.observe_messages(msgs, time.monotonic() - t0)
        if msgs.source_time_ns:
            # the stream carries the source cluster's clock; standby
            # timer processing fires against it (ref syncShardStatus)
            self.shard.set_remote_cluster_current_time(
                self.fetcher.cluster, msgs.source_time_ns
            )
        if not msgs.tasks:
            # nothing to apply in the range: safe to move past it
            self.fetcher.commit(self.shard.shard_id, msgs.last_retrieved_id)
            self._record_lag(msgs)
            done = self._drain_backfill()
            self._persist_progress()
            return done
        applied = self._apply_cycle(msgs)
        # page-derived lag first: a catch-up below re-gauges with the
        # exact probe-derived residue, which must not be clobbered by
        # this page's stale has_more proxy
        self._record_lag(msgs)
        if msgs.has_more and self.transport is not None \
                and self.rereplicator is not None:
            # deep backlog behind this page: switch to the adaptive
            # catch-up plane instead of paying the event stream
            # page-by-page over a link that may not afford it
            applied += self._adaptive_catchup()
        applied += self._drain_backfill()
        self._persist_progress()
        return applied

    def _apply_cycle(self, msgs: ReplicationMessages) -> int:
        if len(msgs.tasks) > 1:
            try:
                self.replicator.apply_events_batch(msgs.tasks)
                self.fetcher.commit(
                    self.shard.shard_id, msgs.tasks[-1].task_id
                )
                return len(msgs.tasks)
            except Exception:
                # sequential fallback below re-applies idempotently; a
                # persistent failure here means every cycle pays double
                # work, so make it visible
                logger.exception(
                    "batched replication drain failed; falling back to "
                    "sequential apply", shard=self.shard.shard_id,
                )
        return self._apply_keyed(msgs.tasks)

    # -- adaptive catch-up (bandwidth-adaptive state transfer) ---------

    def _local_tip(self, domain_id: str, workflow_id: str,
                   run_id: str) -> int:
        try:
            resp = self.shard.persistence.execution.get_workflow_execution(
                self.shard.shard_id, domain_id, workflow_id, run_id
            )
            return max(0, resp.next_event_id - 1)
        except Exception:
            return 0

    def _adaptive_catchup(self) -> int:
        """Summary-driven backlog recovery: one tiny backlog probe
        (per-run spans, no event payloads), then per run the mode
        controller chooses snapshot shipping or an event heal — both
        via the rereplicator, which owns the fallback ladder. The
        cursor fast-forwards past the summarized span only when EVERY
        run healed; any failure leaves it put so the next cycle retries
        (at-least-once, both paths idempotent)."""
        cursor = self.fetcher.last_retrieved(self.shard.shard_id)
        summary = self.transport.fetch_backlog(self.shard.shard_id, cursor)
        if not summary or not summary.get("runs"):
            return 0
        total_gap = 0
        healed = 0
        all_ok = True
        for run in summary["runs"]:
            d, wf, r = (
                run["domain_id"], run["workflow_id"], run["run_id"],
            )
            local_tip = self._local_tip(d, wf, r)
            gap = max(0, run["next_event_id"] - 1 - local_tip)
            total_gap += gap
            if gap == 0:
                healed += run["tasks"]
                continue
            err = RetryTaskV2Error(
                "adaptive catch-up",
                domain_id=d, workflow_id=wf, run_id=r,
                start_event_id=local_tip,
                end_event_id=run["next_event_id"],
            )
            try:
                self.rereplicator.rereplicate(err)
                healed += run["tasks"]
            except Exception:
                all_ok = False
                logger.exception(
                    "adaptive catch-up failed for workflow; cursor "
                    "held for retry",
                    shard=self.shard.shard_id, workflow=wf, run=r,
                )
        # the probe knew the gap exactly; after a fully healed pass the
        # residue is zero (the seconds view keeps its last
        # fetch-derived estimate — the summary carries no event
        # timestamps)
        self.transport.record_lag(
            0 if all_ok else total_gap,
            self.transport.estimator.lag_seconds,
        )
        if all_ok:
            # debt becomes durable BEFORE the cursor that fast-forwards
            # past it can be acked to the source (the ack rides the
            # NEXT fetch) — one write for the whole healed span, not
            # one per shipped run
            self._persist_progress()
            self.fetcher.commit(
                self.shard.shard_id, summary["max_task_id"]
            )
        return healed

    def _enqueue_backfill(self, domain_id: str, workflow_id: str,
                          run_id: str, from_event_id: int,
                          through_event_id: int) -> None:
        """Record the history debt a snapshot ship owes. The debt rides
        the durable progress row next to the cursor — a restart must
        never hold a fast-forwarded cursor without the owed ranges
        beside it (state current, bytes gone, forever). The durable
        write itself batches at the catch-up/cycle boundary, always
        before the cursor can be acked to the source."""
        item = (domain_id, workflow_id, run_id, from_event_id,
                through_event_id)
        if item not in self._backfill:
            self._backfill.append(item)

    def _drain_backfill(self, budget: int = 2) -> int:
        """Fetch + append up to ``budget`` owed history ranges (the
        byte-identity debt of snapshot shipping). A failed range
        rotates to the BACK of the queue (later debt keeps draining)
        and raises so the pump backs off; after
        ``backfill_max_attempts`` failures the range is abandoned with
        a loud log — a source that GC'd the history can never serve
        it, and one poison range must not wedge the plane forever."""
        if self.rereplicator is None:
            return 0
        done = 0
        while self._backfill and done < budget:
            item = self._backfill.popleft()
            try:
                self.rereplicator.backfill(*item)
                self._backfill_attempts.pop(item, None)
                done += 1
            except Exception:
                attempts = self._backfill_attempts.get(item, 0) + 1
                if attempts >= self.backfill_max_attempts:
                    self._backfill_attempts.pop(item, None)
                    logger.exception(
                        "history backfill range abandoned after "
                        f"{attempts} attempts (source no longer serves "
                        "it?); the standby is missing those bytes",
                        shard=self.shard.shard_id, range=item,
                    )
                else:
                    self._backfill_attempts[item] = attempts
                    self._backfill.append(item)
                raise
        return done

    # -- lag observability ---------------------------------------------

    @staticmethod
    def _lag_seconds_from(source_time_ns: int,
                          newest_event_ts_ns: Optional[int]) -> float:
        if not source_time_ns:
            return 0.0
        if not newest_event_ts_ns:
            return 0.0
        return max(0.0, (source_time_ns - newest_event_ts_ns) / 1e9)

    def _record_lag(self, msgs: ReplicationMessages) -> None:
        if self.transport is None:
            return
        newest_ts = None
        n_events = 0
        for t in msgs.tasks:
            n_events += len(t.events)
            if t.events:
                newest_ts = t.events[-1].timestamp
        # after a full apply the fetched span is current; only a
        # has_more backlog leaves a known residue behind this page
        lag_events = n_events if msgs.has_more else 0
        self.transport.record_lag(
            lag_events,
            self._lag_seconds_from(msgs.source_time_ns, newest_ts),
        )

    # -- durable progress (replication_progress rows) ------------------

    def _progress_blob(self, cursor: int) -> str:
        mode = "events"
        switches = 0
        if self.transport is not None:
            mode = self.transport.controller.mode
            switches = self.transport.controller.switches
        return json.dumps({
            "applied_through": cursor,
            "mode": mode,
            "mode_switches": switches,
            # owed history ranges from snapshot-shipped catch-ups: the
            # byte-identity debt survives a restart with the cursor
            "backfill": [list(item) for item in self._backfill],
        }, sort_keys=True)

    def _restore_progress(self) -> None:
        """Resume the fetch cursor from the durable progress row — a
        restarted standby re-fetches from where it durably applied, not
        from task id 0."""
        if not self._progress_supported:
            return
        try:
            row = self.shard.persistence.shard.get_replication_progress(
                self.shard.shard_id, self.fetcher.cluster
            )
        except Exception:
            return
        if not row:
            return
        try:
            blob = json.loads(row[1])
            cursor = int(blob.get("applied_through", 0))
            debt = [tuple(item) for item in blob.get("backfill", [])]
        except (ValueError, TypeError):
            return
        if cursor > 0:
            self.fetcher.commit(self.shard.shard_id, cursor)
            self._persisted_cursor = cursor
        for item in debt:
            if item not in self._backfill:
                self._backfill.append(item)
        self._persisted_debt = tuple(self._backfill)

    def _persist_progress(self) -> None:
        """Best-effort durable write of (cursor, mode, backfill debt)
        under a version LWT. Torn-write semantics match
        ``reshard_state``: a retry that reads back exactly the blob it
        tried to write treats the torn write as landed. Writes fire on
        cursor advance OR debt change — a drained (or newly owed)
        backfill range must reach the row even when the cursor sat
        still."""
        if not self._progress_supported:
            return
        cursor = self.fetcher.last_retrieved(self.shard.shard_id)
        debt = tuple(self._backfill)
        if cursor <= self._persisted_cursor and \
                debt == self._persisted_debt:
            return
        blob = self._progress_blob(cursor)
        mgr = self.shard.persistence.shard
        for _ in range(3):
            try:
                row = mgr.get_replication_progress(
                    self.shard.shard_id, self.fetcher.cluster
                )
                version = row[0] if row else 0
                mgr.set_replication_progress(
                    self.shard.shard_id, self.fetcher.cluster, blob,
                    version,
                )
                self._persisted_cursor = cursor
                self._persisted_debt = debt
                return
            except Exception as e:
                try:
                    row = mgr.get_replication_progress(
                        self.shard.shard_id, self.fetcher.cluster
                    )
                except Exception:
                    row = None
                if row and row[1] == blob:
                    # the torn write landed; the lost ack is paid
                    self._persisted_cursor = cursor
                    self._persisted_debt = debt
                    return
                if not isinstance(e, ConditionFailedError):
                    logger.warn(
                        "replication progress write failed "
                        f"({type(e).__name__}); cursor stays in-memory",
                        shard=self.shard.shard_id,
                    )
                    return

    def _apply_keyed(self, tasks) -> int:
        """Per-task fallback: runs sequentially PER WORKFLOW (a
        continue-as-new chain's runs must apply in order — the batched
        path barriers on the same key), concurrently across workflows
        (reference: replication tasks feed a keyed sequential task
        processor, common/task/sequentialTaskProcessor.go). The cursor
        commits through the longest finished-and-successful prefix, so
        a failed or still-running task re-fetches while already-applied
        peers dedup via version-history bookkeeping."""
        failures: List[tuple] = []  # (task_id, exception)
        flock = threading.Lock()

        def run(t: HistoryTaskV2) -> None:
            try:
                self._process_task(t)
            except Exception as e:
                with flock:
                    failures.append((t.task_id, e))
                logger.exception(
                    "replication task apply failed",
                    shard=self.shard.shard_id, task_id=t.task_id,
                    workflow=t.workflow_id,
                )

        seq = self._get_seq()
        for task in tasks:
            seq.submit(
                (task.domain_id, task.workflow_id),
                lambda t=task: run(t),
            )
        if not seq.flush(timeout_s=self.APPLY_FLUSH_TIMEOUT_S):
            # tasks still in flight: committing past them could lose
            # them forever (the cursor only moves forward). Raise —
            # returning 0 would read as "stream quiescent" to a
            # failover drain while work is still outstanding
            raise TimeoutError(
                f"shard {self.shard.shard_id}: keyed replication apply "
                "timed out with work in flight"
            )
        cutoff = min(tid for tid, _ in failures) if failures else None
        applied = 0
        last_ok = None
        for task in tasks:
            if cutoff is not None and task.task_id >= cutoff:
                break
            last_ok = task.task_id
            applied += 1
        if last_ok is not None:
            # the cursor is a monotonic watermark: one commit covers
            # the whole successful prefix
            self.fetcher.commit(self.shard.shard_id, last_ok)
        if applied == 0 and failures:
            # no progress at all: surface the failure to the caller
            # (drain()/pump) exactly like the old sequential loop did
            raise failures[0][1]
        return applied

    def drain_tasks(self, max_rounds: int = 100) -> int:
        """Pull+apply until a fetch comes back empty; returns the task
        count (test/assembly harness surface)."""
        total = 0
        for _ in range(max_rounds):
            n = self.process_once()
            total += n
            if n == 0:
                return total
        return total

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Queue-processor drain contract (HistoryService.drain_queues):
        True when the remote stream is quiescent within the budget. The
        keyed-apply flush budget shrinks to the caller's deadline for
        the duration — a single hung apply must not turn a 5s drain
        into a 120s stall."""
        deadline = time.monotonic() + timeout_s
        saved = self.APPLY_FLUSH_TIMEOUT_S
        self.APPLY_FLUSH_TIMEOUT_S = max(0.5, timeout_s)
        try:
            while time.monotonic() < deadline:
                if self.process_once() == 0:
                    return True
        finally:
            self.APPLY_FLUSH_TIMEOUT_S = saved
        return False

    def _process_task(self, task: HistoryTaskV2) -> None:
        for attempt in range(self.max_retry):
            try:
                self.replicator.apply_events(task)
                return
            except RetryTaskV2Error as e:
                if self.rereplicator is None or attempt == self.max_retry - 1:
                    raise
                self.rereplicator.rereplicate(e)

    # -- background pump -----------------------------------------------

    def start(self, interval_s: float = 0.05) -> None:
        if self._thread is not None:
            return

        def pump() -> None:
            # capped jittered exponential backoff on FAILED cycles: a
            # dead remote link costs one retry per backoff_max_s (not a
            # log line every interval_s), and the first successful
            # cycle resets the ladder so a healed link resumes at full
            # pull cadence immediately. Jitter keeps concurrent shards
            # pulling one dead link from retrying in phase.
            ladder = BackoffLadder(
                interval_s, max(self.backoff_max_s, interval_s),
                jitter=0.5, rng=self._backoff_rng,
            )
            while not self._stop.is_set():
                try:
                    n = self.process_once()
                    ladder.success()
                    if n == 0:
                        self._stop.wait(interval_s)
                except Exception:
                    logger.exception(
                        "replication pump cycle failed",
                        shard=self.shard.shard_id,
                        cluster=self.fetcher.cluster,
                    )
                    self._metrics.inc("replication_pump_backoffs")
                    self._stop.wait(ladder.failure())

        self._thread = threading.Thread(target=pump, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)
            self._thread = None
        if self._seq is not None:
            # wait=False: a hung apply must not turn the bounded stop()
            # into an indefinite block (the pool threads are abandoned;
            # the interpreter reaps them at exit)
            self._seq.shutdown(wait=False)
