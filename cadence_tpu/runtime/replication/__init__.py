"""Cross-cluster replication plane.

Reference: service/history/replicatorQueueProcessor.go (emit side),
replicationTaskFetcher.go / replicationTaskProcessor.go (consume side),
nDCHistoryReplicator.go + nDCBranchMgr / nDCConflictResolver /
nDCStateRebuilder / nDCEventsReapplier / nDCTransactionMgr (apply),
common/xdc/historyRereplicator.go (gap fill).
"""

from .failover import (
    ClusterHandle,
    DomainFailoverCoordinator,
    FailoverDrillError,
    FailoverReport,
)
from .messages import (
    HistoryTaskV2,
    ReplicationMessages,
    RetryTaskV2Error,
)
from .replicator_queue import ReplicatorQueueProcessor
from .rebuilder import StateRebuilder
from .ndc import NDCHistoryReplicator
from .processor import ReplicationTaskFetcher, ReplicationTaskProcessor
from .rereplicator import HistoryRereplicator
from .transport import (
    MODE_EVENTS,
    MODE_SNAPSHOT,
    AdaptiveTransport,
    LinkEstimator,
    ReplicationModeController,
)

__all__ = [
    "ClusterHandle",
    "DomainFailoverCoordinator",
    "FailoverDrillError",
    "FailoverReport",
    "HistoryTaskV2",
    "ReplicationMessages",
    "RetryTaskV2Error",
    "ReplicatorQueueProcessor",
    "StateRebuilder",
    "NDCHistoryReplicator",
    "ReplicationTaskFetcher",
    "ReplicationTaskProcessor",
    "HistoryRereplicator",
    "MODE_EVENTS",
    "MODE_SNAPSHOT",
    "AdaptiveTransport",
    "LinkEstimator",
    "ReplicationModeController",
]
