"""Bandwidth-adaptive replication transport.

The geographic-SMR state-transfer adaptation ("A State Transfer Method
That Adapts to Network Bandwidth Variations in Geographic SMR",
PAPERS.md): a standby cluster behind a slow or lossy WAN link should
switch between **event-stream shipping** (the NDC pull plane's normal
mode — cheap on healthy links, O(backlog bytes) on degraded ones) and
**snapshot shipping** (a delta-compressed ``ReplayCheckpoint`` row per
workflow, applied through the existing suffix-only resume path) per the
measured link budget.

Three pieces, all consumer-side:

* ``LinkEstimator`` — EWMA observations of every transfer on one link
  (bytes, wall seconds → bandwidth; events per fetch → bytes/event;
  snapshot blob sizes and apply times), plus the lag view derived from
  the ``source_time_ns`` clock every ``ReplicationMessages`` carries.
* ``ReplicationModeController`` — the decision rule with hysteresis.
  For a catch-up gap of G events the estimated costs are::

      t_events   = G * bytes_per_event / bandwidth
      t_snapshot = snapshot_bytes / bandwidth + snapshot_apply_s

  Snapshot mode is chosen when ``t_snapshot * hysteresis < t_events``
  for ``min_dwell`` consecutive decisions (and back symmetrically), so
  a noisy estimate cannot flap the mode; with no bandwidth sample yet
  the controller always answers "events" (the safe default — event
  shipping is the correctness baseline).
* ``AdaptiveTransport`` — one per (remote cluster) link: owns the
  estimator + controller, wraps the remote client's snapshot/backlog
  calls with byte/latency measurement, and serializes checkpoints for
  the wire.

Checkpoint wire codec: the state-row int32 tensors (the bulk of a
``ReplayCheckpoint``) ship through the ``native`` varint+zigzag delta
codec (``tensor_compress``); the remainder rides the persistence serde
JSON. Decode validates shapes and falls back loudly — a torn or
corrupt snapshot transfer must degrade to event shipping, never apply
garbage state.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

from cadence_tpu.utils.log import get_logger
from cadence_tpu.utils.metrics import NOOP

logger = get_logger("cadence_tpu.replication.transport")

MODE_EVENTS = "events"
MODE_SNAPSHOT = "snapshot"


# ---------------------------------------------------------------------------
# wire sizing + checkpoint codec
# ---------------------------------------------------------------------------


def wire_size(payload: Any) -> int:
    """Honest byte count of one replication transfer: what the rpc
    codec would put on the wire. ``bytes`` payloads (already-encoded
    snapshot blobs) are counted as-is. The size is cached on the
    payload where the object allows it — a fetched page is measured by
    both the chaos link and the consumer's estimator, and re-encoding
    a large event batch twice per cycle is pure waste."""
    if payload is None:
        return 0
    if isinstance(payload, (bytes, bytearray)):
        return len(payload)
    cached = getattr(payload, "_wire_size", None)
    if cached is not None:
        return cached
    from cadence_tpu.rpc import codec

    try:
        n = len(codec.dumps(payload))
    except TypeError:
        # non-wire type (in-process test double): coarse repr estimate
        n = len(repr(payload))
    try:
        payload._wire_size = n
    except (AttributeError, TypeError):
        pass  # tuples/dicts can't carry the cache; recompute is fine
    return n


_WIRE_VERSION = 1


def encode_checkpoint_wire(ckpt) -> bytes:
    """``ReplayCheckpoint`` → compressed wire blob. The int32 state-row
    tensors ride the native varint+zigzag delta codec; everything else
    (resume tables, side table, version history) rides the persistence
    serde JSON the record already defines."""
    from cadence_tpu import native

    meta = json.loads(ckpt.to_json())
    rows = meta.pop("state_row")
    packed: Dict[str, Dict[str, Any]] = {}
    for name, values in rows.items():
        arr = np.asarray(values, dtype=np.int32)
        blob, shape = native.tensor_compress(arr)
        packed[name] = {
            "b": base64.b64encode(blob).decode(),
            "shape": list(shape),
        }
    return json.dumps(
        {"v": _WIRE_VERSION, "meta": meta, "rows": packed}
    ).encode()


def decode_checkpoint_wire(raw: bytes):
    """Wire blob → ``ReplayCheckpoint``. Raises ``ValueError`` on any
    truncation/corruption (the codec validates element counts), which
    the callers translate into the event-shipping fallback."""
    from cadence_tpu import native
    from cadence_tpu.checkpoint.record import ReplayCheckpoint

    frame = json.loads(raw.decode())
    if frame.get("v") != _WIRE_VERSION:
        raise ValueError(
            f"checkpoint wire: unknown version {frame.get('v')!r}"
        )
    meta = frame["meta"]
    rows: Dict[str, list] = {}
    for name, rec in frame["rows"].items():
        blob = base64.b64decode(rec["b"])
        arr = native.tensor_decompress(blob, tuple(rec["shape"]))
        rows[name] = arr.tolist()
    meta["state_row"] = rows
    return ReplayCheckpoint.from_json(json.dumps(meta))


# ---------------------------------------------------------------------------
# link estimation
# ---------------------------------------------------------------------------


class LinkEstimator:
    """EWMA view of one replication link, fed by the consumer around
    every remote call. Thread-safe: several shards' processors share
    one link (one fetcher per remote cluster)."""

    # priors used before the first observation of each kind; chosen so
    # an unobserved link never prefers snapshots (bandwidth None gates
    # the controller anyway)
    BYTES_PER_EVENT_PRIOR = 512.0
    BYTES_PER_TASK_PRIOR = 2048.0
    SNAPSHOT_BYTES_PRIOR = 64 * 1024.0
    SNAPSHOT_APPLY_S_PRIOR = 0.05

    def __init__(self, alpha: float = 0.3) -> None:
        if not 0.0 < alpha <= 1.0:
            raise ValueError("estimator alpha must be in (0, 1]")
        self.alpha = alpha
        self._lock = threading.Lock()
        self._bandwidth_bps: Optional[float] = None
        self._bytes_per_event: Optional[float] = None
        self._bytes_per_task: Optional[float] = None
        self._snapshot_bytes: Optional[float] = None
        self._snapshot_apply_s: Optional[float] = None
        self.bytes_total = 0
        self.lag_events = 0
        self.lag_seconds = 0.0

    def _ewma(self, prev: Optional[float], sample: float) -> float:
        if prev is None:
            return sample
        return prev + self.alpha * (sample - prev)

    # -- observations --------------------------------------------------

    def observe_transfer(self, nbytes: int, seconds: float,
                         n_events: int = 0, n_tasks: int = 0) -> None:
        """One completed transfer on the link (any payload kind)."""
        with self._lock:
            self.bytes_total += max(0, nbytes)
            if nbytes > 0 and seconds > 1e-6:
                self._bandwidth_bps = self._ewma(
                    self._bandwidth_bps, nbytes / seconds
                )
            if n_events > 0 and nbytes > 0:
                self._bytes_per_event = self._ewma(
                    self._bytes_per_event, nbytes / n_events
                )
            if n_tasks > 0 and nbytes > 0:
                self._bytes_per_task = self._ewma(
                    self._bytes_per_task, nbytes / n_tasks
                )

    def observe_snapshot(self, nbytes: int, apply_seconds: float) -> None:
        with self._lock:
            if nbytes > 0:
                self._snapshot_bytes = self._ewma(
                    self._snapshot_bytes, float(nbytes)
                )
            if apply_seconds > 0:
                self._snapshot_apply_s = self._ewma(
                    self._snapshot_apply_s, apply_seconds
                )

    def observe_lag(self, lag_events: int, lag_seconds: float) -> None:
        with self._lock:
            self.lag_events = max(0, lag_events)
            self.lag_seconds = max(0.0, lag_seconds)

    # -- views ---------------------------------------------------------

    def bandwidth_bps(self) -> Optional[float]:
        with self._lock:
            return self._bandwidth_bps

    def bytes_per_event(self) -> float:
        with self._lock:
            return self._bytes_per_event or self.BYTES_PER_EVENT_PRIOR

    def bytes_per_task(self) -> float:
        with self._lock:
            return self._bytes_per_task or self.BYTES_PER_TASK_PRIOR

    def snapshot_bytes(self) -> float:
        with self._lock:
            return self._snapshot_bytes or self.SNAPSHOT_BYTES_PRIOR

    def snapshot_apply_s(self) -> float:
        with self._lock:
            return self._snapshot_apply_s or self.SNAPSHOT_APPLY_S_PRIOR

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "bandwidth_bps": self._bandwidth_bps,
                "bytes_per_event": self._bytes_per_event,
                "bytes_per_task": self._bytes_per_task,
                "snapshot_bytes": self._snapshot_bytes,
                "snapshot_apply_s": self._snapshot_apply_s,
                "bytes_total": self.bytes_total,
                "lag_events": self.lag_events,
                "lag_seconds": self.lag_seconds,
            }


class ReplicationModeController:
    """Event-vs-snapshot decision with hysteresis.

    The mode is LINK-WIDE (one controller per remote cluster, like the
    estimator); ``decide(gap_events)`` evaluates one catch-up decision
    and returns the mode to use for that gap. Switching requires the
    challenger mode to win the cost comparison by ``hysteresis`` for
    ``min_dwell`` CONSECUTIVE decisions — a single burst of noise in
    the bandwidth EWMA cannot flap the mode. Gaps below
    ``min_gap_events`` always ship events (a snapshot cannot beat a
    handful of events no matter the link)."""

    def __init__(
        self,
        estimator: LinkEstimator,
        hysteresis: float = 1.5,
        min_dwell: int = 2,
        min_gap_events: int = 32,
        force_mode: Optional[str] = None,
        metrics=None,
    ) -> None:
        if hysteresis < 1.0:
            raise ValueError("controller hysteresis must be >= 1.0")
        if min_dwell < 1:
            raise ValueError("controller min_dwell must be >= 1")
        self.estimator = estimator
        self.hysteresis = hysteresis
        self.min_dwell = min_dwell
        self.min_gap_events = min_gap_events
        # pin the mode (bench comparison arms); None = adaptive
        self.force_mode = force_mode
        self._lock = threading.Lock()
        self.mode = MODE_EVENTS
        self.switches = 0
        self._streak = 0
        self._metrics = (metrics or NOOP).tagged(layer="replication")

    def _preferred(self, gap_events: int) -> str:
        """Raw (hysteresis-free) cost comparison for one gap."""
        est = self.estimator
        bw = est.bandwidth_bps()
        if bw is None or bw <= 0:
            return MODE_EVENTS
        t_events = gap_events * est.bytes_per_event() / bw
        t_snap = est.snapshot_bytes() / bw + est.snapshot_apply_s()
        challenger = MODE_SNAPSHOT if self.mode == MODE_EVENTS else MODE_EVENTS
        if challenger == MODE_SNAPSHOT:
            return (
                MODE_SNAPSHOT
                if t_snap * self.hysteresis < t_events
                else MODE_EVENTS
            )
        return (
            MODE_EVENTS
            if t_events * self.hysteresis < t_snap
            else MODE_SNAPSHOT
        )

    def decide(self, gap_events: int) -> str:
        if self.force_mode is not None:
            return self.force_mode
        if gap_events < self.min_gap_events:
            # a below-floor gap ships events AND breaks any pending
            # switch streak — min_dwell means CONSECUTIVE qualifying
            # wins, not wins bridged across unrelated small gaps
            with self._lock:
                self._streak = 0
            return MODE_EVENTS
        with self._lock:
            want = self._preferred(gap_events)
            if want == self.mode:
                self._streak = 0
                return self.mode
            self._streak += 1
            if self._streak < self.min_dwell:
                return self.mode
            self.mode = want
            self._streak = 0
            self.switches += 1
        self._metrics.inc("replication_mode_switches")
        self._metrics.gauge(
            "replication_mode", 1 if want == MODE_SNAPSHOT else 0
        )
        return want


# ---------------------------------------------------------------------------
# the per-link transport bundle
# ---------------------------------------------------------------------------


class AdaptiveTransport:
    """One remote cluster's adaptive replication plane, shared by every
    shard's processor the way the fetcher is (the estimator/controller
    describe the LINK, not a shard).

    ``client`` is the fetcher's ``RemoteClusterClient``; the two extra
    verbs (``get_replication_backlog`` / ``get_replication_checkpoint``)
    are probed lazily so a transport pointed at a pre-adaptive remote
    degrades to pure event shipping instead of erroring."""

    def __init__(
        self,
        client: Any,
        cluster: str,
        hysteresis: float = 1.5,
        min_dwell: int = 2,
        min_gap_events: int = 32,
        snapshot_bytes_prior: float = 64 * 1024.0,
        force_mode: Optional[str] = None,
        metrics=None,
    ) -> None:
        self.client = client
        self.cluster = cluster
        self.estimator = LinkEstimator()
        self.estimator.SNAPSHOT_BYTES_PRIOR = float(snapshot_bytes_prior)
        self._metrics = (metrics or NOOP).tagged(
            layer="replication", cluster=cluster
        )
        self.controller = ReplicationModeController(
            self.estimator,
            hysteresis=hysteresis,
            min_dwell=min_dwell,
            min_gap_events=min_gap_events,
            force_mode=force_mode,
            metrics=self._metrics,
        )

    # -- measured remote calls ----------------------------------------

    def _measured(self, payload: Any, t0: float, n_events: int = 0,
                  mode: str = MODE_EVENTS) -> int:
        nbytes = wire_size(payload)
        self.estimator.observe_transfer(
            nbytes, time.monotonic() - t0, n_events=n_events
        )
        self._metrics.tagged(mode=mode).inc(
            "replication_bytes_shipped", nbytes
        )
        return nbytes

    def observe_messages(self, msgs, seconds: float) -> None:
        """Account one regular fetch cycle (the processor performs the
        call; the transport does the bookkeeping)."""
        n_events = sum(len(t.events) for t in msgs.tasks)
        nbytes = wire_size(msgs)
        self.estimator.observe_transfer(
            nbytes, seconds, n_events=n_events, n_tasks=len(msgs.tasks)
        )
        self._metrics.tagged(mode=MODE_EVENTS).inc(
            "replication_bytes_shipped", nbytes
        )

    # -- dynamic fetch paging -----------------------------------------

    # one fetch should occupy the link for about this long; on a
    # throttled link the page shrinks accordingly instead of one huge
    # hydrated page timing out (or sleeping the chaos link for minutes)
    FETCH_TARGET_S = 2.0
    MIN_FETCH_PAGE = 4
    MAX_FETCH_PAGE = 512

    def page_size(self) -> Optional[int]:
        """Per-link emit-page cap for the next fetch, from the measured
        bandwidth and bytes-per-task EWMAs: the task count whose
        hydrated bytes fit ``FETCH_TARGET_S`` of link time, clamped to
        [MIN_FETCH_PAGE, MAX_FETCH_PAGE]. None before the first
        bandwidth sample — the emit side's static default applies (an
        unmeasured link is not presumed slow)."""
        bw = self.estimator.bandwidth_bps()
        if bw is None or bw <= 0:
            return None
        tasks = int(bw * self.FETCH_TARGET_S / self.estimator.bytes_per_task())
        page = max(self.MIN_FETCH_PAGE, min(self.MAX_FETCH_PAGE, tasks))
        self._metrics.gauge("replication_fetch_page_limit", page)
        return page

    def fetch_backlog(self, shard_id: int,
                      last_retrieved_id: int) -> Optional[dict]:
        """Per-run backlog spans past the cursor (tiny transfer — no
        event payloads), or None when the remote lacks the verb."""
        fn = getattr(self.client, "get_replication_backlog", None)
        if fn is None:
            return None
        t0 = time.monotonic()
        summary = fn(shard_id, last_retrieved_id)
        self._measured(summary, t0)
        return summary

    def fetch_snapshot(self, domain_id: str, workflow_id: str,
                       run_id: str) -> Optional[Tuple[Any, int]]:
        """(decoded ReplayCheckpoint, wire bytes), or None when the
        remote lacks the verb or has no shippable snapshot."""
        fn = getattr(self.client, "get_replication_checkpoint", None)
        if fn is None:
            return None
        t0 = time.monotonic()
        raw = fn(domain_id, workflow_id, run_id)
        if not raw:
            return None
        nbytes = self._measured(raw, t0, mode=MODE_SNAPSHOT)
        ckpt = decode_checkpoint_wire(raw)
        return ckpt, nbytes

    def fetch_raw_history(self, domain_id: str, workflow_id: str,
                          run_id: str, start_event_id: int,
                          end_event_id: int):
        t0 = time.monotonic()
        batches, items = self.client.get_workflow_history_raw(
            domain_id, workflow_id, run_id, start_event_id, end_event_id
        )
        self._measured(
            (batches, items), t0,
            n_events=sum(len(b) for b in batches),
        )
        return batches, items

    # -- lag bookkeeping ----------------------------------------------

    def record_lag(self, lag_events: int, lag_seconds: float) -> None:
        self.estimator.observe_lag(lag_events, lag_seconds)
        self._metrics.gauge("replication_lag_events", max(0, lag_events))
        self._metrics.gauge(
            "replication_lag_seconds", max(0.0, lag_seconds)
        )
