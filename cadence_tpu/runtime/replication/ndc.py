"""NDC multi-master history replication: apply remote event batches.

Reference: service/history/nDCHistoryReplicator.go:158 (ApplyEvents) and
its satellites — nDCBranchMgr.go (LCA branch selection / fork),
nDCConflictResolver.go:65 (rebuild-at-branch-point via state rebuilder),
nDCTransactionMgr*.go (create/update as current vs zombie),
nDCEventsReapplier.go (reapply signals from stale branches).

The control flow is host-side Python; the replay inside creation,
continuation, and rebuild all goes through the shared StateBuilder whose
semantics are differential-tested against the TPU kernel — so a
replication storm can be drained through ``StateRebuilder.rebuild_many``
(one device scan for the whole backlog) without changing this module's
contract.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from cadence_tpu.core.events import HistoryEvent
from cadence_tpu.core.enums import EventType, WorkflowState
from cadence_tpu.core.mutable_state import MutableState
from cadence_tpu.core.state_builder import StateBuilder
from cadence_tpu.core.version_history import (
    VersionHistories,
    VersionHistory,
    VersionHistoryError,
    VersionHistoryItem,
)

from ..persistence.errors import EntityNotExistsError
from ..persistence.records import (
    BranchToken,
    CreateWorkflowMode,
    WorkflowSnapshot,
)
from ..shard import ShardContext
from .messages import HistoryTaskV2, RetryTaskV2Error
from .rebuilder import RebuildRequest, StateRebuilder


def _incoming_history(task: HistoryTaskV2) -> VersionHistory:
    return VersionHistory(
        items=[
            VersionHistoryItem(it["event_id"], it["version"])
            for it in task.version_history_items
        ]
    )


class NDCHistoryReplicator:
    """Applies HistoryTaskV2 batches to the local shard."""

    def __init__(
        self,
        shard: ShardContext,
        domains,
        cache,
        rebuilder: Optional[StateRebuilder] = None,
        is_active_locally=None,
        task_notifier=lambda: None,
        timer_notifier=lambda: None,
        rebuild_chunk_size=0,
        faults=None,
        checkpoints=None,
        metrics=None,
        serving=None,
    ) -> None:
        self.shard = shard
        self.domains = domains
        self.cache = cache
        # chaos hook: fired per applied task BEFORE any mutation, so an
        # injected fault exercises the fetcher's re-fetch/re-apply path
        # (at-least-once), never a half-applied batch
        from ..queues.base import make_fault_hook

        self._fault_hook = make_fault_hook(
            faults, "replication.ndc", shard_id=shard.shard_id
        )
        self.rebuilder = rebuilder or StateRebuilder(
            shard.persistence.history,
            domain_resolver=self._resolve_domain,
            chunk_size=rebuild_chunk_size,
            checkpoints=checkpoints,
            metrics=metrics,
            serving=serving,
        )
        # whether this cluster is currently active for a domain (drives
        # signal reapplication; standby clusters never mint events)
        self._is_active_locally = is_active_locally or (lambda domain_id: False)
        self._task_notifier = task_notifier
        self._timer_notifier = timer_notifier
        # raw metrics handle for the snapshot-shipping install plane (the
        # transient rebuilder it builds must emit events_replayed_saved
        # into the same registry as the engine-wired one)
        self._raw_metrics = metrics
        from cadence_tpu.utils.metrics import NOOP

        # conflict-resolution observability: the failover drill reports
        # (replication/failover.py) read these counters as "how many
        # divergent-branch storms did the heal actually resolve"
        self._metrics = (metrics or NOOP).tagged(layer="replication")
        self._transient_snapshots = None

    def _resolve_domain(self, name: str) -> str:
        if not name:
            return ""
        try:
            return self.domains.resolve(name).info.id
        except Exception:
            return name

    # -- entry point ---------------------------------------------------

    def apply_events(
        self, task: HistoryTaskV2, _defer_rebuild: bool = False,
    ) -> Optional[dict]:
        """Apply one replication task.

        With ``_defer_rebuild`` (the batched drain), a task whose apply
        requires a conflict rebuild is NOT rebuilt inline; a plan record
        is returned instead so the caller can rebuild many workflows in
        one device scan (``apply_events_batch``)."""
        if not task.events:
            raise ValueError("replication task has no events")
        if self._fault_hook is not None:
            self._fault_hook("apply_events", self.shard.shard_id)
        # replication apply runs on the pull-pump thread; the same
        # workflow-keyed binding the queue pumps use joins this apply to
        # the workflow's sampled trace, if one exists (utils/tracing.py)
        from cadence_tpu.runtime.queues.base import task_span
        from cadence_tpu.runtime.queues.effects import task_effect_scope

        with task_span("replication-apply", task), \
                task_effect_scope("replication", "HistoryReplication"):
            ctx = self.cache.get_or_create(
                task.domain_id, task.workflow_id, task.run_id
            )
            with ctx.lock:
                try:
                    ms = ctx.load()
                except EntityNotExistsError:
                    self._apply_for_new_workflow(ctx, task)
                    return None
                return self._apply_for_existing(
                    ctx, ms, task, _defer_rebuild=_defer_rebuild
                )

    def apply_events_batch(self, tasks) -> None:
        """Batched drain: apply a fetched cycle's tasks, routing every
        conflict rebuild through ONE ``rebuild_many`` device scan.

        Matches the reference's per-task semantics
        (replicationTaskProcessor.go:85-434 feeding
        nDCConflictResolver.go:65) — a replication storm that forces N
        workflows to rebuild at a branch point replays all N histories
        as one batched scan instead of N sequential host replays. Once a
        workflow defers, ALL its later tasks in the cycle — any run of
        the same workflow_id, matching the reference's per-workflow
        sequential ordering (common/task/sequentialTaskProcessor.go) —
        queue behind the rebuild and apply, in order, after it
        completes."""
        deferred: dict = {}
        order: list = []
        barrier: dict = {}   # (domain, wf) -> deferred key
        for task in tasks:
            wf_key = (task.domain_id, task.workflow_id)
            if wf_key in barrier:
                deferred[barrier[wf_key]]["followups"].append(task)
                continue
            rec = self.apply_events(task, _defer_rebuild=True)
            if rec is not None:
                key = (task.domain_id, task.workflow_id, task.run_id)
                deferred[key] = rec
                order.append(key)
                barrier[wf_key] = key
        if not deferred:
            return
        from cadence_tpu.runtime.queues.effects import task_effect_scope

        # the batched conflict rebuilds happen outside apply_events;
        # they are still HistoryReplication work for the effect witness
        with task_effect_scope("replication", "HistoryReplication"):
            self._drain_deferred(deferred, order)

    def _drain_deferred(self, deferred: dict, order: list) -> None:
        reqs = [
            RebuildRequest(
                domain_id=deferred[k]["task"].domain_id,
                workflow_id=deferred[k]["task"].workflow_id,
                run_id=deferred[k]["task"].run_id,
                branch_token=deferred[k]["branch_token"],
                next_event_id=deferred[k]["next_event_id"],
                # the target branch's items: the checkpoint manager's
                # NDC divergence guard — a conflicting branch must not
                # resume past its fork point
                version_history_items=deferred[k]["vh_items"],
            )
            for k in order
        ]
        rebuilt = self.rebuilder.rebuild_many(reqs, use_device=True)
        for k, (ms, _, _) in zip(order, rebuilt):
            rec = deferred[k]
            self._finish_deferred_rebuild(rec, ms)
            for t in rec["followups"]:
                self.apply_events(t)

    def _finish_deferred_rebuild(self, rec: dict, rebuilt) -> None:
        task, bi = rec["task"], rec["branch_index"]
        # re-fetch the context: the plan-time handle may have been
        # evicted from the cache between planning and completion (e.g.
        # a SUPPRESS_CURRENT create zombifying this run) and would then
        # serve a stale cached mutable state
        ctx = self.cache.get_or_create(
            task.domain_id, task.workflow_id, task.run_id
        )
        with ctx.lock:
            ms = ctx.load()
            local = ms.version_histories
            # re-validate the plan under the lock (the replication pump
            # is the shard's single writer, but anything may have moved
            # between planning and completion); on any drift fall back
            # to the inline path
            plan_holds = (
                local is not None
                and bi < len(local.histories)
                and local.current_index != bi
                and local.get_version_history(bi).branch_token
                == rec["branch_token"]
                and local.get_version_history(bi).last_item().event_id + 1
                == rec["next_event_id"]
                and task.version
                > local.get_current_version_history().last_item().version
            )
            if not plan_holds:
                # the fallback may re-fork at the same LCA, leaving the
                # plan-time fork as an orphan branch (the same window
                # exists on any inline retry after _fork_branch); the
                # history scavenger owns orphan-branch cleanup
                self._apply_for_existing(ctx, ms, task)
                return
            target_vh = local.get_version_history(bi)
            rebuilt.version_histories = local
            local.current_index = bi
            rebuilt.execution_info.run_id = task.run_id
            rebuilt.execution_info.workflow_id = task.workflow_id
            self._apply_to_current(ctx, rebuilt, task, target_vh)
            self._metrics.inc("replication_conflicts_resolved")

    # -- creation path (nDCTransactionMgrForNewWorkflow) ---------------

    def _apply_for_new_workflow(self, ctx, task: HistoryTaskV2) -> None:
        if task.first_event_id != 1:
            raise RetryTaskV2Error(
                "workflow missing locally; need history from the start",
                domain_id=task.domain_id,
                workflow_id=task.workflow_id,
                run_id=task.run_id,
                start_event_id=0,
                end_event_id=task.first_event_id,
                end_event_version=task.version,
            )
        history = self.shard.persistence.history
        branch = history.new_history_branch(tree_id=task.run_id)
        history.append_history_nodes(
            branch, task.events, transaction_id=self.shard.next_task_id()
        )

        ms = MutableState(domain_id=task.domain_id)
        ms.version_histories = VersionHistories.new_empty()
        sb = StateBuilder(ms, domain_resolver=self._resolve_domain)
        sb.apply_events(
            task.domain_id, "replication", task.workflow_id, task.run_id,
            list(task.events),
            list(task.new_run_events) or None,
        )
        ms.execution_info.branch_token = branch.to_json().encode()
        ms.version_histories.get_current_version_history().branch_token = (
            ms.execution_info.branch_token
        )

        mode, prev_run_id = self._create_mode(
            task.domain_id, task.workflow_id, task.version
        )
        snapshot = self._snapshot(
            ms, sb.transfer_tasks, sb.timer_tasks, zombie=(
                mode == CreateWorkflowMode.ZOMBIE
            ),
        )
        self.shard.persistence.execution.create_workflow_execution(
            self.shard.shard_id, self.shard.range_id, mode, snapshot,
            prev_run_id=prev_run_id,
        )
        if mode == CreateWorkflowMode.SUPPRESS_CURRENT:
            # the store zombified the stale run's persisted record; evict
            # its cached context so a late replication task for that run
            # reloads the zombie state instead of resurrecting the cached
            # Running mutable state on its next write
            self.cache.evict(task.domain_id, task.workflow_id, prev_run_id)
        ctx._ms = ms
        ctx._condition = ms.next_event_id
        self._notify(sb)

    def _create_mode(
        self, domain_id: str, workflow_id: str, version: int
    ) -> Tuple[int, str]:
        """current-vs-zombie decision for a replication-created run
        (shared by the event path and snapshot shipping — both create
        runs the local cluster has never seen)."""
        try:
            cur = self.shard.persistence.execution.get_current_execution(
                self.shard.shard_id, domain_id, workflow_id
            )
        except EntityNotExistsError:
            return CreateWorkflowMode.BRAND_NEW, ""
        if version >= cur.last_write_version and cur.state == int(
            WorkflowState.Completed
        ):
            return CreateWorkflowMode.WORKFLOW_ID_REUSE, cur.run_id
        if version > cur.last_write_version:
            # incoming run was written by a NEWER failover version than
            # the still-running current run: after a failover the new
            # active cluster's run must take primacy — suppress the
            # stale run and create the incoming one as current (ref
            # nDCTransactionMgrForNewWorkflow.go
            # SuppressCurrentAndCreateAsCurrent); a plain ZOMBIE create
            # would leave workflow_id lookups resolving to the stale
            # run forever
            return CreateWorkflowMode.SUPPRESS_CURRENT, cur.run_id
        # a running current run with a version >= ours keeps primacy
        return CreateWorkflowMode.ZOMBIE, ""

    # -- existing-workflow path ----------------------------------------

    def _apply_for_existing(
        self, ctx, ms: MutableState, task: HistoryTaskV2,
        _defer_rebuild: bool = False,
    ) -> Optional[dict]:
        local = ms.version_histories
        if local is None:
            raise ValueError(
                "replication target has no version histories (local domain?)"
            )
        incoming = _incoming_history(task)
        try:
            branch_index, lca_item = local.find_lca_index_and_item(incoming)
        except VersionHistoryError:
            raise RetryTaskV2Error(
                "no common ancestor; resync from start",
                domain_id=task.domain_id,
                workflow_id=task.workflow_id,
                run_id=task.run_id,
                start_event_id=0,
                end_event_id=task.first_event_id,
                end_event_version=task.version,
            )

        branch_vh = local.get_version_history(branch_index)
        last_local = branch_vh.last_item()

        if branch_vh.is_lca_appendable(lca_item):
            # incoming continues this branch
            if task.next_event_id <= last_local.event_id + 1 and (
                branch_vh.contains_item(
                    VersionHistoryItem(task.next_event_id - 1, task.version)
                )
            ):
                return None  # duplicate batch — already applied
            if task.first_event_id > last_local.event_id + 1:
                raise RetryTaskV2Error(
                    "missing intermediate events",
                    domain_id=task.domain_id,
                    workflow_id=task.workflow_id,
                    run_id=task.run_id,
                    start_event_id=last_local.event_id,
                    start_event_version=last_local.version,
                    end_event_id=task.first_event_id,
                    end_event_version=task.version,
                )
        else:
            # divergence: fork a new branch at the LCA
            branch_index = self._fork_branch(
                local, branch_index, lca_item, task
            )
            branch_vh = local.get_version_history(branch_index)
            if task.first_event_id > lca_item.event_id + 1:
                raise RetryTaskV2Error(
                    "fork point behind incoming batch",
                    domain_id=task.domain_id,
                    workflow_id=task.workflow_id,
                    run_id=task.run_id,
                    start_event_id=lca_item.event_id,
                    start_event_version=lca_item.version,
                    end_event_id=task.first_event_id,
                    end_event_version=task.version,
                )

        # conflict resolution: which branch becomes/stays current
        if branch_index == local.current_index:
            self._apply_to_current(ctx, ms, task, branch_vh)
            return None

        current_vh = local.get_current_version_history()
        if task.version > current_vh.last_item().version:
            # incoming wins: rebuild state from the target branch tip,
            # then continue applying on it as the new current
            target_vh = local.get_version_history(branch_index)
            if _defer_rebuild:
                return {
                    "task": task,
                    "branch_index": branch_index,
                    "branch_token": target_vh.branch_token,
                    "next_event_id": target_vh.last_item().event_id + 1,
                    "vh_items": [
                        (it.event_id, it.version)
                        for it in target_vh.items
                    ],
                    "followups": [],
                }
            self._rebuild_and_apply(ctx, ms, task, branch_index)
        else:
            self._backfill_branch(ctx, ms, task, branch_index)
        return None

    # -- branch manager ------------------------------------------------

    def _fork_branch(
        self,
        local: VersionHistories,
        base_index: int,
        lca_item: VersionHistoryItem,
        task: HistoryTaskV2,
    ) -> int:
        base_vh = local.get_version_history(base_index)
        base_branch = BranchToken.from_json(base_vh.branch_token.decode())
        forked = self.shard.persistence.history.fork_history_branch(
            base_branch, lca_item.event_id + 1
        )
        # items up to the LCA, with the BOUNDARY item appended when the
        # LCA falls mid-item (base [(2,v0),(10,v1)], LCA (5,v1): the
        # fork holds events 1-5, so its items must end at (5,v1) — not
        # (2,v0), which would make the rebuild replay only events 1-2
        # and silently lose 3-5. Reference
        # CopyVersionHistoryUntilLCAVersionHistoryItem.
        items = [
            it for it in base_vh.items
            if it.event_id <= lca_item.event_id
        ]
        if not items or items[-1].event_id < lca_item.event_id:
            items.append(
                VersionHistoryItem(lca_item.event_id, lca_item.version)
            )
        new_vh = VersionHistory(
            branch_token=forked.to_json().encode(), items=items
        )
        self._metrics.inc("replication_branches_forked")
        prior_current = local.current_index
        changed, new_index = local.add_version_history(new_vh)
        if changed:
            # add_version_history flips current when the fork's last
            # version is the max; the CONFLICT RESOLVER owns that
            # decision — without this restore, _apply_for_existing
            # would see branch_index == current_index and apply the
            # incoming batch onto the old branch's un-rebuilt state
            # (append-at-end keeps prior indices stable)
            local.current_index = prior_current
        return new_index

    # -- apply variants ------------------------------------------------

    def _apply_to_current(
        self, ctx, ms: MutableState, task: HistoryTaskV2,
        branch_vh: VersionHistory,
    ) -> None:
        branch = BranchToken.from_json(
            (branch_vh.branch_token or ms.execution_info.branch_token).decode()
        )
        self.shard.persistence.history.append_history_nodes(
            branch, task.events, transaction_id=self.shard.next_task_id()
        )
        sb = StateBuilder(ms, domain_resolver=self._resolve_domain)
        _, _, new_run_ms = sb.apply_events(
            task.domain_id, "replication", task.workflow_id, task.run_id,
            list(task.events),
            list(task.new_run_events) or None,
        )
        if branch_vh.branch_token:
            ms.execution_info.branch_token = branch_vh.branch_token

        new_snapshot = None
        if new_run_ms is not None and task.new_run_events:
            new_snapshot = self._stage_new_run(new_run_ms, task)

        snapshot = self._snapshot(ms, sb.transfer_tasks, sb.timer_tasks)
        self.shard.persistence.execution.update_workflow_execution(
            self.shard.shard_id, self.shard.range_id, ctx.condition,
            snapshot, new_snapshot=new_snapshot,
        )
        ctx._ms = ms
        ctx._condition = ms.next_event_id
        self._notify(sb)

    def _rebuild_and_apply(
        self, ctx, ms: MutableState, task: HistoryTaskV2, branch_index: int
    ) -> None:
        """Reference nDCConflictResolver: the incoming version beats the
        current branch → rebuild mutable state from the target branch,
        flip current, then apply the batch on top."""
        local = ms.version_histories
        target_vh = local.get_version_history(branch_index)
        req = RebuildRequest(
            domain_id=task.domain_id,
            workflow_id=task.workflow_id,
            run_id=task.run_id,
            branch_token=target_vh.branch_token,
            next_event_id=target_vh.last_item().event_id + 1,
            version_history_items=[
                (it.event_id, it.version) for it in target_vh.items
            ],
        )
        rebuilt, _, _ = self.rebuilder.rebuild(req)
        # carry over the full set of branches; flip current
        rebuilt.version_histories = local
        local.current_index = branch_index
        rebuilt.execution_info.run_id = task.run_id
        rebuilt.execution_info.workflow_id = task.workflow_id
        self._apply_to_current(ctx, rebuilt, task, target_vh)
        self._metrics.inc("replication_conflicts_resolved")

    def _backfill_branch(
        self, ctx, ms: MutableState, task: HistoryTaskV2, branch_index: int
    ) -> None:
        """Events belong to a stale branch: persist them + the version-
        history bookkeeping without touching workflow state."""
        local = ms.version_histories
        vh = local.get_version_history(branch_index)
        if all(
            vh.contains_item(VersionHistoryItem(e.event_id, e.version))
            for e in task.events
        ):
            # at-least-once re-fetch of an already-archived batch: the
            # bookkeeping would reject the replayed item ids, and a
            # second signal reapplication would mint divergent bytes —
            # the duplicate is dropped whole, like the current-branch
            # dedup above
            return
        branch = BranchToken.from_json(vh.branch_token.decode())
        self.shard.persistence.history.append_history_nodes(
            branch, task.events, transaction_id=self.shard.next_task_id()
        )
        for e in task.events:
            vh.add_or_update_item(e.event_id, e.version)
        snapshot = self._snapshot(ms, [], [])
        self.shard.persistence.execution.update_workflow_execution(
            self.shard.shard_id, self.shard.range_id, ctx.condition, snapshot,
        )
        ctx._ms = ms
        ctx._condition = ms.next_event_id
        # the losing side of a version conflict is resolved here: its
        # events are archived on the stale branch, the winner keeps
        # current — count it like the rebuild-win path does
        self._metrics.inc("replication_conflicts_resolved")
        # signals on the stale branch still matter to the live run
        if self._is_active_locally(task.domain_id):
            self._reapply_signals(ctx, ms, task.events)

    # -- snapshot shipping (bandwidth-adaptive state transfer) ---------

    def _snapshot_rebuilder(self):
        """(StateRebuilder, CheckpointManager) pair for installing
        snapshot-shipped checkpoints. The engine-wired checkpoint plane
        is reused when present (shipped rows land in the durable store
        and seed future rebuilds); otherwise a transient in-memory
        store, cached on this replicator, carries the install — the
        optimization works either way, only its persistence differs."""
        if self.rebuilder.checkpoints is not None:
            return self.rebuilder, self.rebuilder.checkpoints
        if self._transient_snapshots is None:
            from cadence_tpu.checkpoint import (
                CheckpointManager,
                CheckpointPolicy,
                MemoryCheckpointStore,
            )

            mgr = CheckpointManager(
                MemoryCheckpointStore(),
                CheckpointPolicy(every_events=1 << 30, keep_last=1),
            )
            self._transient_snapshots = (
                StateRebuilder(
                    self.shard.persistence.history,
                    domain_resolver=self._resolve_domain,
                    checkpoints=mgr,
                    metrics=self._raw_metrics,
                ),
                mgr,
            )
        return self._transient_snapshots

    def apply_state_snapshot(
        self, domain_id: str, workflow_id: str, run_id: str, ckpt,
    ) -> Optional[dict]:
        """Install a snapshot-shipped ``ReplayCheckpoint`` as the run's
        local state via the existing suffix-only resume path: the row
        is keyed to the LOCAL branch, put into the checkpoint store,
        and the standard rebuilder consults it — a tip hit rehydrates
        without replaying the covered prefix (``events_replayed_saved``
        counts it), exactly like a warm rebuild.

        Returns ``{"covered_through", "backfill_from"}`` on success —
        the caller owes a history backfill of that range (state is
        current; the history bytes arrive behind it) — or None when the
        snapshot cannot be applied (stale vs local state, divergent
        local branch, stale fingerprint/caps): the caller falls back to
        event shipping, the correctness baseline."""
        import dataclasses as _dc

        if not ckpt.vh_items or ckpt.event_id < 1:
            return None
        if self._fault_hook is not None:
            self._fault_hook("apply_state_snapshot", self.shard.shard_id)
        snap_tip = int(ckpt.event_id)
        snap_version = int(ckpt.vh_items[-1][1])
        from cadence_tpu.runtime.queues.effects import task_effect_scope

        witness = task_effect_scope("replication", "SnapshotReplication")
        ctx = self.cache.get_or_create(domain_id, workflow_id, run_id)
        with witness, ctx.lock:
            try:
                ms = ctx.load()
            except EntityNotExistsError:
                ms = None
            if ms is not None:
                local = ms.version_histories
                if local is None:
                    return None
                cur_vh = local.get_current_version_history()
                last = cur_vh.last_item()
                if last.event_id >= snap_tip:
                    return None  # local already at/past the snapshot
                incoming = VersionHistory(items=[
                    VersionHistoryItem(int(e), int(v))
                    for e, v in ckpt.vh_items
                ])
                try:
                    _, lca = local.find_lca_index_and_item(incoming)
                except VersionHistoryError:
                    return None
                if lca.event_id < last.event_id:
                    # local tip is off the snapshot's branch: that is a
                    # version conflict the event path must resolve
                    # (rebuild-at-LCA); fast-forwarding over it would
                    # orphan local events
                    return None
                branch_token = (
                    cur_vh.branch_token or ms.execution_info.branch_token
                )
                backfill_from = last.event_id + 1
            else:
                branch = self.shard.persistence.history.new_history_branch(
                    tree_id=run_id
                )
                branch_token = branch.to_json().encode()
                backfill_from = 1
            if isinstance(branch_token, str):
                branch_token = branch_token.encode()

            rb, mgr = self._snapshot_rebuilder()
            key = branch_token.decode()
            local_ckpt = _dc.replace(
                ckpt,
                branch_key=key,
                tree_id=BranchToken.from_json(key).tree_id,
                domain_id=domain_id,
                workflow_id=workflow_id,
                run_id=run_id,
            )
            try:
                mgr.store.put_checkpoint(local_ckpt)
                rebuilt, transfer, timer = rb.rebuild_many([RebuildRequest(
                    domain_id=domain_id,
                    workflow_id=workflow_id,
                    run_id=run_id,
                    branch_token=branch_token,
                    version_history_items=[
                        (int(e), int(v)) for e, v in ckpt.vh_items
                    ],
                )])[0]
            except Exception:
                return None
            if rebuilt is None or rebuilt.next_event_id - 1 < snap_tip:
                # the resume didn't stick (stale fingerprint, capacity
                # mismatch, degraded store): event shipping takes over
                return None
            rebuilt.execution_info.workflow_id = workflow_id
            rebuilt.execution_info.run_id = run_id
            rebuilt.execution_info.branch_token = branch_token
            if rebuilt.version_histories is not None:
                rebuilt.version_histories.get_current_version_history(
                ).branch_token = branch_token

            if ms is not None:
                snapshot = self._snapshot(rebuilt, transfer, timer)
                self.shard.persistence.execution.\
                    conflict_resolve_workflow_execution(
                        self.shard.shard_id, self.shard.range_id,
                        ctx.condition, snapshot,
                    )
            else:
                mode, prev_run_id = self._create_mode(
                    domain_id, workflow_id, snap_version
                )
                snapshot = self._snapshot(
                    rebuilt, transfer, timer,
                    zombie=(mode == CreateWorkflowMode.ZOMBIE),
                )
                self.shard.persistence.execution.create_workflow_execution(
                    self.shard.shard_id, self.shard.range_id, mode,
                    snapshot, prev_run_id=prev_run_id,
                )
                if mode == CreateWorkflowMode.SUPPRESS_CURRENT:
                    self.cache.evict(domain_id, workflow_id, prev_run_id)
            ctx._ms = rebuilt
            ctx._condition = rebuilt.next_event_id
        if snapshot.transfer_tasks:
            self._task_notifier()
        if snapshot.timer_tasks:
            self._timer_notifier()
        from cadence_tpu.core.enums import CloseStatus

        return {
            "covered_through": snap_tip,
            "backfill_from": backfill_from,
            # a snapshot-covered run that closed ContinuedAsNew has a
            # chain successor whose first batch rode the predecessor's
            # replication task — which this fast-forward bypassed. The
            # caller (rereplicator) must heal the successor explicitly
            # or the chain's new run never materializes locally.
            "continued_as_new": (
                rebuilt.execution_info.close_status
                == CloseStatus.ContinuedAsNew
            ),
        }

    def backfill_history(
        self, domain_id: str, workflow_id: str, run_id: str, batches,
    ) -> int:
        """Append raw remote event batches to the run's local branch
        WITHOUT touching workflow state — the history half of a
        snapshot-shipped catch-up (state jumped ahead via the snapshot;
        the covered prefix's bytes arrive behind it so the standby
        stays byte-identical). Idempotent: a node-id collision rewrites
        identical bytes under a fresh transaction id."""
        batches = [b for b in batches if b]
        if not batches:
            return 0
        from cadence_tpu.runtime.queues.effects import task_effect_scope

        witness = task_effect_scope("replication", "HistoryBackfill")
        ctx = self.cache.get_or_create(domain_id, workflow_id, run_id)
        with witness, ctx.lock:
            ms = ctx.load()
            branch = BranchToken.from_json(
                ms.execution_info.branch_token.decode()
            )
            applied = 0
            for b in batches:
                self.shard.persistence.history.append_history_nodes(
                    branch, list(b),
                    transaction_id=self.shard.next_task_id(),
                )
                applied += len(b)
            return applied

    # -- events reapplier (nDCEventsReapplier.go) ----------------------

    def _reapply_signals(
        self, ctx, ms: MutableState, events: List[HistoryEvent]
    ) -> None:
        signals = [
            e for e in events
            if e.event_type == EventType.WorkflowExecutionSignaled
        ]
        if not signals or not ms.is_workflow_execution_running():
            return
        from cadence_tpu.core.active_transaction import ActiveTransaction

        txn = ActiveTransaction(
            ms, ms.execution_info.domain_id, ms.execution_info.workflow_id,
            ms.execution_info.run_id, ms.current_version,
        )
        now = self.shard.now()
        for e in signals:
            a = e.attributes
            txn.add_workflow_execution_signaled(
                a.get("signal_name", ""), a.get("input", b""),
                a.get("identity", ""), now,
            )
        result = txn.close()
        repl = []
        if result.events:
            branch = BranchToken.from_json(
                ms.execution_info.branch_token.decode()
            )
            self.shard.persistence.history.append_history_nodes(
                branch, result.events,
                transaction_id=self.shard.next_task_id(),
            )
            # reapplication is an ACTIVE-side mint (this cluster owns
            # the domain): the reapplied events must ship to the peers
            # like any engine transaction, or the recovered region
            # completes the workflow without them and the clusters
            # diverge (the failover drill caught exactly this)
            if ms.version_histories is not None:
                from cadence_tpu.core.tasks import ReplicationTask

                repl = [ReplicationTask(
                    first_event_id=result.events[0].event_id,
                    next_event_id=result.events[-1].event_id + 1,
                    version=result.events[0].version,
                    branch_token=ms.execution_info.branch_token,
                )]
        # with a decision in flight the signals land in buffered_events;
        # they reach history when the decision completes
        snapshot = self._snapshot(
            ms, result.transfer_tasks, result.timer_tasks,
            replication=repl,
        )
        self.shard.persistence.execution.update_workflow_execution(
            self.shard.shard_id, self.shard.range_id, ctx.condition, snapshot,
        )
        ctx._condition = ms.next_event_id

    # -- persistence helpers -------------------------------------------

    def _snapshot(
        self, ms: MutableState, transfer, timer, zombie: bool = False,
        replication=(),
    ) -> WorkflowSnapshot:
        if zombie:
            # a ZOMBIE run is deliberately not current: enqueueing live
            # transfer/timer tasks for it would mint decisions/timers
            # for a suppressed run (reference nDCTransactionMgr zombie
            # writes carry no task generation)
            transfer, timer = [], []
        ei = ms.execution_info
        replication = list(replication)
        for t in list(transfer) + list(timer) + replication:
            if not t.domain_id:
                t.domain_id = ei.domain_id
            if not t.workflow_id:
                t.workflow_id = ei.workflow_id
            if not t.run_id:
                t.run_id = ei.run_id
        self.shard.assign_task_ids(transfer, timer, replication)
        return WorkflowSnapshot(
            domain_id=ei.domain_id,
            workflow_id=ei.workflow_id,
            run_id=ei.run_id,
            snapshot=ms.snapshot(),
            next_event_id=ms.next_event_id,
            last_write_version=ms.current_version,
            transfer_tasks=list(transfer),
            timer_tasks=list(timer),
            replication_tasks=replication,
        )

    def _stage_new_run(
        self, new_run_ms: MutableState, task: HistoryTaskV2
    ) -> WorkflowSnapshot:
        new_run_id = task.new_run_id or task.events[-1].attributes.get(
            "new_execution_run_id", ""
        )
        new_run_ms.execution_info.run_id = new_run_id
        history = self.shard.persistence.history
        branch = history.new_history_branch(tree_id=new_run_id)
        new_run_ms.execution_info.branch_token = branch.to_json().encode()
        if new_run_ms.version_histories is not None:
            new_run_ms.version_histories.get_current_version_history(
            ).branch_token = new_run_ms.execution_info.branch_token
        history.append_history_nodes(
            branch, task.new_run_events,
            transaction_id=self.shard.next_task_id(),
        )
        from cadence_tpu.core.task_refresher import refresh_tasks

        transfer, timer = refresh_tasks(new_run_ms)
        return self._snapshot(new_run_ms, transfer, timer)

    def _notify(self, sb: StateBuilder) -> None:
        if sb.transfer_tasks:
            self._task_notifier()
        if sb.timer_tasks:
            self._timer_notifier()
